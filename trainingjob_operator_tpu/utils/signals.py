"""Signal handling: first SIGINT/SIGTERM triggers a clean stop, the second
hard-exits.

Reference: pkg/signals/signal.go:29-43 (close stop channel, os.Exit(1) on the
second signal).
"""

from __future__ import annotations

import os
import signal
import threading


def setup_signal_handler() -> threading.Event:
    stop = threading.Event()
    state = {"hits": 0}

    def handler(signum, frame):
        state["hits"] += 1
        if state["hits"] >= 2:
            os._exit(1)
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    return stop
