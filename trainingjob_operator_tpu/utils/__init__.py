"""Process utilities: signals, events, leader election, logging.

Reference: pkg/signals/ plus the client-go record/leaderelection machinery the
cmd layer wires up (cmd/app/server.go:85-106,153-157).
"""
