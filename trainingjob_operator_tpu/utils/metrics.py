"""Operator observability: metrics registry + HTTP exposition.

The reference's only observability is leveled klog text and Status.Conditions
(SURVEY.md §5.5 -- no Prometheus endpoint, no pprof).  This module is the
improvement §5.1 asks for: per-reconcile latency histograms, queue depth,
restart/scale counters, a Prometheus text endpoint, and a thread-dump page
(the Python analogue of Go's /debug/pprof/goroutine).

Thread-safe; one process-global registry (``METRICS``) so the controller,
pod/service control, and runtimes all report into the same place.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Histogram bucket upper bounds (seconds) for latency-style metrics.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote, LF."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class _Histogram:
    __slots__ = ("buckets", "counts", "total", "count", "vmax")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +Inf bucket
        self.total = 0.0
        self.count = 0
        self.vmax = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.vmax = max(self.vmax, value)
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the bucket).

        q is clamped to (0, 1]: at q<=0 the old code computed target=0 and
        the first ``seen >= target`` test passed before any mass was seen,
        biasing the answer to the first bucket's upper bound even when the
        histogram held nothing there.
        """
        if self.count == 0 or q <= 0.0:
            return 0.0
        q = min(q, 1.0)
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts[:-1]):
            seen += c
            if seen >= target:
                return self.buckets[i]
        return self.vmax


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._hists: Dict[str, _Histogram] = {}
        self.started_at = time.time()

    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = _key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge(self, name: str, fn: Callable[[], float], **labels: str) -> None:
        """Register a pull-style gauge (evaluated at scrape time)."""
        with self._lock:
            self._gauges[_key(name, labels)] = fn

    def remove_gauge(self, name: str, **labels: str) -> None:
        """Deregister a gauge (component shutting down; its closure must not
        keep the component alive or shadow a newer instance)."""
        with self._lock:
            self._gauges.pop(_key(name, labels), None)

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                **labels: str) -> None:
        key = _key(name, labels)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = self._hists[key] = _Histogram(buckets)
            hist.observe(value)

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            gauges = {k: fn for k, fn in self._gauges.items()}
            counters = dict(self._counters)
            hists = {
                k: {"count": h.count, "sum": h.total, "max": h.vmax,
                    "p50": h.quantile(0.5), "p99": h.quantile(0.99)}
                for k, h in self._hists.items()
            }
        out: Dict[str, Any] = {"uptime_seconds": time.time() - self.started_at}
        out.update(counters)
        for k, fn in gauges.items():
            try:
                out[k] = fn()
            # analyzer: allow[broad-except]: gauge callbacks are arbitrary
            # component code; one bad gauge must not fail the whole scrape.
            except Exception:
                out[k] = None
        for k, stats in hists.items():
            for stat, v in stats.items():
                out[f"{k}_{stat}"] = v
        return out

    def typed_snapshot(self) -> Dict[str, Any]:
        """Type-separated snapshot for the time-series store (obs/tsdb.py):
        counters raw (the store deltaifies them), gauges evaluated,
        histogram stats materialized.  The flat ``snapshot()`` cannot tell
        a counter from a gauge, and deltaifying a gauge would be wrong."""
        with self._lock:
            counters = dict(self._counters)
            gauges = {k: fn for k, fn in self._gauges.items()}
            hists = {
                k: {"count": float(h.count), "sum": h.total, "max": h.vmax,
                    "p50": h.quantile(0.5), "p99": h.quantile(0.99)}
                for k, h in self._hists.items()
            }
        evaluated: Dict[str, float] = {}
        for k, fn in gauges.items():
            try:
                evaluated[k] = float(fn())
            # analyzer: allow[broad-except]: gauge callbacks are arbitrary
            # component code; one bad gauge must not fail the whole sweep.
            except Exception:
                continue
        return {"counters": counters, "gauges": evaluated, "hists": hists}

    def render_prometheus(self) -> str:
        lines: List[str] = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        for key, value in counters:
            lines.append(f"{key} {value}")
        for key, fn in gauges:
            try:
                lines.append(f"{key} {fn()}")
            # analyzer: allow[broad-except]: a failing gauge drops its own
            # line only; the exposition endpoint itself must stay up.
            except Exception:
                pass
        for key, h in hists:
            base, _, labels = key.partition("{")
            labels = ("{" + labels) if labels else ""

            def lbl(extra: str) -> str:
                if not labels:
                    return "{" + extra + "}"
                return labels[:-1] + "," + extra + "}"

            cum = 0
            for ub, c in zip(h.buckets, h.counts[:-1]):
                cum += c
                # Escaped label hoisted out of the f-string: a backslash
                # inside an f-string expression is a SyntaxError before 3.12.
                le_label = f'le="{ub}"'
                lines.append(f"{base}_bucket{lbl(le_label)} {cum}")
            inf_label = 'le="+Inf"'
            lines.append(f"{base}_bucket{lbl(inf_label)} {h.count}")
            lines.append(f"{base}_sum{labels} {h.total}")
            lines.append(f"{base}_count{labels} {h.count}")
        return "\n".join(lines) + "\n"


#: Process-global registry.
METRICS = MetricsRegistry()


def thread_dump() -> str:
    """All live threads with stacks -- Go's /debug/pprof/goroutine analogue."""
    import sys

    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in frames.items():
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        parts.append("".join(traceback.format_stack(frame)))
    return "\n".join(parts)


def _render_traces(tracer, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/traces: JSON trace list by
    default, Chrome trace_event JSON with ?format=chrome (Perfetto-loadable).
    A non-numeric ?limit or unknown ?format -> explicit 400: silently
    ignoring a typo'd knob serves the wrong answer with a 200 on it."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "chrome"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or chrome\n"
    limit_raw = params.get("limit", [""])[0]
    if limit_raw and not limit_raw.isdigit():
        return 400, "text/plain", f"bad limit {limit_raw!r}; use a non-negative integer\n"
    limit = int(limit_raw) if limit_raw else None
    traces = tracer.traces(limit)
    if fmt == "chrome":
        return 200, "application/json", tracer.export_chrome(traces)
    return 200, "application/json", json.dumps(
        {"count": len(traces), "traces": traces}, indent=2)


def _render_events(events_fn, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/events: the durable event
    store, newest last, filterable with ?job=<namespace/name> (or bare
    name) on the involved object.  Unknown ?format -> explicit 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json\n"
    events = list(events_fn())
    job = params.get("job", [""])[0]
    if job:
        def matches(ev) -> bool:
            return (f"{ev.involved_namespace}/{ev.involved_name}" == job
                    or ev.involved_name == job)
        events = [ev for ev in events if matches(ev)]
    events.sort(key=lambda ev: ev.timestamp or 0.0)
    return 200, "application/json", json.dumps(
        {"count": len(events),
         "events": [ev.to_dict() for ev in events]}, indent=2)


def _render_steps(telemetry, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/steps: per-replica live step
    table for ?job=<namespace/name> (text with ?format=text), or the list of
    jobs with telemetry when no job is given.  Unknown job -> 404; unknown
    ?format -> explicit 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "text"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or text\n"
    job = params.get("job", [""])[0]
    if not job:
        jobs = telemetry.jobs()
        return 200, "application/json", json.dumps(
            {"count": len(jobs), "jobs": jobs}, indent=2)
    table = telemetry.job_table(job)
    if table is None:
        return 404, "text/plain", ""
    if fmt == "text":
        return 200, "text/plain", telemetry.render_table(job)
    return 200, "application/json", json.dumps(table, indent=2)


def _render_serve(telemetry, params: Dict[str, List[str]],
                  reqtrace=None) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/serve: the latest
    serving-plane snapshot (queue depth, batch occupancy, token-latency
    percentiles, tokens/s) for ?job=<namespace/name>, or the list of jobs
    that have ever served when no job is given.  With the request plane
    wired the snapshot gains TTFT/TPOT percentile columns -- None (JSON)
    or ``-`` (text) for a job the ledger has never seen, never a fake
    zero.  Unknown / never-served job -> 404; unknown ?format -> 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "text"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or text\n"
    job = params.get("job", [""])[0]
    if not job:
        jobs = [j for j in telemetry.jobs()
                if telemetry.serve_stats(j) is not None]
        return 200, "application/json", json.dumps(
            {"count": len(jobs), "jobs": jobs}, indent=2)
    snap = telemetry.serve_stats(job)
    if snap is None:
        return 404, "text/plain", ""
    slots = snap.get("slots") or 0.0
    snap["occupancy"] = (round(snap.get("active_slots", 0.0) / slots, 3)
                         if slots else 0.0)
    ttft = reqtrace.ttft_percentiles(job) if reqtrace is not None else None
    tpot = reqtrace.tpot_percentiles(job) if reqtrace is not None else None
    snap["ttft_ms_p50"] = round(ttft[0], 3) if ttft else None
    snap["ttft_ms_p99"] = round(ttft[1], 3) if ttft else None
    snap["tpot_ms_p50"] = round(tpot[0], 3) if tpot else None
    snap["tpot_ms_p99"] = round(tpot[1], 3) if tpot else None
    if fmt == "text":
        width = max(len(k) for k in snap)
        lines = [f"serve: {job}"]
        for k in sorted(snap):
            v = snap[k]
            lines.append(f"  {k:<{width}}  {'-' if v is None else v}")
        return 200, "text/plain", "\n".join(lines) + "\n"
    return 200, "application/json", json.dumps(
        {"job": job, "serve": snap}, indent=2)


def _render_incidents(incidents,
                      params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/incidents: the per-job list
    of retained incident bundles.  No ?job= -> job summary list; with one,
    the bundles (?format=chrome -> the newest bundle -- or ?id=N -- as
    Chrome trace_event JSON).  Unknown job -> 404; a ?format other than
    json/chrome -> explicit 400, the caller typo'd the one knob the
    endpoint has."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "chrome"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or chrome\n"
    job = params.get("job", [""])[0]
    if not job:
        jobs = incidents.jobs()
        return 200, "application/json", json.dumps(
            {"count": len(jobs), "jobs": jobs}, indent=2)
    bundles = incidents.bundles(job)
    if bundles is None:
        return 404, "text/plain", ""
    id_raw = params.get("id", [""])[0]
    incident_id = int(id_raw) if id_raw.isdigit() else None
    if fmt == "chrome":
        body = incidents.export_chrome(job, incident_id)
        if body is None:
            return 404, "text/plain", ""
        return 200, "application/json", body
    if incident_id is not None:
        body = incidents.bundle_json(job, incident_id)
        if body is None:
            return 404, "text/plain", ""
        return 200, "application/json", body
    return 200, "application/json", json.dumps(
        {"job": job, "count": len(bundles),
         "open": incidents.open_incident(job),
         "incidents": bundles}, indent=2)


def _render_requests(reqtrace,
                     params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/requests: the request
    lifecycle ledger (obs/reqtrace.py).  No ?job= -> fleet summary; with
    one, the job summary plus its retained spans.  ?id=<ledger seq> ->
    that span (?format=chrome -> Perfetto/chrome://tracing trace_event
    JSON; without ?id= chrome exports the newest retained span).  Unknown
    job or sampled-away id -> 404; a non-integer ?id= or unknown ?format
    -> explicit 400 -- a typo'd knob must not get a 200 with the wrong
    answer on it."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "chrome"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or chrome\n"
    id_raw = params.get("id", [""])[0]
    if id_raw and not id_raw.isdigit():
        return (400, "text/plain",
                f"bad id {id_raw!r}; use the integer seq from the job listing\n")
    job = params.get("job", [""])[0]
    if not job:
        return 200, "application/json", json.dumps(reqtrace.summary(),
                                                   indent=2)
    spans = reqtrace.retained_list(job)
    if spans is None:
        return 404, "text/plain", ""
    if id_raw:
        seq = int(id_raw)
        if fmt == "chrome":
            trace = reqtrace.export_chrome(job, seq)
            if trace is None:
                return 404, "text/plain", ""
            return 200, "application/json", json.dumps(trace, indent=2)
        rec = reqtrace.request(job, seq)
        if rec is None:
            return 404, "text/plain", ""
        return 200, "application/json", json.dumps(
            {"job": job, "seq": seq, "request": rec}, indent=2)
    if fmt == "chrome":
        if not spans:
            return 404, "text/plain", ""
        trace = reqtrace.export_chrome(job, spans[-1]["seq"])
        if trace is None:
            return 404, "text/plain", ""
        return 200, "application/json", json.dumps(trace, indent=2)
    return 200, "application/json", json.dumps(
        {"job": job, "summary": reqtrace.job_summary(job),
         "retained": spans}, indent=2)


def _render_timeseries(tsdb, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/timeseries: the in-process
    tsdb (obs/tsdb.py).  No ?series= -> the store summary (every ring with
    its last value); with one, that ring's points.  ?format=sparkline ->
    a text view, one scaled unicode sparkline per ring.  Unknown series ->
    404; unknown ?format -> explicit 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "sparkline"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or sparkline\n"
    name = params.get("series", [""])[0]
    if name:
        points = tsdb.series(name)
        if points is None:
            return 404, "text/plain", ""
        if fmt == "sparkline":
            return 200, "text/plain", tsdb.render_sparklines([name])
        return 200, "application/json", json.dumps(
            {"series": name, "interval_s": tsdb.interval,
             "points": [[round(t, 3), v] for t, v in points]}, indent=2)
    if fmt == "sparkline":
        return 200, "text/plain", tsdb.render_sparklines()
    return 200, "application/json", json.dumps(tsdb.summary(), indent=2)


def _render_slo(slos, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/slo: the burn-rate engine's
    current verdicts (obs/slo.py) -- per-objective burn rates, breach
    state and counters.  Unknown ?format -> explicit 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json\n"
    return 200, "application/json", json.dumps(slos.verdicts(), indent=2)


def _render_profile(profiler, params: Dict[str, List[str]]) -> Tuple[int, str, str]:
    """(status, content-type, body) for /debug/profile: the sampling span
    profiler (obs/profiler.py) -- per-span-stack CPU% table and overhead
    by default, flamegraph-ready collapsed stacks with ?format=collapsed.
    Unknown ?format -> explicit 400."""
    fmt = params.get("format", [""])[0]
    if fmt not in ("", "json", "collapsed"):
        return 400, "text/plain", f"unknown format {fmt!r}; use json or collapsed\n"
    if fmt == "collapsed":
        return 200, "text/plain", profiler.collapsed()
    return 200, "application/json", json.dumps(profiler.report(), indent=2)


def serve_metrics(port: int, registry: Optional[MetricsRegistry] = None,
                  host: str = "127.0.0.1", tracer=None, events_fn=None,
                  ready_fn: Optional[Callable[[], bool]] = None,
                  telemetry=None, incidents=None, tsdb=None, slos=None,
                  profiler=None, reqtrace=None):
    """Serve /metrics (Prometheus text), /metrics.json, /healthz, /readyz,
    /debug (route index), /debug/threads, /debug/traces, /debug/events,
    /debug/steps, /debug/serve, /debug/incidents, /debug/requests,
    /debug/timeseries, /debug/slo and /debug/profile on a daemon thread;
    ``.shutdown()`` stops it and closes the socket.

    - ``tracer``: an obs.trace.Tracer; enables /debug/traces (404 without).
    - ``events_fn``: zero-arg callable returning Event objects (e.g.
      ``lambda: clientset.events.list(None)``); enables /debug/events.
    - ``ready_fn``: informer-synced gate for /readyz -- 503 until it returns
      truthy.  Omitted -> always ready (no controller to wait for).
    - ``telemetry``: an obs.telemetry.TelemetryAggregator; enables
      /debug/steps and /debug/serve (404 without).
    - ``incidents``: an obs.incident.IncidentRecorder; enables
      /debug/incidents (404 without).
    - ``reqtrace``: an obs.reqtrace.RequestLedger; enables /debug/requests
      and the TTFT/TPOT columns on /debug/serve (404 / None without).
    - ``tsdb``: an obs.tsdb.TimeSeriesStore; enables /debug/timeseries.
    - ``slos``: an obs.slo.SLOEngine; enables /debug/slo.
    - ``profiler``: an obs.profiler.SpanProfiler; enables /debug/profile.

    ``/debug`` itself serves an index of every debug route with a one-line
    description and whether its provider is wired -- endpoint discovery
    without reading docs/OBSERVABILITY.md.

    Binds loopback by default -- /debug/threads exposes live stacks, the
    pprof convention (expose beyond localhost only deliberately via
    ``host="0.0.0.0"``).  Threaded with per-connection timeouts so one stuck
    client can neither block other scrapes nor hang operator shutdown.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs

    reg = registry or METRICS

    # The /debug index: (path, one-line description, provider wired?).
    # Built once per server so the index always reflects what *this*
    # process can actually serve, not the theoretical full set.
    routes = (
        ("/metrics", "Prometheus text exposition", True),
        ("/metrics.json", "flat registry snapshot as JSON", True),
        ("/healthz", "liveness", True),
        ("/readyz", "readiness (503 until informers sync)", ready_fn is not None),
        ("/debug", "this index", True),
        ("/debug/threads", "all live thread stacks (pprof/goroutine analogue)", True),
        ("/debug/traces", "finished traces; ?limit=N, ?format=chrome",
         tracer is not None),
        ("/debug/events", "durable event store; ?job=<ns/name>",
         events_fn is not None),
        ("/debug/steps", "per-replica live step table; ?job=, ?format=text",
         telemetry is not None),
        ("/debug/serve", "serving-plane snapshot; ?job=",
         telemetry is not None),
        ("/debug/incidents", "incident bundles; ?job=, ?id=N, ?format=chrome",
         incidents is not None),
        ("/debug/requests", "request lifecycle ledger; ?job=, ?id=N, ?format=chrome",
         reqtrace is not None),
        ("/debug/timeseries", "in-process tsdb rings; ?series=, ?format=sparkline",
         tsdb is not None),
        ("/debug/slo", "SLO burn rates + breach verdicts",
         slos is not None),
        ("/debug/profile", "sampling span profiler; ?format=collapsed",
         profiler is not None),
    )

    class Handler(BaseHTTPRequestHandler):
        timeout = 5  # settimeout on the connection: drop stuck clients

        def do_GET(self):  # noqa: N802 (stdlib API)
            path, _, query = self.path.partition("?")
            params = parse_qs(query)
            status, ctype, body = 200, "text/plain", None
            if path == "/metrics":
                ctype, body = "text/plain; version=0.0.4", reg.render_prometheus()
            elif path == "/metrics.json":
                ctype, body = "application/json", json.dumps(reg.snapshot(),
                                                            indent=2)
            elif path == "/healthz":
                body = "ok\n"
            elif path == "/readyz":
                if ready_fn is None or ready_fn():
                    body = "ok\n"
                else:
                    status, body = 503, "not ready\n"
            elif path == "/debug":
                ctype, body = "application/json", json.dumps(
                    {"count": len(routes),
                     "routes": [{"path": p, "description": d, "enabled": e}
                                for p, d, e in routes]}, indent=2)
            elif path == "/debug/threads":
                body = thread_dump()
            elif path == "/debug/traces" and tracer is not None:
                status, ctype, body = _render_traces(tracer, params)
            elif path == "/debug/events" and events_fn is not None:
                status, ctype, body = _render_events(events_fn, params)
            elif path == "/debug/steps" and telemetry is not None:
                status, ctype, body = _render_steps(telemetry, params)
                if status == 404:
                    body = None
            elif path == "/debug/serve" and telemetry is not None:
                status, ctype, body = _render_serve(telemetry, params,
                                                    reqtrace)
                if status == 404:
                    body = None
            elif path == "/debug/incidents" and incidents is not None:
                status, ctype, body = _render_incidents(incidents, params)
                if status == 404:
                    body = None
            elif path == "/debug/requests" and reqtrace is not None:
                status, ctype, body = _render_requests(reqtrace, params)
                if status == 404:
                    body = None
            elif path == "/debug/timeseries" and tsdb is not None:
                status, ctype, body = _render_timeseries(tsdb, params)
                if status == 404:
                    body = None
            elif path == "/debug/slo" and slos is not None:
                status, ctype, body = _render_slo(slos, params)
            elif path == "/debug/profile" and profiler is not None:
                status, ctype, body = _render_profile(profiler, params)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            raw = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def log_message(self, *args):  # quiet
            pass

    class _Server(ThreadingHTTPServer):
        daemon_threads = True

        def shutdown(self):
            super().shutdown()
            self.server_close()

    server = _Server((host, port), Handler)
    th = threading.Thread(target=server.serve_forever, daemon=True,
                          name="metrics-http")
    th.start()
    return server
