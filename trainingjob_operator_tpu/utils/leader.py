"""Leader election over a lock file.

Reference: the operator leader-elects before running so only one instance
reconciles (cmd/app/server.go:85-106, endpoints lock, lease 15 s / renew 5 s /
retry 3 s).  Locally the resource is an ``fcntl`` file lock: the OS releases
it when the holder dies, giving crash-failover without a heartbeat protocol;
the lease/renew knobs shape the retry cadence.  On a real cluster the kube
backend would use a Lease object instead.
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading
import time
from typing import Callable, Optional

from trainingjob_operator_tpu.cmd.options import LeaderElectionConfig

log = logging.getLogger("trainingjob.leader")


class LeaderElector:
    def __init__(self, config: LeaderElectionConfig, identity: str = ""):
        self._config = config
        self.identity = identity or f"{os.uname().nodename}-{os.getpid()}"
        self._lock_path = config.lock_path or "/tmp/tpu-trainingjob-leader.lock"
        self._fd: Optional[int] = None
        self._stop = threading.Event()

    def run(self, on_started_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        """Block until leadership is acquired, then invoke the callback
        (reference: leaderelection.RunOrDie -> OnStartedLeading)."""
        retry = max(self._config.retry_period, 0.1)
        while not self._stop.is_set() and (stop is None or not stop.is_set()):
            if self._try_acquire():
                log.info("%s became leader (%s)", self.identity, self._lock_path)
                self._write_identity()
                try:
                    on_started_leading()
                finally:
                    self.release()
                return
            time.sleep(retry)

    def _try_acquire(self) -> bool:
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def _write_identity(self) -> None:
        if self._fd is not None:
            os.ftruncate(self._fd, 0)
            os.write(self._fd, f"{self.identity} {time.time()}\n".encode())

    def is_leader(self) -> bool:
        return self._fd is not None

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def stop(self) -> None:
        self._stop.set()


class KubeLeaderElector:
    """Cluster-wide leader election over a coordination.k8s.io/v1 Lease.

    Reference: leaderelection.RunOrDie over an endpoints lock in kube-system
    (cmd/app/server.go:85-106), modernized to the Lease resource (the
    endpoints lock is deprecated upstream).  Semantics: the holder renews
    every ``retry_period``; a candidate takes over when
    ``renewTime + lease_duration`` has passed; optimistic-concurrency
    conflicts mean someone else moved first -- back off and re-observe.
    """

    LEASE_PREFIX = "/apis/coordination.k8s.io/v1"

    def __init__(self, rest: "object", config: LeaderElectionConfig,
                 identity: str = "", namespace: str = "kube-system",
                 name: str = "tpu-trainingjob-operator"):
        self._rest = rest
        self._config = config
        self.identity = identity or f"{os.uname().nodename}-{os.getpid()}"
        self._path = (f"{self.LEASE_PREFIX}/namespaces/{namespace}"
                      f"/leases/{name}")
        self._create_path = f"{self.LEASE_PREFIX}/namespaces/{namespace}/leases"
        self._name = name
        self._namespace = namespace
        self._stop = threading.Event()
        self.lost = threading.Event()
        self._renewer: "Optional[threading.Thread]" = None
        self._on_lost = None

    # -- lease object plumbing ----------------------------------------------

    def _lease_body(self, lease: Optional[dict], transitions: int) -> dict:
        now = time.time()
        spec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self._config.lease_duration),
            "renewTime": _micro_ts(now),
            "leaseTransitions": transitions,
        }
        if lease is None or (lease.get("spec") or {}).get(
                "holderIdentity") != self.identity:
            spec["acquireTime"] = _micro_ts(now)
        else:
            spec["acquireTime"] = (lease.get("spec") or {}).get(
                "acquireTime", _micro_ts(now))
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self._name, "namespace": self._namespace},
            "spec": spec,
        }
        if lease is not None:
            body["metadata"]["resourceVersion"] = (
                lease.get("metadata") or {}).get("resourceVersion", "")
        return body

    def _try_acquire_or_renew(self) -> bool:
        from trainingjob_operator_tpu.client.rest import ApiError
        from trainingjob_operator_tpu.client.tracker import (
            AlreadyExistsError,
            ConflictError,
            NotFoundError,
        )

        try:
            try:
                lease = self._rest.request("GET", self._path)
            except NotFoundError:
                self._rest.request("POST", self._create_path,
                                   body=self._lease_body(None, 0))
                log.info("%s acquired new lease %s", self.identity, self._name)
                return True
            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity", "")
            if holder and holder != self.identity:
                renew = _parse_micro_ts(spec.get("renewTime"))
                duration = float(spec.get("leaseDurationSeconds")
                                 or self._config.lease_duration)
                if renew is not None and time.time() - renew < duration:
                    return False  # current holder is alive
                log.info("%s taking over expired lease from %s",
                         self.identity, holder)
            transitions = int(spec.get("leaseTransitions") or 0)
            if holder != self.identity:
                transitions += 1
            self._rest.request("PUT", self._path,
                               body=self._lease_body(lease, transitions))
            return True
        except (ConflictError, AlreadyExistsError):
            return False  # raced another candidate; re-observe next period
        except ApiError as exc:
            log.warning("lease %s: apiserver error %s", self._name, exc)
            return False
        except Exception as exc:
            # Transport failure (ConnectionError / SSLError / timeout /
            # OSError from the socket layer).  MUST be a failed-renew, not an
            # unhandled exception: letting it propagate kills _renew_loop
            # without setting ``lost`` or firing on_lost, so a deposed leader
            # would keep reconciling while a candidate takes the lease
            # (split-brain; client-go treats any renew error uniformly).
            log.warning("lease %s: transport error %s: %s", self._name,
                        type(exc).__name__, exc)
            return False

    # -- run loop ------------------------------------------------------------

    def run(self, on_started_leading, stop: Optional[threading.Event] = None,
            on_lost=None) -> None:
        """Block until the lease is held, then renew in the background while
        invoking the callback (leaderelection.RunOrDie -> OnStartedLeading).

        On renewal failing past the renew deadline, ``lost`` is set and
        ``on_lost`` fires (OnStoppedLeading) -- wire it to the process stop
        event so a deposed leader halts reconciling instead of running split-
        brain against the new leader.
        """
        self._on_lost = on_lost
        retry = max(self._config.retry_period, 0.1)
        while not self._stop.is_set() and (stop is None or not stop.is_set()):
            if self._try_acquire_or_renew():
                self._renewer = threading.Thread(
                    target=self._renew_loop, daemon=True, name="lease-renew")
                self._renewer.start()
                try:
                    on_started_leading()
                finally:
                    self.release()
                return
            self._stop.wait(retry)

    def _renew_loop(self) -> None:
        # Self-demotion after renew_deadline, NOT lease_duration: the old
        # leader must consider itself deposed strictly BEFORE a candidate may
        # take the lease at renewTime + lease_duration (client-go semantics;
        # the gap absorbs clock skew and a late last renew attempt).
        retry = max(self._config.retry_period, 0.1)
        last_renewed = time.time()
        while not self._stop.wait(retry):
            if self._try_acquire_or_renew():
                last_renewed = time.time()
            elif time.time() - last_renewed > self._config.renew_deadline:
                log.error("%s lost lease %s (renewal failed past the renew "
                          "deadline)", self.identity, self._name)
                self.lost.set()
                if self._on_lost is not None:
                    self._on_lost()
                return

    def is_leader(self) -> bool:
        return self._renewer is not None and not self.lost.is_set()

    def release(self) -> None:
        """Stop renewing and clear the holder so a successor acquires
        immediately rather than waiting out the lease."""
        self._stop.set()
        from trainingjob_operator_tpu.client.tracker import NotFoundError

        try:
            lease = self._rest.request("GET", self._path)
            if (lease.get("spec") or {}).get("holderIdentity") == self.identity:
                lease["spec"]["holderIdentity"] = ""
                self._rest.request("PUT", self._path, body=lease)
        # analyzer: allow[broad-except]: NotFound/conflict/connection
        # loss -- release is best effort; the lease expires anyway.
        except Exception:
            pass

    def stop(self) -> None:
        self._stop.set()
        th = self._renewer
        # The renew loop itself may end up here via on_lost: never
        # self-join.
        if th is not None and th is not threading.current_thread():
            th.join(timeout=2.0)


def _micro_ts(ts: float) -> str:
    """RFC3339 with microseconds (the Lease renewTime format)."""
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _parse_micro_ts(s: Optional[str]) -> Optional[float]:
    from trainingjob_operator_tpu.core.objects import from_iso

    if not s:
        return None
    try:
        return from_iso(s)
    except ValueError:
        return None
