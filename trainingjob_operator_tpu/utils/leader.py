"""Leader election over a lock file.

Reference: the operator leader-elects before running so only one instance
reconciles (cmd/app/server.go:85-106, endpoints lock, lease 15 s / renew 5 s /
retry 3 s).  Locally the resource is an ``fcntl`` file lock: the OS releases
it when the holder dies, giving crash-failover without a heartbeat protocol;
the lease/renew knobs shape the retry cadence.  On a real cluster the kube
backend would use a Lease object instead.
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading
import time
from typing import Callable, Optional

from trainingjob_operator_tpu.cmd.options import LeaderElectionConfig

log = logging.getLogger("trainingjob.leader")


class LeaderElector:
    def __init__(self, config: LeaderElectionConfig, identity: str = ""):
        self._config = config
        self.identity = identity or f"{os.uname().nodename}-{os.getpid()}"
        self._lock_path = config.lock_path or "/tmp/tpu-trainingjob-leader.lock"
        self._fd: Optional[int] = None
        self._stop = threading.Event()

    def run(self, on_started_leading: Callable[[], None],
            stop: Optional[threading.Event] = None) -> None:
        """Block until leadership is acquired, then invoke the callback
        (reference: leaderelection.RunOrDie -> OnStartedLeading)."""
        retry = max(self._config.retry_period, 0.1)
        while not self._stop.is_set() and (stop is None or not stop.is_set()):
            if self._try_acquire():
                log.info("%s became leader (%s)", self.identity, self._lock_path)
                self._write_identity()
                try:
                    on_started_leading()
                finally:
                    self.release()
                return
            time.sleep(retry)

    def _try_acquire(self) -> bool:
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def _write_identity(self) -> None:
        if self._fd is not None:
            os.ftruncate(self._fd, 0)
            os.write(self._fd, f"{self.identity} {time.time()}\n".encode())

    def is_leader(self) -> bool:
        return self._fd is not None

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def stop(self) -> None:
        self._stop.set()
