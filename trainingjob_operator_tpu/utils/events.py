"""Event recorder: durable, queryable action trail.

Reference: the client-go event broadcaster/recorder wired in
NewTrainingJobController (controller.go:88-102) so create/delete actions
surface in ``kubectl describe`` (README.md:17).  Events are stored as first-
class objects through the clientset, so tests and the CLI can assert on them.
"""

from __future__ import annotations

import itertools
import logging
from collections import deque
from typing import Any

from trainingjob_operator_tpu.core.objects import Event, ObjectMeta, new_uid, now

log = logging.getLogger("trainingjob.events")

_seq = itertools.count()


class EventRecorder:
    NORMAL = "Normal"
    WARNING = "Warning"

    #: Retention cap: oldest events are pruned past this (k8s expires events
    #: after ~1 h; a crash-looping job must not grow the store unboundedly).
    MAX_EVENTS = 2000

    def __init__(self, clientset: Any, component: str):
        self._cs = clientset
        self._component = component
        self._created: "deque[tuple[str, str]]" = deque()

    def set_sink(self, sink: Any) -> None:
        """``sink(obj, reason, message)`` observes every recorded event
        (the incident flight recorder taps the stream here).  Attribute-
        based so a ``NullRecorder`` -- whose ``__init__`` is empty and whose
        ``event`` never fires -- stays safe."""
        self._sink = sink

    def event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        sink = getattr(self, "_sink", None)
        if sink is not None:
            try:
                sink(obj, reason, message)
            # analyzer: allow[broad-except]: the tap is observability; the
            # event itself must still be recorded.
            except Exception:
                log.exception("event sink failed")
        meta = obj.metadata
        ev = Event(
            metadata=ObjectMeta(
                # Unique across operator restarts: on a persistent backend a
                # process-local counter would collide with a previous run's
                # events (409) and drop them; the uid suffix never collides,
                # the counter keeps same-moment events ordered in listings.
                name=f"{meta.name}.{next(_seq):06d}.{new_uid()[:8]}",
                namespace=meta.namespace or "default",
            ),
            involved_kind=obj.KIND,
            involved_name=meta.name,
            involved_namespace=meta.namespace,
            type=etype,
            reason=reason,
            message=message,
            source=self._component,
            timestamp=now(),
        )
        log.log(logging.WARNING if etype == self.WARNING else logging.INFO,
                "%s %s %s/%s: %s", etype, reason, meta.namespace, meta.name, message)
        try:
            self._cs.events.create(ev)
            self._created.append((ev.namespace, ev.name))
            while len(self._created) > self.MAX_EVENTS:
                old_ns, old_name = self._created.popleft()
                try:
                    self._cs.events.delete(old_ns, old_name)
                except KeyError:
                    pass
        except Exception:  # events are best-effort, never fail the caller
            log.exception("failed to record event")


class NullRecorder(EventRecorder):
    def __init__(self):
        pass

    def event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        pass
