"""Event recorder: durable, queryable action trail.

Reference: the client-go event broadcaster/recorder wired in
NewTrainingJobController (controller.go:88-102) so create/delete actions
surface in ``kubectl describe`` (README.md:17).  Events are stored as first-
class objects through the clientset, so tests and the CLI can assert on them.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Tuple

from trainingjob_operator_tpu.core.objects import Event, ObjectMeta, new_uid, now

log = logging.getLogger("trainingjob.events")


class EventSeq:
    """Process-wide event sequencer: lock-guarded ``(epoch, shard, seq)``.

    Replaces the bare ``itertools.count()`` module global -- the
    registry's last ``shard_hostile`` entry.  The tuple key is unique and
    totally ordered: ``epoch`` distinguishes operator incarnations
    (default 0; a deployment that persists events across restarts passes
    its restart counter -- wall clock would break same-seed digest
    determinism), ``shard`` distinguishes shards in a sharded
    deployment, ``seq`` is the in-process counter, all advanced and read
    under one lock.  ``next_suffix()`` renders the key fixed-width so
    lexicographic name order equals allocation order in listings.
    """

    def __init__(self, epoch: int = 0, shard: int = 0):
        self._lock = threading.Lock()
        self._epoch = int(epoch)
        self._shard = int(shard)
        self._seq = 0

    def configure(self, *, epoch: "int | None" = None,
                  shard: "int | None" = None) -> None:
        """Set the incarnation/shard coordinates (sharded deployments
        call this once at startup, before recording)."""
        with self._lock:
            if epoch is not None:
                self._epoch = int(epoch)
            if shard is not None:
                self._shard = int(shard)

    def next_key(self) -> Tuple[int, int, int]:
        with self._lock:
            key = (self._epoch, self._shard, self._seq)
            self._seq += 1
            return key

    def next_suffix(self) -> str:
        epoch, shard, seq = self.next_key()
        return f"{epoch:03d}-{shard:02d}-{seq:06d}"


#: Module singleton (SHARD_STATE_REGISTRY: lock_guarded_shared).
EVENT_SEQ = EventSeq()


class EventRecorder:
    NORMAL = "Normal"
    WARNING = "Warning"

    #: Retention cap: oldest events are pruned past this (k8s expires events
    #: after ~1 h; a crash-looping job must not grow the store unboundedly).
    MAX_EVENTS = 2000

    def __init__(self, clientset: Any, component: str):
        self._cs = clientset
        self._component = component
        self._created: "deque[tuple[str, str]]" = deque()
        # Guards the retention ledger: every controller worker records
        # through one shared recorder, and the len-check/popleft prune is
        # a check-then-act sequence.
        self._created_lock = threading.Lock()

    def set_sink(self, sink: Any) -> None:
        """``sink(obj, reason, message)`` observes every recorded event
        (the incident flight recorder taps the stream here).  Attribute-
        based so a ``NullRecorder`` -- whose ``__init__`` is empty and whose
        ``event`` never fires -- stays safe."""
        self._sink = sink

    def event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        sink = getattr(self, "_sink", None)
        if sink is not None:
            try:
                sink(obj, reason, message)
            # analyzer: allow[broad-except]: the tap is observability; the
            # event itself must still be recorded.
            except Exception:
                log.exception("event sink failed")
        meta = obj.metadata
        ev = Event(
            metadata=ObjectMeta(
                # Unique across operator restarts: on a persistent backend a
                # process-local counter would collide with a previous run's
                # events (409) and drop them; the uid suffix never collides,
                # the (epoch, shard, seq) suffix keeps same-moment events
                # ordered in listings and distinct across shards.
                name=f"{meta.name}.{EVENT_SEQ.next_suffix()}.{new_uid()[:8]}",
                namespace=meta.namespace or "default",
            ),
            involved_kind=obj.KIND,
            involved_name=meta.name,
            involved_namespace=meta.namespace,
            type=etype,
            reason=reason,
            message=message,
            source=self._component,
            timestamp=now(),
        )
        log.log(logging.WARNING if etype == self.WARNING else logging.INFO,
                "%s %s %s/%s: %s", etype, reason, meta.namespace, meta.name, message)
        try:
            self._cs.events.create(ev)
            with self._created_lock:
                self._created.append((ev.namespace, ev.name))
                expired = []
                while len(self._created) > self.MAX_EVENTS:
                    expired.append(self._created.popleft())
            for old_ns, old_name in expired:
                try:
                    self._cs.events.delete(old_ns, old_name)
                except KeyError:
                    pass
        except Exception:  # events are best-effort, never fail the caller
            log.exception("failed to record event")


class NullRecorder(EventRecorder):
    def __init__(self):
        pass

    def event(self, obj: Any, etype: str, reason: str, message: str) -> None:
        pass
