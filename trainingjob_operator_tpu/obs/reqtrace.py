"""Request-lifecycle plane: per-request spans + dropped-request audit.

Job- and replica-granular planes (telemetry, incidents, the SLO engine)
cannot answer the router-tier gate question "did any in-flight request
silently die during that drain/restart?".  This module is the
request-granular ledger that makes the question answerable:

- every serving request carries a **monotonically-ordered id** within a
  ``(job, epoch)`` stream (epoch = one service incarnation, so an id
  reset after restart is a new stream, not a regression) and a bounded
  record of per-phase wall attribution (``queued`` -> ``prefill`` ->
  ``decode``), mirroring the incident recorder's downtime phases;
- every wire record also carries ``submitted_hwm`` -- the highest id
  *submitted* so far in its stream.  That is what makes the audit sound:
  a replica that dies without flushing leaves ids that never produced a
  terminal record, and terminal-record gap detection alone cannot see an
  id that was never reported.  The high-water mark can.
- ``reconcile()`` is the **dropped-request audit**: per stream, every id
  in ``[contig+1, hwm]`` without a terminal record is filed as an
  explicit ``orphaned`` record (never silently lost).  The fleet harness
  harvests the count into ``FleetReport`` and files a nonzero count as
  an invariant violation, exactly like ``unattributed_downtime_ms``.
- retention is **tail-sampling**: the slowest ``ring`` requests per job
  keep their full span (``/debug/requests?id=``, ``?format=chrome``);
  the rest are dropped with an audible
  ``trainingjob_reqtrace_sampled_dropped_total`` counter -- never
  silent truncation.  A separate bounded recent window answers incident
  overlap queries (the ``requests`` bundle stanza) and percentiles.

The plane is strictly no-op unless ``start()`` ran (the PR 17 contract:
plane-off runs are byte-identical in digests and phase counts).  Stdlib
only; imports nothing above :mod:`utils.metrics` so the telemetry
aggregator and the incident recorder can both reach the singleton
without a cycle.  See docs/SERVING.md (request lifecycle) and
docs/OBSERVABILITY.md (wire shape + metric rows).
"""

from __future__ import annotations

import heapq
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.utils.metrics import METRICS

#: Terminal states a request can reach.  ``orphaned`` is never emitted by
#: a live scheduler -- only ``reconcile()`` files it, which is what makes
#: a nonzero count evidence of a dropped request rather than traffic.
REQUEST_OUTCOMES = ("completed", "rejected", "evicted", "orphaned")

#: Per-stream cap on *explicitly enumerated* orphan records; the counter
#: carries the full count either way (bounded memory, audible total).
_MAX_ORPHAN_RECORDS = 100

#: Evictions/orphans bind to an incident that OPENS up to this many
#: seconds after them.  A pod kill flushes its in-flight requests as
#: ``evicted`` records synchronously, but the incident's ``started``
#: stamp is the *controller's detection* -- under chaos a dropped watch
#: stream delays that past the eviction, and a plain interval overlap
#: would miss the failure's own footprint.
_EVICTION_BIND_S = 10.0


def _env_int(name: str, default: int, floor: int = 1) -> int:
    raw = os.environ.get(name, "")
    try:
        return max(floor, int(raw)) if raw else default
    except ValueError:
        return default


class _Stream:
    """Audit state for one ``(job, epoch)`` id stream.

    ``contig`` is the contiguous-prefix watermark (every id <= contig has
    a terminal record); ``sparse`` holds terminal ids above it and is
    compacted TCP-SACK style; ``hwm`` is the highest id known to have
    been *submitted* (terminal ids and ``submitted_hwm`` fields both
    advance it).  Missing = ids in ``[contig+1, hwm]`` not in sparse.
    """

    __slots__ = ("contig", "sparse", "hwm")

    def __init__(self) -> None:
        self.contig = -1
        self.sparse: set = set()
        self.hwm = -1

    def terminal(self, rid: int) -> None:
        if rid <= self.contig or rid in self.sparse:
            return  # duplicate terminal; first record wins
        self.sparse.add(rid)
        while (self.contig + 1) in self.sparse:
            self.contig += 1
            self.sparse.discard(self.contig)
        self.hwm = max(self.hwm, rid)

    def submitted(self, hwm: int) -> None:
        self.hwm = max(self.hwm, hwm)

    def missing(self) -> List[int]:
        return [rid for rid in range(self.contig + 1, self.hwm + 1)
                if rid not in self.sparse]


class _JobState:
    __slots__ = ("streams", "outcomes", "retained", "recent", "ttfts",
                 "tpots", "seq", "dropped")

    def __init__(self, window: int) -> None:
        self.streams: Dict[str, _Stream] = {}
        self.outcomes: Dict[str, int] = {}
        #: Slowest-k min-heap of (score, seq, record) -- tail sampling.
        self.retained: List[Tuple[float, int, Dict[str, Any]]] = []
        #: Bounded recent window for overlap queries and percentiles.
        self.recent: Deque[Dict[str, Any]] = deque(maxlen=window)
        self.ttfts: Deque[float] = deque(maxlen=window)
        self.tpots: Deque[float] = deque(maxlen=window)
        self.seq = 0
        self.dropped = 0


def _score(rec: Dict[str, Any]) -> float:
    """Slowness score for tail-sampling: total attributed wall, falling
    back to TTFT when the record carries no phase breakdown."""
    phases = rec.get("phase_ms") or {}
    total = sum(v for v in phases.values() if isinstance(v, (int, float)))
    if total > 0.0:
        return float(total)
    ttft = rec.get("ttft_ms")
    return float(ttft) if isinstance(ttft, (int, float)) else 0.0


def _pct(values: List[float], q: float) -> float:
    ordered = sorted(values)
    idx = min(int(q * len(ordered)), len(ordered) - 1)
    return round(ordered[idx], 3)


class RequestLedger:
    """Bounded per-job request ledger with a monotonic-id audit.

    Strictly no-op unless ``start()`` ran.  ``ring``/``window`` default
    from TRAININGJOB_REQTRACE_RING / _WINDOW at ``reset()`` time so tests
    and the harness can re-knob between in-process runs.
    """

    def __init__(self, ring: Optional[int] = None,
                 window: Optional[int] = None):
        self._lock = threading.Lock()
        self._started = False
        self._ring_arg = ring
        self._window_arg = window
        self._ring = 0
        self._window = 0
        self._jobs: Dict[str, _JobState] = {}
        self._apply_knobs()

    def _apply_knobs(self) -> None:
        self._ring = (self._ring_arg if self._ring_arg is not None
                      else _env_int(constants.REQTRACE_RING_ENV, 64))
        self._window = (self._window_arg if self._window_arg is not None
                        else _env_int(constants.REQTRACE_WINDOW_ENV, 512))

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            self._started = True

    def stop(self) -> None:
        """Stop accepting records; retained state stays readable (the
        harness builds its report after stopping the plane)."""
        with self._lock:
            self._started = False

    def reset(self) -> None:
        with self._lock:
            self._jobs = {}
            self._apply_knobs()

    @property
    def started(self) -> bool:
        return self._started

    # -- ingest ---------------------------------------------------------------

    def record(self, job: str, rec: Dict[str, Any]) -> bool:
        """One terminal-state record (validated upstream by the telemetry
        aggregator).  Returns False when the plane is off."""
        with self._lock:
            if not self._started:
                return False
            st = self._jobs.get(job)
            if st is None:
                st = self._jobs[job] = _JobState(self._window)
            self._record_locked(job, st, rec)
            return True

    def _record_locked(self, job: str, st: _JobState,
                       rec: Dict[str, Any]) -> None:
        epoch = str(rec.get("request_epoch", ""))
        stream = st.streams.get(epoch)
        if stream is None:
            stream = st.streams[epoch] = _Stream()
        rid = int(rec["request_id"])
        if rid <= stream.contig or rid in stream.sparse:
            return  # duplicate terminal for an already-settled id
        stream.terminal(rid)
        hwm = rec.get("submitted_hwm")
        if isinstance(hwm, int):
            stream.submitted(hwm)
        outcome = rec["request_outcome"]
        st.outcomes[outcome] = st.outcomes.get(outcome, 0) + 1
        seq = st.seq
        st.seq += 1
        kept = dict(rec)
        kept["seq"] = seq
        st.recent.append(kept)
        ttft = rec.get("ttft_ms")
        if isinstance(ttft, (int, float)):
            st.ttfts.append(float(ttft))
        tpot = rec.get("tpot_ms")
        if isinstance(tpot, (int, float)):
            st.tpots.append(float(tpot))
        # Tail-sampling: keep the slowest ``ring`` full spans; everything
        # else is dropped AUDIBLY.
        entry = (_score(kept), seq, kept)
        if len(st.retained) < self._ring:
            heapq.heappush(st.retained, entry)
        else:
            heapq.heappushpop(st.retained, entry)
            st.dropped += 1
            METRICS.inc("trainingjob_reqtrace_sampled_dropped_total",
                        job=job)

    # -- the audit ------------------------------------------------------------

    def reconcile(self, now: float) -> int:
        """File every submitted-but-never-terminal id as an explicit
        ``orphaned`` record.  Idempotent: filed ids join their stream's
        terminal set, so a second reconcile finds nothing new.  Returns
        the number of orphans filed by THIS call."""
        with self._lock:
            if not self._started and not self._jobs:
                return 0
            filed = 0
            for job, st in self._jobs.items():
                for epoch, stream in st.streams.items():
                    missing = stream.missing()
                    for i, rid in enumerate(missing):
                        stream.terminal(rid)
                        st.outcomes["orphaned"] = (
                            st.outcomes.get("orphaned", 0) + 1)
                        METRICS.inc("trainingjob_requests_total",
                                    job=job, outcome="orphaned")
                        filed += 1
                        if i >= _MAX_ORPHAN_RECORDS:
                            continue  # counted above, not enumerated
                        rec = {
                            "request_outcome": "orphaned",
                            "request_id": rid,
                            "request_epoch": epoch,
                            "ts": now,
                            "seq": st.seq,
                        }
                        st.seq += 1
                        st.recent.append(rec)
                        heapq.heappush(
                            st.retained, (float("inf"), rec["seq"], rec))
                        while len(st.retained) > self._ring:
                            heapq.heappop(st.retained)
            return filed

    # -- queries --------------------------------------------------------------

    def jobs(self) -> List[str]:
        with self._lock:
            return sorted(self._jobs)

    def window(self, job: str, start: float, end: float) -> Dict[str, Any]:
        """Requests whose [arrival, final] interval overlaps [start, end]
        -- the incident ``requests`` stanza.  Empty dict when nothing
        overlaps (absent stanza, not a zero-filled one)."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return {}
            overlapping: List[Dict[str, Any]] = []
            for rec in st.recent:
                final = rec.get("ts")
                if not isinstance(final, (int, float)):
                    continue
                arrival = rec.get("arrival")
                if not isinstance(arrival, (int, float)):
                    arrival = final  # orphans have no known arrival
                # Failure-caused terminals land BEFORE the incident opens
                # (detection latency); bind them within _EVICTION_BIND_S.
                lead = (_EVICTION_BIND_S
                        if rec.get("request_outcome") in ("evicted",
                                                          "orphaned")
                        else 0.0)
                if arrival <= end and final >= start - lead:
                    overlapping.append(rec)
            if not overlapping:
                return {}
            by_outcome: Dict[str, int] = {}
            worst_ttft = None
            for rec in overlapping:
                oc = rec.get("request_outcome", "unknown")
                by_outcome[oc] = by_outcome.get(oc, 0) + 1
                ttft = rec.get("ttft_ms")
                if isinstance(ttft, (int, float)):
                    if worst_ttft is None or ttft > worst_ttft:
                        worst_ttft = float(ttft)
            out: Dict[str, Any] = {
                "in_flight": len(overlapping),
                "outcomes": dict(sorted(by_outcome.items())),
                "orphaned": by_outcome.get("orphaned", 0),
            }
            if worst_ttft is not None:
                out["worst_ttft_ms"] = round(worst_ttft, 3)
            return out

    def ttft_percentiles(self, job: str
                         ) -> Optional[Tuple[float, float]]:
        """(p50, p99) TTFT ms, or None for a never-reporting job --
        absence is not zero (the PR 8 convention)."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None or not st.ttfts:
                return None
            vals = list(st.ttfts)
            return _pct(vals, 0.50), _pct(vals, 0.99)

    def tpot_percentiles(self, job: str
                         ) -> Optional[Tuple[float, float]]:
        with self._lock:
            st = self._jobs.get(job)
            if st is None or not st.tpots:
                return None
            vals = list(st.tpots)
            return _pct(vals, 0.50), _pct(vals, 0.99)

    def job_summary(self, job: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return None
            return self._summary_locked(st)

    def _summary_locked(self, st: _JobState) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "records_total": st.seq,
            "outcomes": dict(sorted(st.outcomes.items())),
            "orphaned": st.outcomes.get("orphaned", 0),
            "streams": len(st.streams),
            "retained": len(st.retained),
            "sampled_dropped": st.dropped,
            "open_ids": sum(len(s.missing()) for s in st.streams.values()),
        }
        if st.ttfts:
            vals = list(st.ttfts)
            out["ttft_ms_p50"] = _pct(vals, 0.50)
            out["ttft_ms_p99"] = _pct(vals, 0.99)
        if st.tpots:
            vals = list(st.tpots)
            out["tpot_ms_p50"] = _pct(vals, 0.50)
            out["tpot_ms_p99"] = _pct(vals, 0.99)
        return out

    def summary(self) -> Dict[str, Any]:
        """Fleet-level rollup for ``FleetReport.requests``."""
        with self._lock:
            jobs = {job: self._summary_locked(st)
                    for job, st in sorted(self._jobs.items())}
            return {
                "jobs_reporting": len(jobs),
                "records_total": sum(j["records_total"]
                                     for j in jobs.values()),
                "orphaned_total": sum(j["orphaned"] for j in jobs.values()),
                "sampled_dropped_total": sum(j["sampled_dropped"]
                                             for j in jobs.values()),
                "by_job": jobs,
            }

    def retained_list(self, job: str) -> Optional[List[Dict[str, Any]]]:
        """Retained spans (slowest-k plus orphans) seq-ascending, each with
        its ledger ``seq`` -- the /debug/requests?id= handle -- merged in.
        None for a job the ledger has never seen."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return None
            out: List[Dict[str, Any]] = []
            for _, s, rec in sorted(st.retained, key=lambda t: t[1]):
                d = dict(rec)
                d["seq"] = s
                out.append(d)
            return out

    def request(self, job: str, seq: int) -> Optional[Dict[str, Any]]:
        """Full retained span by ledger seq, or None (sampled away or
        never existed -- the endpoint 404s either way)."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return None
            for _, s, rec in st.retained:
                if s == seq:
                    return dict(rec)
            return None

    def export_chrome(self, job: str, seq: int
                      ) -> Optional[Dict[str, Any]]:
        """One retained request as a chrome://tracing / Perfetto trace:
        consecutive ``ph:"X"`` complete events, one per lifecycle phase,
        on a (job, request) track.  ts/dur are microseconds."""
        rec = self.request(job, seq)
        if rec is None:
            return None
        base_us = float(rec.get("arrival", rec.get("ts", 0.0))) * 1e6
        events: List[Dict[str, Any]] = []
        cursor = base_us
        for phase, ms in (rec.get("phase_ms") or {}).items():
            if not isinstance(ms, (int, float)) or ms < 0.0:
                continue
            events.append({
                "name": phase,
                "ph": "X",
                "ts": round(cursor, 3),
                "dur": round(float(ms) * 1000.0, 3),
                "pid": job,
                "tid": f"request-{rec.get('request_id', seq)}",
                "args": {"outcome": rec.get("request_outcome"),
                         "epoch": rec.get("request_epoch")},
            })
            cursor += float(ms) * 1000.0
        return {"traceEvents": events, "displayTimeUnit": "ms"}


#: Process-global request ledger, mirroring METRICS / INCIDENTS / TSDB.
REQTRACE = RequestLedger()
