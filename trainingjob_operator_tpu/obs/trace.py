"""Span tracer: the causal trail the reference operator never had.

The reference's observability is leveled klog text plus Status.Conditions
(SURVEY.md §5.5) -- when a job flaps through restart scopes you cannot
reconstruct *why* without replaying logs by hand.  This module is a
dependency-free tracer in the OpenTelemetry shape (trace_id/span_id/parent,
attributes, status) without the SDK: spans are context managers,
``contextvars`` makes nested calls auto-parent, finished traces land in a
bounded ring buffer, and two exporters serialize them -- JSON-lines (one span
per line, machine-diffable) and Chrome ``trace_event`` format (drop the file
on https://ui.perfetto.dev and read the reconcile timeline visually).

Cross-process propagation is rendezvous-style, like the rest of the
operator's workload contract: the controller serializes the current span as
``"trace_id:span_id"`` into ``constants.TRACE_CONTEXT_ENV`` and the workload
adopts it as the parent of its local root span, so one trace id spans
controller, runtime, and train loop.

A disabled tracer is a guarded fast path: ``span()`` returns a shared no-op
singleton without touching the lock, the ring, or the contextvar.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Union

#: Span status values (OpenTelemetry's OK/ERROR, lowercased).
OK = "ok"
ERROR = "error"

#: The active span of the calling context; nested ``tracer.span()`` calls
#: read it to auto-parent.  Thread-local by construction (each thread starts
#: from the default), crosses threads only via ``contextvars.copy_context()``
#: or an explicit ``parent=`` argument.
_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "trainingjob_current_span", default=None)

#: Active-span registry for the sampling profiler (obs/profiler.py): thread
#: ident -> the innermost *open* span on that thread.  A contextvar cannot
#: be read from another thread, so the profiler needs this side table to
#: join a stack sample against the span that was live when it fired.  Each
#: thread writes only its own key (single dict ops, GIL-atomic), and the
#: whole path is gated off ``_span_registry_on`` so untraced/unprofiled
#: runs pay exactly one falsy check per span enter/exit.
_THREAD_SPANS: Dict[int, "Span"] = {}
_span_registry_on = False


def enable_span_registry() -> None:
    """Turn on per-thread active-span tracking (profiler starting)."""
    global _span_registry_on
    _span_registry_on = True


def disable_span_registry() -> None:
    """Turn tracking back off and drop the map (profiler stopped)."""
    global _span_registry_on
    _span_registry_on = False
    _THREAD_SPANS.clear()


def thread_span_stack(ident: int) -> "tuple[str, ...]":
    """Root-first names of the spans open on thread ``ident`` (empty when
    none).  Racy by design -- the owner may enter/exit concurrently; a
    sample landing mid-transition sees the previous consistent chain or
    nothing, never a torn one (the chain links are set before the map
    write)."""
    span = _THREAD_SPANS.get(ident)
    names: List[str] = []
    while span is not None and len(names) < 32:
        names.append(span.name)
        span = span._prev_active
    names.reverse()
    return tuple(names)


def _new_id() -> str:
    return os.urandom(8).hex()


def current_span() -> Optional["Span"]:
    """The span enclosing the caller, or None outside any span."""
    return _current_span.get()


def current_context() -> str:
    """Serialized ``"trace_id:span_id"`` of the enclosing span (``""`` when
    there is none) -- the value handed to workloads via TRACE_CONTEXT_ENV."""
    span = _current_span.get()
    return f"{span.trace_id}:{span.span_id}" if span is not None else ""


class Span:
    """One timed operation.  Use as a context manager::

        with tracer.span("reconcile", job="default/j1") as sp:
            sp.set_attribute("pods", 4)

    Entering sets the span as the context's current span (children
    auto-parent); exiting restores the previous one, records an exception as
    status=error, and hands the finished span to the tracer.
    """

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "attributes", "status", "start_time", "end_time",
                 "pid", "tid", "thread_name", "_token", "_local_root",
                 "_prev_active")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attributes: Dict[str, Any],
                 local_root: bool):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attributes = attributes
        self.status = OK
        self.start_time = 0.0
        self.end_time = 0.0
        self.pid = os.getpid()
        self.tid = threading.get_ident()
        self.thread_name = threading.current_thread().name
        self._token: Optional[contextvars.Token] = None
        self._local_root = local_root
        self._prev_active: Optional[Span] = None

    # -- recording -----------------------------------------------------------

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def set_status(self, status: str) -> "Span":
        self.status = status
        return self

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "Span":
        self.start_time = time.time()
        self._token = _current_span.set(self)
        if _span_registry_on:
            # Link before publishing: a profiler sample between the two
            # writes sees the old head (consistent), never a broken chain.
            self._prev_active = _THREAD_SPANS.get(self.tid)
            _THREAD_SPANS[self.tid] = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end_time = time.time()
        if exc_type is not None:
            self.status = ERROR
            self.attributes.setdefault(
                "exception", f"{exc_type.__name__}: {exc}")
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        if _span_registry_on and _THREAD_SPANS.get(self.tid) is self:
            if self._prev_active is None:
                _THREAD_SPANS.pop(self.tid, None)
            else:
                _THREAD_SPANS[self.tid] = self._prev_active
        self._prev_active = None
        self._tracer._finish(self)
        return False  # never swallow

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "status": self.status,
            "attributes": dict(self.attributes),
            "pid": self.pid,
            "tid": self.tid,
            "thread": self.thread_name,
        }


class _NoopSpan:
    """Shared do-nothing span returned by a disabled tracer.  Touches no
    lock, no ring, no contextvar -- the guarded fast path."""

    __slots__ = ()

    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    status = OK
    attributes: Dict[str, Any] = {}

    def set_attribute(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def set_status(self, status: str) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


#: Module-level singleton: every disabled ``span()`` call returns this.
NOOP_SPAN = _NoopSpan()

SpanParent = Union[None, Span, str]


class Tracer:
    """Collects finished spans into traces.

    A *trace* is the span tree under one local root -- a span created with no
    enclosing span (a fresh reconcile) or with an env-carried string context
    (a workload adopting the controller's trace id).  While the root is open,
    its finished descendants accumulate in ``_active``; when the root
    finishes, the whole list moves into the bounded ``_finished`` ring
    (oldest trace evicted first).
    """

    #: Hard cap on spans recorded per trace: a runaway span producer (a train
    #: loop emitting one span per step for a week) must not grow one trace
    #: without bound.  Overflow is counted, not silent.
    MAX_SPANS_PER_TRACE = 4096

    #: Cap on concurrently-open traces: spans finishing after their local
    #: root (cross-thread stragglers) reopen an _active entry that no root
    #: will ever flush; evict oldest past this.
    MAX_ACTIVE_TRACES = 256

    def __init__(self, enabled: bool = True, max_traces: int = 256,
                 service: str = "trainingjob-operator"):
        self.enabled = enabled
        self.service = service
        self._lock = threading.Lock()
        self._active: "Dict[str, List[Dict[str, Any]]]" = {}
        self._dropped: Dict[str, int] = {}
        self._finished: "deque[Dict[str, Any]]" = deque(maxlen=max_traces)

    # -- span creation -------------------------------------------------------

    def span(self, name: str, parent: SpanParent = None,
             **attributes: Any) -> Union[Span, _NoopSpan]:
        """Open a span.  ``parent`` may be a Span, a ``"trace_id:span_id"``
        string (env-carried context), or None to adopt the context's current
        span (a fresh trace when there is none)."""
        if not self.enabled:
            return NOOP_SPAN
        local_root = False
        if parent is None:
            parent = _current_span.get()
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, str) and ":" in parent:
            trace_id, _, parent_id = parent.partition(":")
            local_root = True  # the real root lives in another process
        else:
            trace_id, parent_id = _new_id(), None
            local_root = True
        return Span(self, name, trace_id, parent_id, dict(attributes),
                    local_root)

    def _finish(self, span: Span) -> None:
        record = span.to_dict()
        with self._lock:
            spans = self._active.setdefault(span.trace_id, [])
            if (len(spans) >= self.MAX_SPANS_PER_TRACE
                    and not span._local_root):
                # Drop descendants past the cap; the root always lands so the
                # trace still flushes with its drop count attached.
                self._dropped[span.trace_id] = (
                    self._dropped.get(span.trace_id, 0) + 1)
                return
            spans.append(record)
            if span._local_root:
                self._active.pop(span.trace_id, None)
                dropped = self._dropped.pop(span.trace_id, 0)
                trace = {"trace_id": span.trace_id, "root": span.name,
                         "service": self.service, "spans": spans}
                if dropped:
                    trace["dropped_spans"] = dropped
                self._finished.append(trace)
            elif len(self._active) > self.MAX_ACTIVE_TRACES:
                oldest = next(iter(self._active))
                self._active.pop(oldest, None)
                self._dropped.pop(oldest, None)

    # -- retrieval -----------------------------------------------------------

    def traces(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Finished traces, newest first."""
        with self._lock:
            out = list(self._finished)
        out.reverse()
        return out[:limit] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self._active.clear()
            self._dropped.clear()

    # -- exporters -----------------------------------------------------------

    def export_jsonl(self, traces: Optional[List[Dict[str, Any]]] = None) -> str:
        """One JSON object per line, one line per span (trace_id on every
        line, so ``spans_from_jsonl`` reassembles traces losslessly)."""
        if traces is None:
            traces = self.traces()
        lines = [json.dumps(span, sort_keys=True)
                 for trace in traces for span in trace["spans"]]
        return "\n".join(lines) + ("\n" if lines else "")

    def export_chrome(self, traces: Optional[List[Dict[str, Any]]] = None) -> str:
        """Chrome ``trace_event`` JSON (the Perfetto/about:tracing format):
        one complete event (``ph:"X"``) per span, timestamps and durations in
        microseconds."""
        if traces is None:
            traces = self.traces()
        events: List[Dict[str, Any]] = []
        for trace in traces:
            for span in trace["spans"]:
                args = dict(span["attributes"])
                args.update(trace_id=span["trace_id"],
                            span_id=span["span_id"],
                            parent_id=span["parent_id"],
                            status=span["status"])
                events.append({
                    "ph": "X",
                    "name": span["name"],
                    "cat": trace.get("service", self.service),
                    "ts": span["start_time"] * 1e6,
                    "dur": max(span["end_time"] - span["start_time"], 0.0) * 1e6,
                    "pid": span["pid"],
                    "tid": span["tid"],
                    "args": args,
                })
        return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"},
                          indent=2)


def spans_from_jsonl(text: str) -> List[Dict[str, Any]]:
    """Inverse of ``export_jsonl``: parse back to a list of span dicts."""
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def group_traces(spans: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    """Group span dicts by trace_id, preserving order (round-trip helper)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for span in spans:
        out.setdefault(span["trace_id"], []).append(span)
    return out


#: Process-global tracer, mirroring utils.metrics.METRICS: controller,
#: pod/service control, and runtimes all record into the same ring.
TRACER = Tracer()


def tracer_from_env(environ: Optional[Dict[str, str]] = None) -> "tuple[Tracer, str]":
    """Workload-side tracer + the parent context handed down by the runtime.

    Enabled only when the launcher injected TRACE_CONTEXT_ENV -- an untraced
    run pays the no-op fast path and nothing else.  Returns ``(tracer,
    parent_context)``; pass ``parent=parent_context`` to the workload's root
    span so it joins the controller's trace.
    """
    from trainingjob_operator_tpu.api import constants

    env = os.environ if environ is None else environ
    parent = env.get(constants.TRACE_CONTEXT_ENV, "")
    return Tracer(enabled=bool(parent), service="trainingjob-workload"), parent
