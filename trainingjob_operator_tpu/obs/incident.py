"""Incident flight recorder: phase-attributed downtime timelines per job.

A preempted or stalled production job leaves its evidence scattered across
planes that never meet: controller events (``EVENT_REASONS``), restart-scope
transitions, telemetry step records, and the workload's
``resume.restore``/``resume.compile`` spans.  The recovery bench
(docs/RECOVERY.md) itemizes downtime offline; nothing reassembles it for a
LIVE job.  This module is that reassembly: a bounded per-job **flight
recorder** that taps every plane into one normalized timeline ring, and on
abnormal transitions assembles an **incident bundle** attributing every ms
of downtime to a named phase::

    detect -> teardown -> reschedule -> rendezvous -> restore -> compile
           -> reshard -> first_step   (+ ``unknown`` for evicted residue)

An in-place resize (scope Resize, docs/ELASTIC.md) never tears the
survivors down, so its window attributes to ``detect -> reshard ->
first_step`` only: ``reshard`` is the survivors re-forming the mesh and
exchanging shards peer-to-peer -- a window with ``teardown`` time in it
means the fast path did not engage.

Lifecycle mirrors the GOODPUT/TELEMETRY singletons: the controller calls
``on_interruption``/``on_running``/``on_complete``/``forget`` from the same
chokepoints, the ``EventRecorder`` sink feeds ``record_event``, telemetry
ingest feeds ``record_step``/``record_resume``.  An incident opens at the
interruption (or at abnormal evidence: StepStalled, a terminal Failed /
Preempted / NodeFail / Timeout), closes provisionally when the job is
Running again (the control window -- byte-for-byte the goodput ledger's
downtime window, both hooks receive the same ``now``), and is amended once
by the first post-recovery step record so the workload tail (restore /
compile / first step) is attributed too.

Determinism: bundle assembly is a pure function of the frozen ring snapshot
(``reassemble`` re-runs it; two assemblies of the same ring are
byte-identical), serialized with sorted keys and no wall-clock reads.

Exported via ``/debug/incidents`` (utils/metrics.py), the metrics
``trainingjob_downtime_ms{job,phase}`` / ``trainingjob_incidents_total{reason}``
/ ``trainingjob_incident_bundle_bytes{job}``, and aggregated per churn fate
into the fleet report (fleet/harness.py).  Everything is bounded: the
timeline rings by ``TRAININGJOB_INCIDENT_RING`` entries per plane, retained
bundles by ``TRAININGJOB_INCIDENT_BUNDLES`` per job.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.reqtrace import REQTRACE
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

#: Attribution order.  Control-plane phases (detect/teardown/reschedule/
#: rendezvous) partition the interruption -> Running window exactly; the
#: workload phases (restore/compile/first_step) cover the tail up to the
#: first post-recovery step; ``unknown`` absorbs windows whose markers were
#: evicted from the ring.
PHASES = ("detect", "teardown", "reschedule", "rendezvous", "restore",
          "compile", "reshard", "first_step", "unknown")

#: Terminal phases that are incidents in their own right (spellings match
#: api/types.py TrainingJobPhase; this module stays import-light like
#: obs/goodput.py and cannot pull types.py in).
ABNORMAL_ENDINGS = frozenset(("Failed", "Preempted", "NodeFail", "Timeout"))

#: Event reasons that mark the controller ACTING on an abnormality -- the
#: first one inside a window ends the ``detect`` phase.
_CORRECTIVE_REASONS = frozenset((
    constants.RESTARTING_REASON,
    constants.SCALING_REASON,
    constants.TERMINATING_REASON,
    constants.SUCCESSFUL_DELETE_POD_REASON,
    constants.RESIZE_STARTED_REASON,
))

#: Event reasons that are abnormal evidence on their own -- the earliest one
#: anchors a terminal incident that never went through on_interruption.
_EVIDENCE_REASONS = frozenset((
    constants.EXITED_WITH_CODE_REASON,
    constants.PREEMPTED_REASON,
    constants.FAILED_REASON,
    constants.NODE_FAIL_REASON,
    constants.TIMEOUT_REASON,
    constants.TERMINATING_REASON,
    constants.STEP_STALLED_REASON,
))


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        value = int(os.environ.get(name, "") or default)
    except ValueError:
        value = default
    return max(value, floor)


def _clamp(x: float, lo: float, hi: float) -> float:
    return min(max(x, lo), hi)


class _OpenIncident:
    __slots__ = ("id", "kind", "reason", "scope", "started", "running_at",
                 "trace", "counted")

    def __init__(self, inc_id: int, kind: str, reason: str, scope: str,
                 started: float, trace: str) -> None:
        self.id = inc_id
        self.kind = kind              # "restart" | "resize" | "stall" | "terminal"
        self.reason = reason          # the triggering EVENT_REASONS member
        self.scope = scope            # RestartScope value, "scale", or ""
        self.started = started
        self.running_at: Optional[float] = None
        self.trace = trace            # sync_job "trace_id:span_id" context
        self.counted = False          # trainingjob_incidents_total inc'd


class _JobIncidents:
    __slots__ = ("events", "steps", "resumes", "rendezvous", "open",
                 "bundles", "seq", "completed", "last_end", "gauges")

    def __init__(self, ring: int, keep: int) -> None:
        #: (ts, reason, message), newest last -- the control-plane ring.
        self.events: Deque[Tuple[float, str, str]] = deque(maxlen=ring)
        #: (ts, step, ms, ckpt_ms, hbm_bytes) -- the workload step ring.
        #: Separate from ``events`` so a busy job's step flood cannot evict
        #: the create/delete markers attribution depends on.
        self.steps: Deque[Tuple[float, int, float, Optional[float],
                                Optional[float]]] = deque(maxlen=ring)
        #: (ts, restore_ms, compile_ms, overlapped, fallback) resume-span
        #: records; ``fallback`` is the structured checkpoint-fallback
        #: reason ("" when the restore took the happy path).
        self.resumes: Deque[Tuple[float, float, float, bool, str]] = \
            deque(maxlen=8)
        #: (ts, total_ms, rung, reason, phases) live-rebootstrap records
        #: (docs/ELASTIC.md): the survivor reporting which fallback rung its
        #: re-rendezvous took and how long it spent there.  ``phases`` is a
        #: sorted ((name, ms), ...) tuple so the frozen snapshot stays
        #: hashable and serializes deterministically.
        self.rendezvous: Deque[Tuple[float, float, str, str,
                                     Tuple[Tuple[str, float], ...]]] = \
            deque(maxlen=8)
        self.open: Optional[_OpenIncident] = None
        #: Retained bundles, oldest first: {"bundle", "json", "inputs"}.
        self.bundles: Deque[Dict[str, Any]] = deque(maxlen=keep)
        self.seq = 0
        self.completed = False
        self.last_end = 0.0           # newest finalized incident's end ts
        self.gauges: List[Tuple[str, Dict[str, str]]] = []


def _attribute(kind: str, t0: float, t1c: float, t_end: float,
               events: Tuple[Tuple[float, str, str], ...],
               steps: Tuple[Tuple[float, int, float, Optional[float],
                                  Optional[float]], ...],
               resumes: Tuple[Tuple[float, float, float, bool, str], ...],
               rendezvous: Tuple[Tuple[float, float, str, str,
                                       Tuple[Tuple[str, float], ...]], ...]
               = (),
               ) -> List[Tuple[str, float, float]]:
    """Partition [t0, t_end] into phase segments from the ring markers.

    Pure: no clocks, no state -- the determinism contract.  ``t1c`` is the
    control-window end (the Running transition; == t_end while no workload
    evidence has arrived).  Returns ordered (phase, start, end) segments
    whose union is exactly [t0, t_end].
    """
    if kind == "stall":
        # Stall that resolved without controller action: the whole window is
        # detection latency -- nothing ever acted.
        return [("detect", t0, t_end)]
    if kind == "terminal":
        corrective = [ts for ts, reason, _ in events
                      if reason in _CORRECTIVE_REASONS and t0 <= ts <= t_end]
        b = _clamp(min(corrective), t0, t_end) if corrective else t_end
        return [("detect", t0, b), ("teardown", b, t_end)]

    window = [(ts, reason) for ts, reason, _ in events if t0 <= ts <= t_end]
    if not window:
        # Ring evicted (or events never tapped): refuse to invent phases.
        return [("unknown", t0, t_end)]
    corrective = [ts for ts, reason in window
                  if reason in _CORRECTIVE_REASONS]
    b_detect = _clamp(min(corrective), t0, t1c) if corrective else t0
    rdv_records = [r for r in rendezvous if t0 <= r[0] <= t_end]
    rung = rdv_records[-1][2] if rdv_records else ""
    if kind == "resize" and rung not in ("checkpoint", "restart_all"):
        # Survivor-keepalive resize: nothing is torn down or rescheduled.
        # A live-rebootstrap record splits the window at its completion
        # timestamp -- before it is the coordinator re-rendezvous
        # (shutdown/barrier/reinit, docs/ELASTIC.md), after it the
        # peer-to-peer reshard; the first step's own duration is
        # first_step, as in the generic path.  A degraded rung
        # (checkpoint/restart_all) means pods really restarted, so it
        # falls through to the generic teardown/reschedule attribution.
        first_steps = [s for s in steps if t1c < s[0] <= t_end]
        if rdv_records:
            # The record's timestamp is a direct observation of when the
            # rebootstrap finished, so it outranks the inferred step-start
            # boundary: reshard/first_step split whatever remains after it.
            b_rdv = _clamp(rdv_records[-1][0], b_detect, t_end)
            if first_steps:
                b_reshard = _clamp(t_end - first_steps[0][2] / 1e3,
                                   b_rdv, t_end)
            else:
                b_reshard = _clamp(t1c, b_rdv, t_end)
            return [("detect", t0, b_detect),
                    ("rendezvous", b_detect, b_rdv),
                    ("reshard", b_rdv, b_reshard),
                    ("first_step", b_reshard, t_end)]
        if first_steps:
            b_reshard = _clamp(t_end - first_steps[0][2] / 1e3,
                               b_detect, t_end)
        else:
            b_reshard = _clamp(t1c, b_detect, t_end)
        return [("detect", t0, b_detect),
                ("reshard", b_detect, b_reshard),
                ("first_step", b_reshard, t_end)]
    deletes = [ts for ts, reason in window
               if reason == constants.SUCCESSFUL_DELETE_POD_REASON]
    b_teardown = _clamp(max(deletes), b_detect, t1c) if deletes else b_detect
    creates = [ts for ts, reason in window
               if reason == constants.SUCCESSFUL_CREATE_POD_REASON]
    b_resched = _clamp(max(creates), b_teardown, t1c) if creates else b_teardown

    resume = [r for r in resumes if b_resched <= r[0] <= t_end]
    first_steps = [s for s in steps if t1c < s[0] <= t_end]
    if resume:
        # The workload reported its resume spans: anchor rendezvous end at
        # (resume completion - resume duration).  Overlapped restore+compile
        # charges ``compile`` only the non-hidden tail, matching the
        # ~max(restore, compile) wall cost docs/RECOVERY.md measures.
        ts_r, restore_ms, compile_ms, overlapped = resume[-1][:4]
        extra_ms = (max(compile_ms - restore_ms, 0.0) if overlapped
                    else compile_ms)
        b_rdv = _clamp(ts_r - (restore_ms + extra_ms) / 1e3, b_resched, t_end)
        b_restore = _clamp(b_rdv + restore_ms / 1e3, b_rdv, t_end)
        b_compile = _clamp(b_restore + extra_ms / 1e3, b_restore, t_end)
    elif first_steps:
        # No resume evidence, but a first step: its own duration is the
        # first_step phase; everything between Running and it is rendezvous.
        ts_s, _step, ms_s = first_steps[0][:3]
        b_rdv = b_restore = b_compile = _clamp(t_end - ms_s / 1e3,
                                               b_resched, t_end)
    else:
        # Control-only window (provisional bundle, or a job that never
        # reports telemetry): rendezvous runs to the Running transition.
        b_rdv = b_restore = b_compile = _clamp(t1c, b_resched, t_end)
    return [("detect", t0, b_detect),
            ("teardown", b_detect, b_teardown),
            ("reschedule", b_teardown, b_resched),
            ("rendezvous", b_resched, b_rdv),
            ("restore", b_rdv, b_restore),
            ("compile", b_restore, b_compile),
            ("first_step", b_compile, t_end)]


def _union_ms(windows: Tuple[Tuple[str, float, float], ...]) -> float:
    """Total milliseconds covered by the union of (kind, start, end)
    windows (kinds may overlap; double-counting would overstate chaos)."""
    spans = sorted((s, e) for _, s, e in windows if e > s)
    total = 0.0
    cur_s = cur_e = None
    for s, e in spans:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total * 1e3


def _freeze_requests(snap: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    """Request-window snapshot (obs/reqtrace.py ``window``) -> hashable
    sorted tuple, so the frozen ``inputs`` stay reassembly-exact."""
    out: List[Tuple[str, Any]] = []
    for k, v in sorted(snap.items()):
        if isinstance(v, dict):
            v = tuple(sorted(v.items()))
        out.append((k, v))
    return tuple(out)


def _thaw_requests(frozen: Tuple[Tuple[str, Any], ...]) -> Dict[str, Any]:
    return {k: (dict(v) if isinstance(v, tuple) else v) for k, v in frozen}


def _assemble(inc: Dict[str, Any],
              events: Tuple[Tuple[float, str, str], ...],
              steps: Tuple[Tuple[float, int, float, Optional[float],
                                 Optional[float]], ...],
              resumes: Tuple[Tuple[float, float, float, bool, str], ...],
              rendezvous: Tuple[Tuple[float, float, str, str,
                                      Tuple[Tuple[str, float], ...]], ...]
              = (),
              chaos: Tuple[Tuple[str, float, float], ...] = (),
              slo: Tuple[str, ...] = (),
              requests: Tuple[Tuple[str, Any], ...] = (),
              ) -> Dict[str, Any]:
    """Ring snapshot -> incident bundle.  Pure and deterministic: the same
    inputs serialize to the same bytes (``reassemble`` asserts this in
    tests); no wall-clock reads, sorted keys at serialization."""
    t0 = inc["started"]
    t_end = inc["ended"]
    t1c = inc["running_at"] if inc["running_at"] is not None else t_end
    segments = _attribute(inc["kind"], t0, t1c, t_end, events, steps, resumes,
                          rendezvous)
    phases = {p: 0.0 for p in PHASES}
    for phase, a, b in segments:
        phases[phase] += max(b - a, 0.0) * 1e3
    timeline: List[Dict[str, Any]] = []
    for ts, reason, message in events:
        timeline.append({"ts": round(ts, 6), "kind": "event",
                         "reason": reason, "message": message})
    for ts, step, ms, ckpt_ms, hbm_bytes in steps:
        entry: Dict[str, Any] = {"ts": round(ts, 6), "kind": "step",
                                 "step": step, "ms": round(ms, 3)}
        if ckpt_ms is not None:
            entry["ckpt_ms"] = round(ckpt_ms, 3)
        if hbm_bytes is not None:
            entry["hbm_bytes"] = hbm_bytes
        timeline.append(entry)
    for record in resumes:
        ts, restore_ms, compile_ms, overlapped = record[:4]
        fallback = record[4] if len(record) > 4 else ""
        entry = {"ts": round(ts, 6), "kind": "resume",
                 "restore_ms": round(restore_ms, 3),
                 "compile_ms": round(compile_ms, 3),
                 "overlapped": overlapped}
        if fallback:
            # Structured checkpoint-fallback reason (missing/stale/corrupt/
            # structure_mismatch/corrupt_latest...): only present when the
            # restore degraded, so happy-path bundles stay byte-identical.
            entry["fallback"] = fallback
        timeline.append(entry)
    for ts, total_ms, rung, why, rdv_phases in rendezvous:
        entry = {"ts": round(ts, 6), "kind": "rendezvous",
                 "total_ms": round(total_ms, 3), "rung": rung}
        if why:
            entry["reason"] = why
        if rdv_phases:
            entry["phase_ms"] = {p: round(v, 3) for p, v in rdv_phases}
        timeline.append(entry)
    timeline.sort(key=lambda e: (e["ts"], e["kind"],
                                 json.dumps(e, sort_keys=True)))
    window_rdv = [r for r in rendezvous if t0 <= r[0] <= t_end]
    out = {
        "id": inc["id"],
        "job": inc["job"],
        "kind": inc["kind"],
        "reason": inc["reason"],
        "scope": inc["scope"],
        "trace": inc["trace"],
        "started": round(t0, 6),
        "running_at": (round(inc["running_at"], 6)
                       if inc["running_at"] is not None else None),
        "ended": round(t_end, 6),
        "downtime_ms": round(max(t_end - t0, 0.0) * 1e3, 3),
        "control_downtime_ms": (round(max(t1c - t0, 0.0) * 1e3, 3)
                                if inc["running_at"] is not None else None),
        "rung": window_rdv[-1][2] if window_rdv else None,
        # Control-plane chaos attribution: every injected-fault window
        # (latency spike, watch drop) overlapping this incident, clipped to
        # it -- a fleet report reading the bundle can tell "slow because
        # the apiserver was browning out" from an organic regression.
        "chaos_windows": [{"kind": k, "start": round(s, 6),
                           "end": round(e, 6),
                           "ms": round(max(e - s, 0.0) * 1e3, 3)}
                          for k, s, e in chaos],
        "chaos_overlap_ms": round(_union_ms(chaos), 3),
        "phases": {p: round(v, 3) for p, v in phases.items()},
        "segments": [{"phase": p, "start": round(a, 6), "end": round(b, 6)}
                     for p, a, b in segments if b > a],
        "timeline": timeline,
    }
    if slo:
        # Fleet SLO attribution (obs/slo.py): the objectives whose breach
        # episode overlapped this incident's window.  Key present only
        # when a breach was live, like "fallback" on resume entries --
        # happy-path bundles stay byte-identical to pre-SLO ones.
        out["slo_breaches"] = list(slo)
    if requests:
        # Request-plane attribution (obs/reqtrace.py): the requests whose
        # lifecycle overlapped this window -- in-flight count, per-outcome
        # split, orphans, worst TTFT.  Key present only when the request
        # plane observed overlap, so plane-off bundles stay byte-identical.
        out["requests"] = _thaw_requests(requests)
    return out


def _canonical(bundle: Dict[str, Any]) -> str:
    return json.dumps(bundle, sort_keys=True, separators=(",", ":"))


def bundle_to_chrome(bundle: Dict[str, Any]) -> str:
    """Chrome ``trace_event`` rendering of one bundle (same format as
    obs/trace.py export_chrome, Perfetto-loadable): one complete event per
    phase segment on a ``phases`` track, one instant event per timeline
    entry on a ``timeline`` track.  Pure function of the bundle."""
    events: List[Dict[str, Any]] = []
    for seg in bundle["segments"]:
        events.append({
            "ph": "X",
            "name": seg["phase"],
            "cat": "incident",
            "ts": seg["start"] * 1e6,
            "dur": max(seg["end"] - seg["start"], 0.0) * 1e6,
            "pid": bundle["job"],
            "tid": "phases",
            "args": {"incident": bundle["id"], "reason": bundle["reason"],
                     "scope": bundle["scope"]},
        })
    for entry in bundle["timeline"]:
        name = (entry.get("reason") if entry["kind"] == "event"
                else f"{entry['kind']} {entry.get('step', '')}".strip())
        events.append({
            "ph": "i",
            "s": "t",
            "name": name,
            "cat": f"incident.{entry['kind']}",
            "ts": entry["ts"] * 1e6,
            "pid": bundle["job"],
            "tid": "timeline",
            "args": {k: v for k, v in entry.items() if k not in ("ts",)},
        })
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"},
                      sort_keys=True, indent=2)


class IncidentRecorder:
    """Thread-safe per-job flight recorder + incident bundle assembly.

    All hooks are cheap and bounded; the controller calls them from the
    reconcile path (the same chokepoints that feed GOODPUT/TELEMETRY), so
    nothing here may block or grow without bound.
    """

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 ring: Optional[int] = None, keep: Optional[int] = None):
        self._metrics = metrics or METRICS
        self.ring = ring if ring is not None else _env_int(
            constants.INCIDENT_RING_ENV, 256)
        self.keep = keep if keep is not None else _env_int(
            constants.INCIDENT_BUNDLES_ENV, 8)
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobIncidents] = {}
        self._event_sink: Optional[Callable[[str, str, str], None]] = None
        #: Global (kind, start, end) chaos-fault windows; bundles assembled
        #: while one overlaps are annotated with the clipped window.
        self._chaos: Deque[Tuple[str, float, float]] = deque(maxlen=1024)
        #: Fleet SLO breach episodes, (objective, start, end-or-None); an
        #: open episode (end None) overlaps everything after its start.
        #: Bundles whose window overlaps one carry the objective name.
        self._slo: Deque[Tuple[str, float, Optional[float]]] = deque(
            maxlen=256)

    def set_event_sink(self,
                       sink: Optional[Callable[[str, str, str], None]]) -> None:
        """``sink(job_key, reason, message)`` -- the controller points this
        at its event plumbing so assembled bundles announce themselves as
        ``IncidentRecorded`` job events."""
        with self._lock:
            self._event_sink = sink

    def _state_locked(self, job: str) -> _JobIncidents:
        st = self._jobs.get(job)
        if st is None:
            st = self._jobs[job] = _JobIncidents(self.ring, self.keep)
        return st

    # -- ring taps ------------------------------------------------------------

    def record_chaos_window(self, kind: str, start: float, end: float) -> None:
        """Declare a control-plane fault window (wall-clock span).  The fleet
        harness registers the chaos plan's latency spikes and watch drops so
        every bundle assembled under one carries the attribution."""
        if end <= start:
            return
        with self._lock:
            self._chaos.append((str(kind), float(start), float(end)))

    def clear_chaos_windows(self) -> None:
        """Drop declared chaos windows (a new run's schedule replaces the
        previous run's in this process-global recorder)."""
        with self._lock:
            self._chaos.clear()

    def record_slo_breach(self, name: str, start: float) -> None:
        """An SLO breach episode opened (obs/slo.py engine transition);
        incident bundles finalized while it is open are stamped with the
        breached objective."""
        with self._lock:
            self._slo.append((str(name), float(start), None))

    def record_slo_recovered(self, name: str, end: float) -> None:
        """Close the newest open episode of ``name`` (the engine only
        recovers an objective it breached, so newest-open is the one)."""
        with self._lock:
            for i in range(len(self._slo) - 1, -1, -1):
                n, s, e = self._slo[i]
                if n == name and e is None:
                    self._slo[i] = (n, s, float(end))
                    return

    def clear_slo_breaches(self) -> None:
        """Drop recorded breach episodes (the SLO engine starting a new
        run replaces the previous run's state)."""
        with self._lock:
            self._slo.clear()

    def record_event(self, job: str, reason: str, message: str,
                     ts: Optional[float] = None) -> None:
        """Every controller event lands here (EventRecorder sink).  Besides
        feeding the ring, StepStalled opens a stall incident and StepResumed
        closes one that no restart adopted."""
        ts = time.time() if ts is None else ts
        emit: List[Tuple[str, str, str]] = []
        with self._lock:
            st = self._state_locked(job)
            st.events.append((ts, reason, message))
            if st.completed:
                return
            if (reason == constants.STEP_STALLED_REASON and st.open is None):
                st.seq += 1
                st.open = _OpenIncident(st.seq, "stall", reason, "", ts, "")
            elif (reason == constants.STEP_RESUMED_REASON
                  and st.open is not None and st.open.kind == "stall"):
                st.open.running_at = ts
                emit = self._finalize_locked(job, st, ended=ts, close=True)
        self._emit(emit)

    def record_step(self, job: str, step: int, ms: float,
                    ckpt_ms: Optional[float] = None,
                    hbm_bytes: Optional[float] = None,
                    now: Optional[float] = None) -> None:
        """One telemetry step record (fed by TelemetryAggregator.ingest).
        The first step after a recovery amends the provisional bundle with
        the workload tail (rendezvous/restore/compile/first_step split)."""
        now = time.time() if now is None else now
        emit: List[Tuple[str, str, str]] = []
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.completed:
                return
            st.steps.append((now, int(step), float(ms), ckpt_ms, hbm_bytes))
            inc = st.open
            if (inc is not None and inc.running_at is not None
                    and now > inc.running_at):
                emit = self._finalize_locked(job, st, ended=now, close=True)
        self._emit(emit)

    def record_resume(self, job: str, restore_ms: float, compile_ms: float,
                      overlapped: bool, now: Optional[float] = None,
                      fallback: str = "") -> None:
        """The workload finished ``overlapped_restore`` (resume.restore /
        resume.compile spans, pushed as a telemetry resume record).
        ``fallback`` carries the structured checkpoint-fallback reason when
        the restore degraded (docs/RECOVERY.md integrity ladder)."""
        now = time.time() if now is None else now
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.completed:
                return
            st.resumes.append((now, float(restore_ms), float(compile_ms),
                               bool(overlapped), str(fallback)))

    def record_rendezvous(self, job: str, total_ms: float, rung: str,
                          reason: str = "",
                          phases: Optional[Dict[str, float]] = None,
                          now: Optional[float] = None) -> None:
        """A survivor finished (or degraded out of) a live re-rendezvous
        (docs/ELASTIC.md fallback ladder).  ``rung`` is which ladder rung
        the resize ultimately took -- the latest record inside an incident
        window wins, so a survivor that reported ``live`` and then degraded
        re-reports with the rung it fell to.  The record both splits the
        resize window's rendezvous phase and stamps ``rung`` on the bundle."""
        now = time.time() if now is None else now
        emit: List[Tuple[str, str, str]] = []
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.completed:
                return
            st.rendezvous.append((
                now, float(total_ms), str(rung), str(reason),
                tuple(sorted((str(p), float(v))
                             for p, v in (phases or {}).items()))))
            inc = st.open
            if inc is not None and inc.running_at is not None:
                # Amend the provisional bundle in place so the rung is
                # visible before (or without) a first-step record.
                emit = self._finalize_locked(
                    job, st, ended=max(now, inc.running_at), close=False)
        self._emit(emit)

    # -- lifecycle hooks (controller/status machine) --------------------------

    def on_interruption(self, job: str, scope: str, reason: str,
                        now: Optional[float] = None,
                        trace: str = "") -> None:
        """A restart/resize drain started (same call site and ``now`` as
        ``GOODPUT.on_interruption``, so the control window matches the
        goodput ledger exactly).  Adopts an open stall incident -- the stall
        detected what the restart is now correcting -- and rolls over an
        incident still waiting on its first post-recovery step."""
        now = time.time() if now is None else now
        emit: List[Tuple[str, str, str]] = []
        # Spelling matches api/types.py RestartScope.RESIZE; this module
        # stays import-light (see ABNORMAL_ENDINGS) and cannot pull types.py.
        kind = "resize" if scope == "Resize" else "restart"
        with self._lock:
            st = self._state_locked(job)
            if st.completed:
                return
            inc = st.open
            if inc is not None and inc.kind == "stall":
                inc.kind = kind
                inc.scope = scope
                inc.trace = inc.trace or trace
                return
            if inc is not None and inc.running_at is None:
                return  # already inside a window; idempotent re-entry
            if inc is not None:
                # Recovering but the first step never came: close as-is.
                emit = self._finalize_locked(job, st, ended=inc.running_at,
                                             close=True)
            st.seq += 1
            st.open = _OpenIncident(st.seq, kind, reason, scope, now,
                                    trace)
        self._emit(emit)

    def on_running(self, job: str, now: Optional[float] = None) -> None:
        """Back to Running: the control window closes (== the goodput
        downtime window) and a provisional bundle is assembled immediately;
        the next step record amends it with the workload tail."""
        now = time.time() if now is None else now
        emit: List[Tuple[str, str, str]] = []
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.completed:
                return
            inc = st.open
            if inc is None or inc.running_at is not None:
                return
            if inc.kind == "stall":
                # A Running refresh is not the stall's resolution signal;
                # StepResumed or a restart adoption will close it.
                return
            inc.running_at = now
            emit = self._finalize_locked(job, st, ended=now, close=False)
        self._emit(emit)

    def on_complete(self, job: str, phase: str,
                    now: Optional[float] = None) -> None:
        """Terminal phase.  An abnormal ending (Failed/Preempted/NodeFail/
        Timeout) without an open window synthesizes a terminal incident
        anchored at the earliest abnormal evidence in the ring."""
        now = time.time() if now is None else now
        emit: List[Tuple[str, str, str]] = []
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.completed:
                return
            st.completed = True
            if st.open is not None:
                inc = st.open
                ended = inc.running_at if inc.running_at is not None else now
                if str(phase) in ABNORMAL_ENDINGS and inc.running_at is None:
                    inc.kind = "terminal"
                    inc.reason = f"TrainingJob{phase}"
                emit = self._finalize_locked(job, st, ended=ended, close=True)
            elif str(phase) in ABNORMAL_ENDINGS:
                evidence = [ts for ts, reason, _ in st.events
                            if reason in _EVIDENCE_REASONS
                            and st.last_end < ts <= now]
                started = min(evidence) if evidence else now
                st.seq += 1
                st.open = _OpenIncident(st.seq, "terminal",
                                        f"TrainingJob{phase}", "", started, "")
                emit = self._finalize_locked(job, st, ended=now, close=True)
        self._emit(emit)

    def forget(self, job: str) -> None:
        """Job object gone: drop state and every gauge registered for it."""
        with self._lock:
            st = self._jobs.pop(job, None)
            if st is None:
                return
            for name, labels in st.gauges:
                self._metrics.remove_gauge(name, **labels)

    # -- assembly -------------------------------------------------------------

    def _finalize_locked(self, job: str, st: _JobIncidents,
                         ended: float, close: bool,
                         ) -> List[Tuple[str, str, str]]:
        """Assemble (or amend) the open incident's bundle from a frozen ring
        snapshot.  Returns the events to emit AFTER the lock is released."""
        inc = st.open
        if inc is None:
            return []
        t0 = inc.started
        inc_dict = {
            "id": inc.id, "job": job, "kind": inc.kind, "reason": inc.reason,
            "scope": inc.scope, "trace": inc.trace, "started": t0,
            "running_at": inc.running_at, "ended": ended,
        }
        # Freeze only the window-relevant slice: bundles stay O(window), and
        # reassembly from stored inputs is exact.
        events = tuple(e for e in st.events if t0 <= e[0] <= ended)
        steps = tuple(s for s in st.steps if t0 <= s[0] <= ended)
        resumes = tuple(r for r in st.resumes if t0 <= r[0] <= ended)
        rendezvous = tuple(r for r in st.rendezvous if t0 <= r[0] <= ended)
        chaos = tuple(sorted((k, max(t0, s), min(ended, e))
                             for (k, s, e) in self._chaos
                             if s <= ended and e >= t0))
        slo = tuple(sorted({n for (n, s, e) in self._slo
                            if s <= ended and (e is None or e >= t0)}))
        requests = _freeze_requests(REQTRACE.window(job, t0, ended))
        inputs = (inc_dict, events, steps, resumes, rendezvous, chaos, slo,
                  requests)
        bundle = _assemble(*inputs)
        encoded = _canonical(bundle)
        if st.bundles and st.bundles[-1]["bundle"]["id"] == inc.id:
            st.bundles[-1] = {"bundle": bundle, "json": encoded,
                              "inputs": inputs}
        else:
            st.bundles.append({"bundle": bundle, "json": encoded,
                               "inputs": inputs})
        emit: List[Tuple[str, str, str]] = []
        if not inc.counted:
            inc.counted = True
            self._metrics.inc("trainingjob_incidents_total",
                              reason=inc.reason)
            if not st.gauges:
                self._register_gauges_locked(job, st)
            top = max(bundle["phases"].items(), key=lambda kv: kv[1])
            emit.append((job, constants.INCIDENT_RECORDED_REASON,
                         f"incident #{inc.id} ({inc.reason}): "
                         f"{bundle['downtime_ms']:.0f} ms downtime, "
                         f"largest phase {top[0]}={top[1]:.0f} ms -- "
                         f"/debug/incidents?job={job}"))
        if close:
            st.last_end = ended
            st.open = None
        return emit

    def _register_gauges_locked(self, job: str, st: _JobIncidents) -> None:
        for phase in PHASES:
            self._metrics.gauge(
                "trainingjob_downtime_ms",
                lambda j=job, p=phase: self._phase_total(j, p),
                job=job, phase=phase)
            st.gauges.append(("trainingjob_downtime_ms",
                              {"job": job, "phase": phase}))
        self._metrics.gauge("trainingjob_incident_bundle_bytes",
                            lambda j=job: float(self.retained_bytes(j)),
                            job=job)
        st.gauges.append(("trainingjob_incident_bundle_bytes", {"job": job}))

    def _phase_total(self, job: str, phase: str) -> float:
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return 0.0
            return sum(b["bundle"]["phases"].get(phase, 0.0)
                       for b in st.bundles)

    def _emit(self, events: List[Tuple[str, str, str]]) -> None:
        if not events:
            return
        with self._lock:
            sink = self._event_sink
        if sink is None:
            return
        for job, reason, message in events:
            try:
                sink(job, reason, message)
            # analyzer: allow[broad-except]: the sink is controller code
            # (event recorder + enqueue); the recorder must survive it.
            except Exception:
                pass

    # -- queries --------------------------------------------------------------

    def jobs(self) -> List[Dict[str, Any]]:
        """Per-job summary behind ``/debug/incidents`` without ``?job=``."""
        with self._lock:
            return [{"job": job,
                     "incidents": len(st.bundles),
                     "open": st.open is not None,
                     "bytes": sum(len(b["json"]) for b in st.bundles)}
                    for job, st in sorted(self._jobs.items())]

    def bundles(self, job: str) -> Optional[List[Dict[str, Any]]]:
        """Retained bundles, oldest first; None when the job is unknown
        (the endpoint 404s)."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return None
            return [b["bundle"] for b in st.bundles]

    def bundle_json(self, job: str,
                    incident_id: Optional[int] = None) -> Optional[str]:
        """Canonical serialized bundle (newest, or by id)."""
        with self._lock:
            entry = self._entry_locked(job, incident_id)
            return entry["json"] if entry is not None else None

    def reassemble(self, job: str,
                   incident_id: Optional[int] = None) -> Optional[str]:
        """Re-run assembly from the stored ring snapshot.  Byte-identical to
        ``bundle_json`` -- the determinism contract the tests pin."""
        with self._lock:
            entry = self._entry_locked(job, incident_id)
            if entry is None:
                return None
            inputs = entry["inputs"]
        return _canonical(_assemble(*inputs))

    def export_chrome(self, job: str,
                      incident_id: Optional[int] = None) -> Optional[str]:
        with self._lock:
            entry = self._entry_locked(job, incident_id)
            if entry is None:
                return None
            bundle = entry["bundle"]
        return bundle_to_chrome(bundle)

    def _entry_locked(self, job: str,
                      incident_id: Optional[int]) -> Optional[Dict[str, Any]]:
        st = self._jobs.get(job)
        if st is None or not st.bundles:
            return None
        if incident_id is None:
            return st.bundles[-1]
        for entry in st.bundles:
            if entry["bundle"]["id"] == incident_id:
                return entry
        return None

    def retained_bytes(self, job: str) -> int:
        """Total serialized bytes of the job's retained bundles (the
        ``trainingjob_incident_bundle_bytes`` gauge)."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None:
                return 0
            return sum(len(b["json"]) for b in st.bundles)

    def open_incident(self, job: str) -> Optional[Dict[str, Any]]:
        """The in-flight incident, for tests/debugging."""
        with self._lock:
            st = self._jobs.get(job)
            if st is None or st.open is None:
                return None
            inc = st.open
            return {"id": inc.id, "kind": inc.kind, "reason": inc.reason,
                    "scope": inc.scope, "started": inc.started,
                    "running_at": inc.running_at}


#: Process-global recorder, mirroring METRICS/TRACER/GOODPUT/TELEMETRY.
INCIDENTS = IncidentRecorder()
