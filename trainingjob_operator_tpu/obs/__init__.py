"""Observability: span tracing, structured logging, goodput, telemetry.

Dependency-free (stdlib only, like ``tools/analyze``).  Process-global
singletons mirror ``utils.metrics.METRICS``:

- ``TRACER``    -- span tracer with a bounded ring of finished traces;
- ``GOODPUT``   -- goodput ledger fed by the status machine;
- ``TELEMETRY`` -- per-step replica telemetry aggregator (throughput, MFU,
  straggler skew, stall watchdog), fed by the runtimes' sinks;
- structured logging is stateless (``get_logger`` binds context per call).

See docs/OBSERVABILITY.md for the span/metric/event catalogs.
"""

from trainingjob_operator_tpu.obs.goodput import GOODPUT, GoodputTracker
from trainingjob_operator_tpu.obs.telemetry import (
    TELEMETRY,
    TelemetryAggregator,
    TelemetryEmitter,
    TelemetrySink,
    peak_flops_for_accelerator,
    publish_sink_address,
    sink_address,
)
from trainingjob_operator_tpu.obs.logs import (
    ContextTextFormatter,
    JsonFormatter,
    StructuredLogger,
    configure_logging,
    get_logger,
)
from trainingjob_operator_tpu.obs.trace import (
    NOOP_SPAN,
    Span,
    TRACER,
    Tracer,
    current_context,
    current_span,
    group_traces,
    spans_from_jsonl,
    tracer_from_env,
)

__all__ = [
    "GOODPUT",
    "GoodputTracker",
    "TELEMETRY",
    "TelemetryAggregator",
    "TelemetryEmitter",
    "TelemetrySink",
    "peak_flops_for_accelerator",
    "publish_sink_address",
    "sink_address",
    "ContextTextFormatter",
    "JsonFormatter",
    "StructuredLogger",
    "configure_logging",
    "get_logger",
    "NOOP_SPAN",
    "Span",
    "TRACER",
    "Tracer",
    "current_context",
    "current_span",
    "group_traces",
    "spans_from_jsonl",
    "tracer_from_env",
]
