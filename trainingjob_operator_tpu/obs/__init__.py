"""Observability: span tracing, structured logging, goodput, telemetry.

Dependency-free (stdlib only, like ``tools/analyze``).  Process-global
singletons mirror ``utils.metrics.METRICS``:

- ``TRACER``    -- span tracer with a bounded ring of finished traces;
- ``GOODPUT``   -- goodput ledger fed by the status machine;
- ``TELEMETRY`` -- per-step replica telemetry aggregator (throughput, MFU,
  straggler skew, stall watchdog), fed by the runtimes' sinks;
- ``TSDB``      -- in-process time-series store sampling the metrics
  registry into bounded rings (docs/SLO.md);
- ``SLOS``      -- multi-window burn-rate SLO engine over the tsdb;
- ``PROFILER``  -- sampling stack profiler with span attribution;
- ``REQTRACE``  -- per-request lifecycle ledger with TTFT/TPOT attribution
  and a dropped-request audit (docs/SERVING.md);
- structured logging is stateless (``get_logger`` binds context per call).

See docs/OBSERVABILITY.md for the span/metric/event catalogs.
"""

from trainingjob_operator_tpu.obs.goodput import GOODPUT, GoodputTracker
from trainingjob_operator_tpu.obs.telemetry import (
    TELEMETRY,
    TelemetryAggregator,
    TelemetryEmitter,
    TelemetrySink,
    peak_flops_for_accelerator,
    publish_sink_address,
    sink_address,
)
from trainingjob_operator_tpu.obs.logs import (
    ContextTextFormatter,
    JsonFormatter,
    StructuredLogger,
    configure_logging,
    get_logger,
)
from trainingjob_operator_tpu.obs.trace import (
    NOOP_SPAN,
    Span,
    TRACER,
    Tracer,
    current_context,
    current_span,
    group_traces,
    spans_from_jsonl,
    tracer_from_env,
)
from trainingjob_operator_tpu.obs.tsdb import TSDB, TimeSeriesStore
from trainingjob_operator_tpu.obs.slo import (
    FleetSLO,
    SLOEngine,
    SLOSpec,
    SLOS,
    default_slos,
)
from trainingjob_operator_tpu.obs.profiler import PROFILER, SpanProfiler
from trainingjob_operator_tpu.obs.reqtrace import (
    REQTRACE,
    REQUEST_OUTCOMES,
    RequestLedger,
)

__all__ = [
    "GOODPUT",
    "GoodputTracker",
    "TELEMETRY",
    "TelemetryAggregator",
    "TelemetryEmitter",
    "TelemetrySink",
    "peak_flops_for_accelerator",
    "publish_sink_address",
    "sink_address",
    "ContextTextFormatter",
    "JsonFormatter",
    "StructuredLogger",
    "configure_logging",
    "get_logger",
    "NOOP_SPAN",
    "Span",
    "TRACER",
    "Tracer",
    "current_context",
    "current_span",
    "group_traces",
    "spans_from_jsonl",
    "tracer_from_env",
    "TSDB",
    "TimeSeriesStore",
    "FleetSLO",
    "SLOEngine",
    "SLOSpec",
    "SLOS",
    "default_slos",
    "PROFILER",
    "SpanProfiler",
    "REQTRACE",
    "REQUEST_OUTCOMES",
    "RequestLedger",
]
