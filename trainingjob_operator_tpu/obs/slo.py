"""Declarative SLOs evaluated against the in-process tsdb: multi-window
multi-burn-rate alerting for the fleet.

An :class:`SLOSpec` names an objective ("99% of sweeps see event->visible
p99 <= 5s"), the tsdb series it reads (prefix + suffix match, reduced
across label sets per sweep tick), and the alerting policy: two windows
(short + long) whose *burn rate* -- the fraction of bad ticks divided by
the error budget ``1 - target`` -- must BOTH exceed a threshold before a
breach fires.  The two-window shape is the standard SRE construction: the
long window proves the budget is really burning, the short window proves
it is burning *now*, so a breach is neither a blip nor a stale alarm.

Breach/recovery transitions are events, not log lines: the engine calls a
sink wired by the controller (``recorder.event`` with ``SLOBreach`` /
``SLORecovered`` against a synthetic fleet-scoped :class:`FleetSLO`
object) and tells the incident recorder so bundles whose window overlaps
a breach episode carry the breached objective.  ``/debug/slo`` serves the
live verdicts; the fleet harness folds them into ``FleetReport``.

One deliberate asymmetry: quantile-fed SLOs (event->visible p99 etc.) read
*run-cumulative* histogram quantiles, which cannot come back down after a
degradation inside one process lifetime -- those objectives breach and
stay breached (correct: the budget is spent).  Gauge-fed SLOs (goodput
floor) genuinely recover.  docs/SLO.md spells this out.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.core.objects import ObjectMeta
from trainingjob_operator_tpu.obs.incident import INCIDENTS
from trainingjob_operator_tpu.obs.tsdb import TSDB, TimeSeriesStore
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry


class FleetSLO:
    """Synthetic involved object for fleet-scoped SLO events: the breach
    is a property of the fleet, not of any one TrainingJob, and the
    incident tap keys on KIND to keep these out of per-job incident
    rings."""

    KIND = "FleetSLO"

    def __init__(self, name: str):
        self.metadata = ObjectMeta(name=name, namespace="fleet-slo")


@dataclass(frozen=True)
class SLOSpec:
    """One objective.  ``series_prefix``/``series_suffix`` match tsdb ring
    names (labels live between the two, e.g. prefix
    ``trainingjob_event_to_visible_ms`` + suffix ``_p99`` matches every
    ``{kind=...}`` label set); per sweep tick the matched values are
    reduced (max/min/avg) to one number, good iff ``value op threshold``.
    """

    name: str
    objective: str
    series_prefix: str
    series_suffix: str = ""
    reduce: str = "max"          # max | min | avg across matched series
    op: str = "<="               # good when value op threshold
    threshold: float = 0.0
    target: float = 0.99         # objective target; budget = 1 - target
    min_points: int = 4          # ticks required per window for a verdict


def _windows_from_env() -> Tuple[float, float]:
    raw = os.environ.get(constants.SLO_WINDOWS_ENV, "")
    if raw:
        short_raw, _, long_raw = raw.partition(":")
        try:
            short, long = float(short_raw), float(long_raw)
            if 0 < short <= long:
                return short, long
        except ValueError:
            pass
    return 5.0, 15.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def default_slos() -> Tuple[SLOSpec, ...]:
    """The built-in fleet inventory (docs/SLO.md).  Thresholds sized for
    the sim fleet's scale and env-overridable; the degraded smoke arm
    tightens them to provoke a breach deliberately."""
    return (
        SLOSpec(
            name="event_visible_p99",
            objective="create/update visible to the controller: p99 under "
                      "the threshold across every event kind",
            series_prefix="trainingjob_event_to_visible_ms",
            series_suffix="_p99",
            reduce="max", op="<=",
            threshold=_env_float(constants.SLO_EVENT_P99_MS_ENV, 5000.0)),
        SLOSpec(
            name="detect_running_p99",
            objective="restart downtime (detect -> Running again): p99 "
                      "under the threshold across every restart scope",
            series_prefix="trainingjob_restart_downtime_seconds",
            series_suffix="_p99",
            reduce="max", op="<=",
            threshold=_env_float(constants.SLO_RESTART_P99_S_ENV, 60.0)),
        SLOSpec(
            name="goodput_floor",
            objective="mean per-job goodput ratio stays above the floor",
            series_prefix="trainingjob_goodput_ratio",
            reduce="avg", op=">=",
            threshold=_env_float(constants.SLO_GOODPUT_FLOOR_ENV, 0.01)),
        SLOSpec(
            name="serve_token_p99",
            objective="serve-plane p99 token latency under the threshold "
                      "across serving jobs",
            series_prefix="trainingjob_serve_token_latency_ms",
            reduce="max", op="<=",
            threshold=_env_float(constants.SLO_SERVE_P99_MS_ENV, 2000.0)),
        SLOSpec(
            name="ttft_p99",
            objective="request plane time-to-first-token: p99 under the "
                      "threshold across serving jobs",
            series_prefix="trainingjob_request_ttft_ms",
            series_suffix="_p99",
            reduce="max", op="<=",
            threshold=_env_float(constants.SLO_TTFT_P99_MS_ENV, 2000.0)),
    )


class SLOEngine:
    """Evaluates specs against the tsdb on a timer (or manually via
    ``evaluate()``); fires the event sink + incident stamps on breach and
    recovery transitions.  No-op until ``start()``, like the other obs
    planes."""

    def __init__(self, tsdb: Optional[TimeSeriesStore] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 incidents=None):
        self._lock = threading.Lock()
        self._tsdb = tsdb if tsdb is not None else TSDB
        self._metrics = metrics if metrics is not None else METRICS
        self._incidents = incidents if incidents is not None else INCIDENTS
        self._specs: Tuple[SLOSpec, ...] = ()
        self._state: Dict[str, Dict[str, Any]] = {}
        self._sink: Optional[Callable[[str, str, str], None]] = None
        self.short_s, self.long_s = _windows_from_env()
        self.burn_threshold = _env_float(constants.SLO_BURN_ENV, 4.0)
        self.interval = _env_float(constants.SLO_EVAL_ENV, 1.0)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def set_event_sink(self,
                       sink: Optional[Callable[[str, str, str], None]]) -> None:
        """``sink(slo_name, reason, message)``; the controller points this
        at its EventRecorder so breaches surface as kubectl-visible
        events."""
        with self._lock:
            self._sink = sink

    def configure(self, specs: Tuple[SLOSpec, ...]) -> None:
        with self._lock:
            self._specs = tuple(specs)
            self._state = {
                spec.name: {"breached": False, "breaches": 0,
                            "recoveries": 0, "burn_short": 0.0,
                            "burn_long": 0.0, "last": None, "points": 0}
                for spec in self._specs
            }

    # -- evaluation ----------------------------------------------------------

    @staticmethod
    def _reduce(spec: SLOSpec, values: List[float]) -> float:
        if spec.reduce == "min":
            return min(values)
        if spec.reduce == "avg":
            return sum(values) / len(values)
        return max(values)

    @staticmethod
    def _good(spec: SLOSpec, value: float) -> bool:
        if spec.op == ">=":
            return value >= spec.threshold
        return value <= spec.threshold

    def _burn(self, spec: SLOSpec, ticks: List[Tuple[float, float]],
              start: float) -> Tuple[float, int]:
        """(burn rate, tick count) over ticks with t >= start."""
        window = [(t, v) for t, v in ticks if t >= start]
        if not window:
            return 0.0, 0
        bad = sum(1 for _, v in window if not self._good(spec, v))
        budget = max(1.0 - spec.target, 1e-9)
        return (bad / len(window)) / budget, len(window)

    def _ticks(self, spec: SLOSpec, start: float) -> List[Tuple[float, float]]:
        """Per-sweep reduced values for the spec since ``start``.  Sweeps
        stamp one timestamp across all series, so grouping by exact t is
        exact, not fuzzy bucketing."""
        by_tick: Dict[float, List[float]] = {}
        for name in self._tsdb.match(spec.series_prefix, spec.series_suffix):
            for t, v in self._tsdb.window(name, start):
                by_tick.setdefault(t, []).append(v)
        return [(t, self._reduce(spec, vs))
                for t, vs in sorted(by_tick.items())]

    def evaluate(self, now: Optional[float] = None) -> None:
        if now is None:
            now = time.time()
        with self._lock:
            specs, sink = self._specs, self._sink
        fired: List[Tuple[str, str, str]] = []
        for spec in specs:
            ticks = self._ticks(spec, now - self.long_s)
            burn_long, n_long = self._burn(spec, ticks, now - self.long_s)
            burn_short, n_short = self._burn(spec, ticks, now - self.short_s)
            with self._lock:
                st = self._state.get(spec.name)
                if st is None:
                    continue
                st["burn_short"], st["burn_long"] = (round(burn_short, 3),
                                                     round(burn_long, 3))
                st["points"] = n_long
                st["last"] = ticks[-1][1] if ticks else None
                enough = (n_short >= spec.min_points
                          and n_long >= spec.min_points)
                if (not st["breached"] and enough
                        and burn_short >= self.burn_threshold
                        and burn_long >= self.burn_threshold):
                    st["breached"] = True
                    st["breaches"] += 1
                    self._metrics.inc("trainingjob_slo_breaches_total",
                                      slo=spec.name)
                    self._incidents.record_slo_breach(spec.name, now)
                    fired.append((spec.name, constants.SLO_BREACH_REASON,
                                  f"burn {burn_short:.1f}x/{burn_long:.1f}x "
                                  f"over budget ({spec.objective}; "
                                  f"last={st['last']})"))
                elif (st["breached"] and enough and burn_short == 0.0):
                    st["breached"] = False
                    st["recoveries"] += 1
                    self._incidents.record_slo_recovered(spec.name, now)
                    fired.append((spec.name, constants.SLO_RECOVERED_REASON,
                                  f"short-window burn back to 0 "
                                  f"({spec.objective})"))
        if sink is not None:
            for name, reason, message in fired:
                sink(name, reason, message)

    def verdicts(self) -> Dict[str, Any]:
        with self._lock:
            slos = {
                spec.name: dict(self._state.get(spec.name, {}),
                                objective=spec.objective,
                                threshold=spec.threshold, op=spec.op,
                                target=spec.target)
                for spec in self._specs
            }
            return {"windows": {"short_s": self.short_s,
                                "long_s": self.long_s,
                                "burn_threshold": self.burn_threshold},
                    "slos": slos,
                    "breaches_total": sum(s["breaches"] for s in slos.values()
                                          if "breaches" in s)}

    # -- lifecycle -----------------------------------------------------------

    def start(self, interval: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        if interval is not None:
            self.interval = interval
        if not self._specs:
            self.configure(default_slos())
        self._incidents.clear_slo_breaches()
        self._stop.clear()
        for spec in self._specs:
            self._metrics.gauge(
                "trainingjob_slo_burn_rate",
                lambda n=spec.name: self._state.get(n, {}).get("burn_short",
                                                               0.0),
                slo=spec.name)

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                self.evaluate()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="trainingjob-slo")
        self._thread.start()

    def stop(self) -> None:
        th = self._thread
        if th is None:
            return
        self._stop.set()
        th.join(timeout=2.0)
        self._thread = None
        for spec in self._specs:
            self._metrics.remove_gauge("trainingjob_slo_burn_rate",
                                       slo=spec.name)


#: Process-global engine (one per controller shard, like the tsdb it reads).
SLOS = SLOEngine()
