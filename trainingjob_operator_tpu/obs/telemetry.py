"""Replica telemetry plane: per-step push metrics from workloads.

Pod phase says a replica is *alive*; it cannot say the replica is *making
progress* -- the signal elastic schedulers act on (Singularity,
arXiv:2202.07848) and the one the TPU-fleet goodput literature measures
(PAPERS.md).  This module closes that gap with a push channel:

- ``TelemetryEmitter`` (workload side): best-effort newline-delimited JSON
  over TCP.  One record per completed optimizer step::

      {"v": 1, "job": "ns/name", "rtype": "trainer", "rank": 0,
       "step": 12, "ms": 35.2, "tokens": 4096, "loss": 2.31,
       "flops": 1.1e12, "peak_flops": 3.9e14, "ts": 1723...}

  The sink address arrives rendezvous-style in ``TRAININGJOB_TELEMETRY_ADDR``
  (pod.set_env, like the trace context); unset -> every call is a no-op.
  Emission must never block or fail training: short connect timeout, and a
  send failure closes the socket and backs off instead of raising.

- ``TelemetrySink`` (controller side): a threaded line-protocol TCP server
  feeding records into an aggregator.  Started by the runtime (localproc
  binds loopback; the kube stub would bind 0.0.0.0 and advertise a
  reachable address).

- ``TelemetryAggregator``: per-job, per-replica step state.  Derives
  step-time percentiles, tokens/sec, an MFU estimate (model FLOPs per step
  from the workload or env, peak FLOP/s from spec.tpu via the controller),
  cross-replica straggler skew (slowest rank's median step time over the
  median of all ranks' medians), and a step-progress watchdog: a replica
  whose step counter stops advancing for ``stall_factor`` x its median step
  time raises a ``StepStalled`` event through the controller's recorder and
  increments ``trainingjob_steps_stalled_total``.

The sim runtime bypasses the socket and calls ``TELEMETRY.ingest`` directly
(its "workloads" are annotations, not processes); the aggregation, metrics,
and watchdog paths are identical.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.goodput import GOODPUT, GoodputTracker
from trainingjob_operator_tpu.obs.incident import INCIDENTS, IncidentRecorder
from trainingjob_operator_tpu.obs.reqtrace import (
    REQTRACE,
    REQUEST_OUTCOMES,
    RequestLedger,
)
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

#: Step-time histogram bucket upper bounds (milliseconds): sim steps run
#: ~1-50 ms, CPU-test steps ~50-5000 ms, real TPU steps up to minutes.
STEP_TIME_BUCKETS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                        1000.0, 2500.0, 5000.0, 15000.0, 60000.0)

#: Checkpoint-stall histogram bucket upper bounds (milliseconds): the
#: snapshot-donate path stalls the step O(device->host copy), sub-ms to
#: tens of ms; the legacy synchronous handoff pays device sync +
#: serialization setup, hundreds of ms to tens of seconds at 100B scale.
CKPT_STALL_BUCKETS_MS = (0.5, 1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                        1000.0, 5000.0, 30000.0)

#: Time-to-first-token histogram bucket upper bounds (milliseconds): sim
#: synthesis scripts tens of ms, CPU-test decode runs hundreds, a cold
#: queue under load reaches seconds.
REQUEST_TTFT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                           500.0, 1000.0, 2500.0, 5000.0, 10000.0)

#: Per-output-token decode gap buckets (milliseconds): steady-state TPOT
#: sits well under TTFT -- one batched step per token.
REQUEST_TPOT_BUCKETS_MS = (0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0,
                           250.0, 1000.0)

#: Peak dense bf16 FLOP/s per chip by accelerator-type substring, first
#: match wins ("v5-lite" before "v5" would matter if a bare "v5" entry
#: existed; it does not -- v5p and v5e are distinct products).  Sources:
#: public TPU spec sheets; used only for the MFU *estimate* gauge.
PEAK_FLOPS_PER_CHIP = (
    ("v6e", 918e12),
    ("v6-lite", 918e12),
    ("v5p", 459e12),
    ("v5-lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_for_accelerator(accelerator: str) -> float:
    """Per-chip peak FLOP/s for a GKE accelerator string (e.g.
    ``tpu-v5-lite-podslice``); 0.0 when unrecognized (MFU then reads 0 and
    the gauge is simply not registered)."""
    acc = (accelerator or "").lower()
    for marker, flops in PEAK_FLOPS_PER_CHIP:
        if marker in acc:
            return flops
    return 0.0


# -- published sink address (process-global, like the TRACER singleton) ------

_publish_lock = threading.Lock()
_published: Dict[str, Any] = {"addr": "", "owner": None}


def publish_sink_address(addr: str, owner: Any = None) -> None:
    """Make ``addr`` the address pod.set_env injects into new pods.  The
    ``owner`` token lets a stopping sink clear only its own publication
    (a test's second runtime must not be unpublished by the first's stop)."""
    with _publish_lock:
        _published["addr"] = addr
        _published["owner"] = owner


def clear_sink_address(owner: Any = None) -> None:
    with _publish_lock:
        if owner is None or _published["owner"] is owner:
            _published["addr"] = ""
            _published["owner"] = None


def sink_address() -> str:
    with _publish_lock:
        return _published["addr"]


# -- aggregator ---------------------------------------------------------------

class _ReplicaState:
    __slots__ = ("rtype", "rank", "last_step", "last_advance", "steps_seen",
                 "samples", "tokens_rate", "flops_rate", "loss", "stalled",
                 "ckpt_ms", "hbm_bytes")

    def __init__(self, rtype: str, rank: int) -> None:
        self.rtype = rtype
        self.rank = rank
        self.last_step = -1
        self.last_advance = 0.0   # wall time the step counter last moved
        self.steps_seen = 0
        #: recent (ingest_ts, ms, tokens, flops) tuples, newest last.
        self.samples: Deque[Tuple[float, float, float, float]] = deque()
        self.tokens_rate = 0.0
        self.flops_rate = 0.0
        self.loss: Optional[float] = None
        self.stalled = False
        #: Latest reported values; None until the replica ever reports one
        #: (a job without checkpointing / the HBM sampler shows "-" in the
        #: /debug/steps table, not a fake zero).
        self.ckpt_ms: Optional[float] = None
        self.hbm_bytes: Optional[float] = None

    def median_ms(self) -> float:
        return self.quantile_ms(0.5)

    def quantile_ms(self, q: float) -> float:
        if not self.samples:
            return 0.0
        ordered = sorted(s[1] for s in self.samples)
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    def window_rates(self) -> Tuple[float, float]:
        """(tokens/sec, flops/sec) over the sample window.  Rates come from
        the per-step wall times, not ingest timestamps: records may arrive
        in bursts (sim synthesizes several steps per tick) and the ingest
        clock would then overstate the rate unboundedly."""
        if not self.samples:
            return 0.0, 0.0
        ms_total = sum(s[1] for s in self.samples)
        if ms_total <= 0.0:
            return 0.0, 0.0
        tokens = sum(s[2] for s in self.samples)
        flops = sum(s[3] for s in self.samples)
        return tokens * 1000.0 / ms_total, flops * 1000.0 / ms_total


class _JobTelemetry:
    __slots__ = ("replicas", "suspended", "completed", "peak_flops",
                 "gauges", "status_cache", "status_cache_at", "serve")

    def __init__(self) -> None:
        self.replicas: Dict[Tuple[str, int], _ReplicaState] = {}
        self.suspended = False
        self.completed = False
        self.peak_flops = 0.0     # job-level, from spec.tpu (controller)
        self.gauges: List[Tuple[str, Dict[str, str]]] = []
        self.status_cache = ""
        self.status_cache_at = 0.0
        #: Latest serving-plane snapshot (workloads/serve.py emit_serve);
        #: None until the job's first serve record.  Survives
        #: on_interruption on purpose: a Resize drain keeps serve
        #: survivors running, and the scale policy needs continuity.
        self.serve: Optional[Dict[str, float]] = None


class TelemetryAggregator:
    """Thread-safe per-job step-record aggregation + stall watchdog.

    ``stall_factor`` x a replica's median step time (floored at
    ``stall_floor`` seconds, so millisecond-scale sim steps don't page on
    scheduler jitter) without the step counter advancing -> ``StepStalled``.
    The watchdog is suspended across controller-driven interruptions
    (restart/resize drains kill replicas on purpose) and after completion.
    """

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 goodput: Optional[GoodputTracker] = None,
                 stall_factor: float = 8.0, stall_floor: float = 2.0,
                 window: int = 128,
                 incidents: Optional[IncidentRecorder] = None,
                 reqtrace: Optional[RequestLedger] = None):
        self._metrics = metrics or METRICS
        self._goodput = goodput or GOODPUT
        # Deliberately NOT defaulted to the INCIDENTS singleton: private
        # test aggregators must not pollute the process-global flight
        # recorder.  The TELEMETRY singleton below passes it explicitly.
        self._incidents = incidents
        # Same contract for the request ledger (obs/reqtrace.py): only the
        # singleton feeds REQTRACE; the metrics above are observed either
        # way (the ledger no-ops unless its plane was started).
        self._reqtrace = reqtrace
        self.stall_factor = stall_factor
        self.stall_floor = stall_floor
        self.window = window
        #: Seconds the Running-condition status line is cached (bounds
        #: status-write churn; tests set 0 for immediate refresh).
        self.status_refresh_seconds = 5.0
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobTelemetry] = {}
        self._event_sink: Optional[Callable[[str, str, str], None]] = None

    def set_event_sink(self,
                       sink: Optional[Callable[[str, str, str], None]]) -> None:
        """``sink(job_key, reason, message)`` -- the controller points this
        at its EventRecorder so watchdog findings become job events."""
        with self._lock:
            self._event_sink = sink

    def count_malformed(self) -> None:
        self._metrics.inc("trainingjob_telemetry_malformed_total")

    # -- ingest ---------------------------------------------------------------

    def ingest(self, record: Any, now: Optional[float] = None) -> bool:
        """Feed one step record (already-decoded dict).  Returns False (and
        counts ``trainingjob_telemetry_malformed_total``) on garbage -- the
        sink must survive any bytes a confused client writes at it."""
        now = time.time() if now is None else now
        if isinstance(record, dict) and "resume_restore_ms" in record:
            # Resume-span record (workloads/train.py overlapped_restore):
            # no step/ms fields -- detect it BEFORE step validation.  Feeds
            # the incident recorder's restore/compile attribution.
            try:
                job = str(record["job"])
                restore_ms = float(record["resume_restore_ms"])
                compile_ms = float(record.get("resume_compile_ms", 0.0))
                overlapped = bool(record.get("resume_overlapped", False))
                fallback = str(record.get("resume_fallback", ""))
            except (TypeError, KeyError, ValueError):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            if "/" not in job or restore_ms < 0.0 or compile_ms < 0.0:
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            if self._incidents is not None:
                self._incidents.record_resume(job, restore_ms, compile_ms,
                                              overlapped, now=now,
                                              fallback=fallback)
            return True
        if isinstance(record, dict) and "rendezvous_ms" in record:
            # Live re-rendezvous record (workloads/train.py
            # push_rendezvous_record): which fallback-ladder rung the resize
            # took and the per-phase wall spent (docs/ELASTIC.md).  No
            # step/ms fields -- detect it BEFORE step validation, like
            # resume spans.  Feeds the incident recorder's rendezvous
            # attribution and the bundle's ``rung`` stamp.
            try:
                job = str(record["job"])
                total_ms = float(record["rendezvous_ms"])
                rung = str(record.get("rendezvous_rung", ""))
                why = str(record.get("rendezvous_reason", ""))
                raw = record.get("rendezvous_phase_ms") or {}
                phase_ms = {str(p): float(v) for p, v in raw.items()}
            except (TypeError, KeyError, ValueError, AttributeError):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            if ("/" not in job or total_ms < 0.0
                    or rung not in ("live", "checkpoint", "restart_all")):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            if self._incidents is not None:
                self._incidents.record_rendezvous(job, total_ms, rung,
                                                  reason=why,
                                                  phases=phase_ms, now=now)
            return True
        if isinstance(record, dict) and "serve_queue_depth" in record:
            # Serving-plane snapshot (workloads/serve.py): queue depth,
            # occupancy, latency percentiles -- no step/ms fields, so
            # detect it BEFORE step validation, like resume spans.  Feeds
            # the serve gauges, /debug/serve, and the controller's
            # traffic-aware scale policy (pod._maybe_scale_serve).
            try:
                job = str(record["job"])
                snap = {
                    "queue_depth": float(record["serve_queue_depth"]),
                    "active_slots": float(record.get("serve_active_slots", 0)),
                    "slots": float(record.get("serve_slots", 0)),
                    "p50_ms": float(record.get("serve_p50_ms", 0.0)),
                    "p99_ms": float(record.get("serve_p99_ms", 0.0)),
                    "tokens_per_sec": float(
                        record.get("serve_tokens_per_sec", 0.0)),
                    "completed": float(record.get("serve_completed", 0)),
                }
            except (TypeError, KeyError, ValueError):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            if "/" not in job or snap["queue_depth"] < 0.0:
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            snap["at"] = now
            with self._lock:
                jt = self._jobs.get(job)
                if jt is None:
                    jt = self._jobs[job] = _JobTelemetry()
                if jt.completed:
                    return True
                first = jt.serve is None
                jt.serve = snap
                if first:
                    self._register_serve_gauges_locked(job, jt)
            return True
        if isinstance(record, dict) and "request_outcome" in record:
            # Request terminal-state record (workloads/serve.py
            # emit_request, docs/SERVING.md): one per request reaching a
            # terminal outcome, carrying the per-phase wall breakdown and
            # the stream's submitted high-water mark for the dropped-
            # request audit.  No step/ms fields -- detect it BEFORE step
            # validation, like the serve snapshot.
            try:
                job = str(record["job"])
                outcome = str(record["request_outcome"])
                rid = int(record["request_id"])
                epoch = str(record["request_epoch"])
                hwm = int(record.get("submitted_hwm", rid))
                tokens = int(record.get("tokens", 0))
                raw = record.get("phase_ms") or {}
                phase_ms = {str(p): float(v) for p, v in raw.items()}
            except (TypeError, KeyError, ValueError, AttributeError):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            ttft = _as_float(record.get("ttft_ms"))
            tpot = _as_float(record.get("tpot_ms"))
            arrival = _as_float(record.get("arrival"))
            if ("/" not in job or outcome not in REQUEST_OUTCOMES
                    or rid < 0 or not epoch or hwm < rid or tokens < 0
                    or (ttft is not None and ttft < 0.0)
                    or (tpot is not None and tpot < 0.0)
                    or any(v < 0.0 for v in phase_ms.values())):
                self._metrics.inc("trainingjob_telemetry_malformed_total")
                return False
            self._metrics.inc("trainingjob_requests_total",
                              job=job, outcome=outcome)
            if ttft is not None:
                self._metrics.observe("trainingjob_request_ttft_ms", ttft,
                                      buckets=REQUEST_TTFT_BUCKETS_MS,
                                      job=job)
            if tpot is not None:
                self._metrics.observe("trainingjob_request_tpot_ms", tpot,
                                      buckets=REQUEST_TPOT_BUCKETS_MS,
                                      job=job)
            if self._reqtrace is not None:
                self._reqtrace.record(job, {
                    "request_outcome": outcome,
                    "request_id": rid,
                    "request_epoch": epoch,
                    "submitted_hwm": hwm,
                    "ttft_ms": ttft,
                    "tpot_ms": tpot,
                    "tokens": tokens,
                    "arrival": arrival if arrival is not None else now,
                    "phase_ms": phase_ms,
                    "ts": now,
                })
            return True
        try:
            job = str(record["job"])
            rtype = str(record.get("rtype") or "worker").lower()
            rank = int(record.get("rank", 0))
            step = int(record["step"])
            ms = float(record["ms"])
        except (TypeError, KeyError, ValueError):
            self._metrics.inc("trainingjob_telemetry_malformed_total")
            return False
        if "/" not in job or rank < 0 or step < 0 or ms <= 0.0:
            self._metrics.inc("trainingjob_telemetry_malformed_total")
            return False
        tokens = _as_float(record.get("tokens")) or _as_float(
            record.get("examples"))
        flops = _as_float(record.get("flops"))
        peak = _as_float(record.get("peak_flops"))
        loss = _as_float(record.get("loss"))
        ckpt_ms = _as_float(record.get("ckpt_ms"))
        hbm_bytes = _as_float(record.get("hbm_bytes"))

        resumed: List[Tuple[str, str, str]] = []
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                jt = self._jobs[job] = _JobTelemetry()
            if jt.completed:
                return True  # late records from a finished job: drop quietly
            jt.suspended = False  # progress reports re-arm the watchdog
            rs = jt.replicas.get((rtype, rank))
            if rs is None:
                rs = jt.replicas[(rtype, rank)] = _ReplicaState(rtype, rank)
                rs.last_advance = now
                self._register_replica_gauges_locked(job, jt, rtype)
            if step > rs.last_step:
                if rs.stalled:
                    rs.stalled = False
                    resumed.append((
                        job, constants.STEP_RESUMED_REASON,
                        f"replica {rtype}-{rank} resumed at step {step} "
                        f"after stalling at step {rs.last_step}"))
                rs.last_step = step
                rs.last_advance = now
            rs.steps_seen += 1
            rs.samples.append((now, ms, tokens or 0.0, flops or 0.0))
            while len(rs.samples) > self.window:
                rs.samples.popleft()
            rs.tokens_rate, rs.flops_rate = rs.window_rates()
            if loss is not None:
                rs.loss = loss
            if ckpt_ms is not None and ckpt_ms >= 0.0:
                rs.ckpt_ms = ckpt_ms
            if hbm_bytes is not None and hbm_bytes >= 0.0:
                rs.hbm_bytes = hbm_bytes
            if peak and not jt.peak_flops:
                jt.peak_flops = peak  # controller's spec.tpu value wins
            if (flops or jt.peak_flops) and not _has_gauge(
                    jt, "trainingjob_mfu_ratio"):
                self._register_gauge_locked(
                    job, jt, "trainingjob_mfu_ratio",
                    lambda j=job: self.mfu(j) or 0.0, {"job": job})
            is_pacer = (rtype, rank) == self._pacer_locked(jt)
        self._metrics.observe("trainingjob_step_time_ms", ms,
                              buckets=STEP_TIME_BUCKETS_MS, job=job)
        if ckpt_ms is not None and ckpt_ms >= 0.0:
            # Step-visible checkpoint stall (workloads/train.py rides it on
            # the record following each save): near-zero under
            # snapshot-donate, device-sync + serialization setup under the
            # legacy direct handoff.
            self._metrics.observe("trainingjob_checkpoint_stall_ms", ckpt_ms,
                                  buckets=CKPT_STALL_BUCKETS_MS, job=job)
        if is_pacer:
            # One replica feeds goodput: in a JAX SPMD job every process
            # takes the same global step, so summing all ranks would count
            # each productive second N times.
            self._goodput.record_step(job, ms / 1000.0, now=now)
            if ckpt_ms is not None and ckpt_ms >= 0.0:
                self._goodput.record_checkpoint_stall(job, ckpt_ms / 1000.0,
                                                      now=now)
            if self._incidents is not None:
                # Same pacer feeds the flight recorder's step ring; the
                # first post-recovery step amends the provisional bundle.
                self._incidents.record_step(job, step, ms, ckpt_ms=ckpt_ms,
                                            hbm_bytes=hbm_bytes, now=now)
        self._emit(resumed)
        return True

    @staticmethod
    def _pacer_locked(jt: _JobTelemetry) -> Tuple[str, int]:
        """The replica whose records represent the job's global progress:
        rank 0 of the alphabetically-first reporting replica type."""
        return min(jt.replicas)

    def _register_replica_gauges_locked(self, job: str, jt: _JobTelemetry,
                                        rtype: str) -> None:
        if not jt.replicas or len(jt.replicas) == 1:
            # First replica of the job: job-scoped gauges.
            self._register_gauge_locked(
                job, jt, "trainingjob_tokens_per_sec",
                lambda j=job: self.tokens_per_sec(j), {"job": job})
            self._register_gauge_locked(
                job, jt, "trainingjob_stalled_replicas",
                lambda j=job: float(self.stalled_count(j)), {"job": job})
        if not _has_gauge(jt, "trainingjob_straggler_skew", rtype=rtype):
            self._register_gauge_locked(
                job, jt, "trainingjob_straggler_skew",
                lambda j=job, r=rtype: self.straggler_skew(j, r),
                {"job": job, "rtype": rtype})

    def _register_gauge_locked(self, job: str, jt: _JobTelemetry, name: str,
                               fn: Callable[[], float],
                               labels: Dict[str, str]) -> None:
        self._metrics.gauge(name, fn, **labels)
        jt.gauges.append((name, labels))

    def _register_serve_gauges_locked(self, job: str,
                                      jt: _JobTelemetry) -> None:
        """Serving-plane gauges, registered on the job's first serve
        record.  Lazy like the MFU gauge: training-only jobs never show
        zero-valued serve series."""
        def snap_field(j: str, key: str) -> Callable[[], float]:
            def read() -> float:
                s = self.serve_stats(j)
                return float(s[key]) if s else 0.0
            return read

        self._register_gauge_locked(
            job, jt, "trainingjob_serve_queue_depth",
            snap_field(job, "queue_depth"), {"job": job})
        self._register_gauge_locked(
            job, jt, "trainingjob_serve_token_latency_ms",
            snap_field(job, "p99_ms"), {"job": job})
        self._register_gauge_locked(
            job, jt, "trainingjob_serve_tokens_per_sec",
            snap_field(job, "tokens_per_sec"), {"job": job})

        def occupancy(j: str = job) -> float:
            s = self.serve_stats(j)
            if not s or not s.get("slots"):
                return 0.0
            return s["active_slots"] / s["slots"]

        self._register_gauge_locked(
            job, jt, "trainingjob_serve_batch_occupancy",
            occupancy, {"job": job})

    # -- lifecycle hooks (controller/status machine) --------------------------

    def set_peak_flops(self, job: str, flops: float) -> None:
        """Job-level aggregate peak FLOP/s, computed by the controller from
        ``spec.tpu`` topology (chips x per-chip peak); overrides any
        per-record value -- the controller knows the real allocation."""
        if flops <= 0.0:
            return
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                jt = self._jobs[job] = _JobTelemetry()
            jt.peak_flops = flops

    def on_interruption(self, job: str) -> None:
        """A controller-driven drain (restart/resize) started: the replicas
        are being killed on purpose.  Suspend the watchdog and drop replica
        state -- ranks may be renumbered at the new width; the first record
        after recovery re-arms everything."""
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                return
            jt.suspended = True
            jt.replicas.clear()
            jt.status_cache = ""
            jt.status_cache_at = 0.0

    def on_complete(self, job: str) -> None:
        """Terminal phase: freeze -- no more stall events, late records are
        dropped.  Gauges stay scrapeable until ``forget``."""
        with self._lock:
            jt = self._jobs.get(job)
            if jt is not None:
                jt.completed = True

    def forget(self, job: str) -> None:
        """Job object gone: drop state and every gauge registered for it."""
        with self._lock:
            jt = self._jobs.pop(job, None)
            if jt is None:
                return
            for name, labels in jt.gauges:
                self._metrics.remove_gauge(name, **labels)

    # -- watchdog -------------------------------------------------------------

    def check_stalls(self, now: Optional[float] = None) -> List[Tuple[str, str, str]]:
        """Runtime-tick hook: fire ``StepStalled`` for every replica whose
        step counter has not advanced for ``max(stall_factor * median step
        time, stall_floor)`` seconds.  Returns the events it emitted."""
        now = time.time() if now is None else now
        fired: List[Tuple[str, str, str]] = []
        with self._lock:
            for job, jt in self._jobs.items():
                if jt.suspended or jt.completed:
                    continue
                for rs in jt.replicas.values():
                    # Need a believable median before accusing anyone.
                    if rs.stalled or rs.steps_seen < 3:
                        continue
                    median_s = rs.median_ms() / 1000.0
                    threshold = max(self.stall_factor * median_s,
                                    self.stall_floor)
                    age = now - rs.last_advance
                    if age >= threshold:
                        rs.stalled = True
                        self._metrics.inc("trainingjob_steps_stalled_total",
                                          job=job, rtype=rs.rtype)
                        fired.append((
                            job, constants.STEP_STALLED_REASON,
                            f"replica {rs.rtype}-{rs.rank} stuck at step "
                            f"{rs.last_step} for {age:.1f}s (median step "
                            f"{rs.median_ms():.0f} ms, threshold "
                            f"{threshold:.1f}s)"))
        self._emit(fired)
        return fired

    def _emit(self, events: List[Tuple[str, str, str]]) -> None:
        if not events:
            return
        with self._lock:
            sink = self._event_sink
        if sink is None:
            return
        for job, reason, message in events:
            try:
                sink(job, reason, message)
            # analyzer: allow[broad-except]: the sink is controller code
            # (event recorder + enqueue); telemetry ingest must survive it.
            except Exception:
                pass

    # -- queries --------------------------------------------------------------

    def jobs(self) -> List[str]:
        with self._lock:
            return sorted(self._jobs)

    def tokens_per_sec(self, job: str) -> float:
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None or not jt.replicas:
                return 0.0
            return jt.replicas[self._pacer_locked(jt)].tokens_rate

    def mfu(self, job: str) -> Optional[float]:
        """Model FLOPs utilization estimate in [0, 1]; None when either the
        achieved-FLOPs rate or the peak is unknown."""
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None or not jt.replicas or jt.peak_flops <= 0.0:
                return None
            rate = jt.replicas[self._pacer_locked(jt)].flops_rate
            if rate <= 0.0:
                return None
            return min(max(rate / jt.peak_flops, 0.0), 1.0)

    def straggler_skew(self, job: str, rtype: str) -> float:
        """Slowest rank's median step time over the median of all ranks'
        medians for the replica type; 1.0 = perfectly balanced (and for a
        single rank, trivially)."""
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                return 0.0
            medians = sorted(rs.median_ms() for rs in jt.replicas.values()
                             if rs.rtype == rtype and rs.samples)
            if not medians:
                return 0.0
            mid = medians[len(medians) // 2]
            if mid <= 0.0:
                return 0.0
            return medians[-1] / mid

    def serve_stats(self, job: str) -> Optional[Dict[str, float]]:
        """Latest serving snapshot (queue_depth, active_slots, slots,
        p50_ms, p99_ms, tokens_per_sec, completed, at) or None for a job
        that never served.  The scale policy and ``/debug/serve`` read
        this; ``at`` lets callers judge staleness."""
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None or jt.serve is None:
                return None
            return dict(jt.serve)

    def stalled_count(self, job: str) -> int:
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                return 0
            return sum(1 for rs in jt.replicas.values() if rs.stalled)

    def job_table(self, job: str,
                  now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """The live per-replica step table behind ``/debug/steps?job=``;
        None when the job has reported nothing (the endpoint 404s)."""
        now = time.time() if now is None else now
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None:
                return None
            rows = []
            for (rtype, rank), rs in sorted(jt.replicas.items()):
                rows.append({
                    "replica": f"{rtype}-{rank}",
                    "rtype": rtype,
                    "rank": rank,
                    "step": rs.last_step,
                    "median_ms": round(rs.median_ms(), 2),
                    "p90_ms": round(rs.quantile_ms(0.9), 2),
                    "tokens_per_sec": round(rs.tokens_rate, 1),
                    "loss": rs.loss,
                    # None (not 0) when the replica never reported the
                    # field -- jobs without checkpointing or the HBM
                    # sampler must be distinguishable from ones at zero.
                    "ckpt_ms": (round(rs.ckpt_ms, 2)
                                if rs.ckpt_ms is not None else None),
                    "hbm_bytes": rs.hbm_bytes,
                    "last_advance_age_s": round(max(now - rs.last_advance,
                                                    0.0), 2),
                    "stalled": rs.stalled,
                })
            peak = jt.peak_flops
            suspended, completed = jt.suspended, jt.completed
            rtypes = sorted({rt for rt, _ in jt.replicas})
        return {
            "job": job,
            "replicas": rows,
            "tokens_per_sec": round(self.tokens_per_sec(job), 1),
            "mfu": self.mfu(job),
            "peak_flops": peak,
            "straggler_skew": {rt: round(self.straggler_skew(job, rt), 3)
                               for rt in rtypes},
            "suspended": suspended,
            "completed": completed,
        }

    def render_table(self, job: str, now: Optional[float] = None) -> str:
        """Aligned text rendering of ``job_table`` (the telemetry demo and
        ``/debug/steps?format=text``)."""
        table = self.job_table(job, now=now)
        if table is None:
            return f"no telemetry for job {job}\n"
        cols = ("replica", "step", "median_ms", "p90_ms", "tokens_per_sec",
                "ckpt_ms", "hbm_bytes", "last_advance_age_s", "stalled")
        rows = [["-" if r[c] is None else str(r[c]) for c in cols]
                for r in table["replicas"]]
        widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
                  for i, c in enumerate(cols)]
        lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
        for r in rows:
            lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
        mfu = table["mfu"]
        lines.append(f"job={job} tokens/s={table['tokens_per_sec']} "
                     f"mfu={'-' if mfu is None else f'{mfu:.3f}'} "
                     f"skew={table['straggler_skew']}")
        return "\n".join(lines) + "\n"

    def status_line(self, job: str, now: Optional[float] = None) -> str:
        """Short throughput snapshot for the Running condition message, e.g.
        ``step 124, 1.2e+04 tokens/s, mfu 0.41``.  Cached for
        ``status_refresh_seconds`` so the status machine does not rewrite
        the condition on every sync."""
        now = time.time() if now is None else now
        with self._lock:
            jt = self._jobs.get(job)
            if jt is None or not jt.replicas:
                return ""
            if (jt.status_cache
                    and now - jt.status_cache_at < self.status_refresh_seconds):
                return jt.status_cache
            pacer = jt.replicas[self._pacer_locked(jt)]
            step = pacer.last_step
        parts = [f"step {step}"]
        tps = self.tokens_per_sec(job)
        if tps > 0.0:
            parts.append(f"{tps:.3g} tokens/s")
        mfu = self.mfu(job)
        if mfu is not None:
            parts.append(f"mfu {mfu:.2f}")
        stalled = self.stalled_count(job)
        if stalled:
            parts.append(f"{stalled} replica(s) stalled")
        line = ", ".join(parts)
        with self._lock:
            jt = self._jobs.get(job)
            if jt is not None:
                jt.status_cache = line
                jt.status_cache_at = now
        return line


def _as_float(value: Any) -> Optional[float]:
    if value is None:
        return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _has_gauge(jt: _JobTelemetry, name: str, **labels: str) -> bool:
    for gname, glabels in jt.gauges:
        if gname == name and all(glabels.get(k) == v
                                 for k, v in labels.items()):
            return True
    return False


#: Process-global aggregator, mirroring METRICS/TRACER/GOODPUT.  Only the
#: singleton feeds the global incident flight recorder and request ledger.
TELEMETRY = TelemetryAggregator(incidents=INCIDENTS, reqtrace=REQTRACE)


# -- sink (controller side) ---------------------------------------------------

class _LineHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            try:
                record = json.loads(line.decode("utf-8", errors="replace"))
            except ValueError:
                self.server.aggregator.count_malformed()
                continue
            self.server.aggregator.ingest(record)


class _SinkServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class TelemetrySink:
    """Line-protocol TCP server feeding an aggregator.

    Runtimes own the lifecycle: ``start()`` binds (port 0 = ephemeral) and,
    with ``publish=True``, makes the bound address the one ``pod.set_env``
    injects into new pods; ``stop()`` closes the socket and withdraws only
    its own publication.  ``advertise`` overrides the host part of the
    published address (a kube deployment binds 0.0.0.0 but must advertise a
    pod-reachable name).
    """

    def __init__(self, aggregator: Optional[TelemetryAggregator] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise: str = "", publish: bool = True,
                 check_interval: float = 0.0):
        self._aggregator = aggregator or TELEMETRY
        self._host = host
        self._port = port
        self._advertise = advertise
        self._publish = publish
        #: >0 -> run the stall watchdog on a timer thread.  The sim and
        #: localproc runtimes leave this at 0 (their kubelet tick calls
        #: check_stalls); the kube backend has no local tick loop.
        self._check_interval = check_interval
        self._server: Optional[_SinkServer] = None
        self._thread: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        self.address = ""

    def start(self) -> "TelemetrySink":
        server = _SinkServer((self._host, self._port), _LineHandler)
        server.aggregator = self._aggregator
        self._server = server
        host = self._advertise or self._host
        self.address = f"{host}:{server.server_address[1]}"
        self._thread = threading.Thread(target=server.serve_forever,
                                        daemon=True, name="telemetry-sink")
        self._thread.start()
        if self._check_interval > 0.0:
            self._watchdog_stop.clear()
            threading.Thread(target=self._watchdog_loop, daemon=True,
                             name="telemetry-watchdog").start()
        if self._publish:
            publish_sink_address(self.address, owner=self)
        return self

    def _watchdog_loop(self) -> None:
        while not self._watchdog_stop.wait(self._check_interval):
            self._aggregator.check_stalls()

    def stop(self) -> None:
        if self._publish:
            clear_sink_address(owner=self)
        self._watchdog_stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        th = self._thread
        if th is not None:
            th.join(timeout=2.0)
            self._thread = None


# -- emitter (workload side) --------------------------------------------------

class TelemetryEmitter:
    """Best-effort per-step record pusher for the train loop (one thread).

    Enabled only when ``TRAININGJOB_TELEMETRY_ADDR`` and the identity env
    (job namespace/name) are present -- both injected by pod.set_env.  A
    connect/send failure closes the socket and backs off ``retry_seconds``;
    training never blocks on observability.
    """

    CONNECT_TIMEOUT = 0.5

    def __init__(self, units_per_step: float = 0.0,
                 flops_per_step: float = 0.0, unit: str = "tokens",
                 addr: Optional[str] = None, retry_seconds: float = 5.0):
        env = os.environ
        self.addr = env.get(constants.TELEMETRY_ADDR_ENV, "") if addr is None else addr
        ns = env.get(constants.JOB_NAMESPACE_ENV, "")
        name = env.get(constants.JOB_NAME_ENV, "")
        self.job = f"{ns}/{name}" if ns and name else ""
        self.rtype = env.get(constants.REPLICA_NAME_ENV, "worker").lower()
        try:
            self.rank = int(env.get(constants.REPLICA_INDEX_ENV, "0") or "0")
        except ValueError:
            self.rank = 0
        self.units_per_step = units_per_step
        self.unit = unit
        self.flops_per_step = _env_float(constants.MODEL_FLOPS_ENV,
                                         flops_per_step)
        self.peak_flops = _env_float(constants.PEAK_FLOPS_ENV, 0.0)
        self.retry_seconds = retry_seconds
        self._sock: Optional[socket.socket] = None
        self._down_until = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self.addr and self.job)

    def emit(self, step: int, ms: float, loss: Optional[float] = None,
             ckpt_ms: Optional[float] = None,
             hbm_bytes: Optional[float] = None) -> None:
        if not self.enabled or time.monotonic() < self._down_until:
            return
        record: Dict[str, Any] = {
            "v": 1, "job": self.job, "rtype": self.rtype, "rank": self.rank,
            "step": step, "ms": round(ms, 3), "ts": time.time(),
        }
        if self.units_per_step:
            record[self.unit] = self.units_per_step
        if self.flops_per_step:
            record["flops"] = self.flops_per_step
        if self.peak_flops:
            record["peak_flops"] = self.peak_flops
        if loss is not None:
            record["loss"] = loss
        if ckpt_ms is not None:
            record["ckpt_ms"] = round(ckpt_ms, 3)
        if hbm_bytes is not None:
            record["hbm_bytes"] = hbm_bytes
        self._send(record)

    def emit_resume(self, restore_ms: float, compile_ms: float,
                    overlapped: bool, fallback: str = "") -> None:
        """One resume completed (train.overlapped_restore): push the span
        durations so the controller's incident bundle can attribute the
        restore/compile tail of the downtime it already measured.
        ``fallback`` is the structured checkpoint-fallback reason when the
        restore degraded (docs/RECOVERY.md integrity ladder); "" rides the
        happy path and is omitted from the wire record."""
        if not self.enabled or time.monotonic() < self._down_until:
            return
        record: Dict[str, Any] = {
            "v": 1, "job": self.job, "rtype": self.rtype, "rank": self.rank,
            "resume_restore_ms": round(restore_ms, 3),
            "resume_compile_ms": round(compile_ms, 3),
            "resume_overlapped": overlapped, "ts": time.time(),
        }
        if fallback:
            record["resume_fallback"] = fallback
        self._send(record)

    def emit_rendezvous(self, total_ms: float, rung: str, reason: str = "",
                        phase_ms: Optional[Dict[str, float]] = None) -> None:
        """One live re-rendezvous finished or degraded (llama_elastic's
        fallback ladder): push the rung taken and per-phase wall so the
        incident bundle attributes the rendezvous slice of the resize
        window.  Emitted once on success (rung=live) and re-emitted with
        the rung fallen to on degrade -- the latest record wins."""
        if not self.enabled or time.monotonic() < self._down_until:
            return
        record: Dict[str, Any] = {
            "v": 1, "job": self.job, "rtype": self.rtype, "rank": self.rank,
            "rendezvous_ms": round(total_ms, 3), "rendezvous_rung": rung,
            "ts": time.time(),
        }
        if reason:
            record["rendezvous_reason"] = reason
        if phase_ms:
            record["rendezvous_phase_ms"] = {p: round(v, 3)
                                             for p, v in phase_ms.items()}
        self._send(record)

    def emit_serve(self, queue_depth: int, active_slots: int, slots: int,
                   p50_ms: float, p99_ms: float, tokens_per_sec: float,
                   completed: int) -> None:
        """Serving-plane snapshot (workloads/serve.py, every emit_every
        scheduler ticks): queue depth and latency percentiles are the
        signals the controller's traffic-aware scale policy acts on."""
        if not self.enabled or time.monotonic() < self._down_until:
            return
        self._send({
            "v": 1, "job": self.job, "rtype": self.rtype, "rank": self.rank,
            "serve_queue_depth": queue_depth,
            "serve_active_slots": active_slots, "serve_slots": slots,
            "serve_p50_ms": round(p50_ms, 3),
            "serve_p99_ms": round(p99_ms, 3),
            "serve_tokens_per_sec": round(tokens_per_sec, 2),
            "serve_completed": completed, "ts": time.time(),
        })

    def emit_request(self, outcome: str, request_id: int, epoch: str,
                     submitted_hwm: int, *, ttft_ms: Optional[float] = None,
                     tpot_ms: Optional[float] = None, tokens: int = 0,
                     arrival: Optional[float] = None,
                     phase_ms: Optional[Dict[str, float]] = None) -> None:
        """One request reached a terminal state (completed / rejected /
        evicted): push its lifecycle record for the request ledger
        (obs/reqtrace.py).  ``submitted_hwm`` -- the highest id submitted
        so far in this service incarnation's stream -- is what makes the
        dropped-request audit sound: ids above the last terminal record
        are visible to ``reconcile()`` even if this process dies before
        flushing them."""
        if not self.enabled or time.monotonic() < self._down_until:
            return
        record: Dict[str, Any] = {
            "v": 1, "job": self.job, "rtype": self.rtype, "rank": self.rank,
            "request_outcome": outcome, "request_id": request_id,
            "request_epoch": epoch, "submitted_hwm": submitted_hwm,
            "tokens": tokens, "ts": time.time(),
        }
        if ttft_ms is not None:
            record["ttft_ms"] = round(ttft_ms, 3)
        if tpot_ms is not None:
            record["tpot_ms"] = round(tpot_ms, 3)
        if arrival is not None:
            record["arrival"] = arrival
        if phase_ms:
            record["phase_ms"] = {p: round(v, 3)
                                  for p, v in phase_ms.items()}
        self._send(record)

    def _send(self, record: Dict[str, Any]) -> None:
        data = (json.dumps(record, sort_keys=True) + "\n").encode()
        try:
            if self._sock is None:
                host, _, port = self.addr.rpartition(":")
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=self.CONNECT_TIMEOUT)
            self._sock.sendall(data)
        except (OSError, ValueError):
            self.close()
            self._down_until = time.monotonic() + self.retry_seconds

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default
