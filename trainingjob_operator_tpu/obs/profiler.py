"""Sampling stack profiler: where do 230 reconciles/s of CPU go?

PR 12 measured the controller CPU-bound at ~150-230 reconciles/s on one
core but produced only the total; ROADMAP item 3 (controller scale-out)
needs *attribution* before sharding.  This is a dependency-free sampling
profiler in the py-spy shape, run in-process: a daemon thread wakes on a
**seeded, jittered** interval (``random.Random(seed)`` -- deterministic
schedule per seed, and jitter so samples don't alias the controller's own
periodic loops), grabs ``sys._current_frames()``, and for every operator
thread records two views of the same sample:

- the collapsed Python stack (``root;...;leaf``), flamegraph.pl-ready via
  ``/debug/profile?format=collapsed``;
- the **span stack** live on that thread at sample time, joined through
  the tracer's per-thread registry (obs/trace.py ``thread_span_stack``)
  -- so CPU lands on ``sync_job;pods_for_job`` instead of an opaque
  function name, the same vocabulary the traces and incident bundles
  already speak.

Threads parked in stdlib wait primitives (Condition.wait, Queue.get,
selectors) are classified idle and excluded from CPU attribution.  The
profiler measures its own cost (perf_counter around each sweep, reported
as ``overhead_ratio`` of wall time) -- the smoke gate holds it under 5%.
No-op unless started, like every other obs plane.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs import trace
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

#: Thread-name prefixes sampled by default: controller workers/resync/gc,
#: sim + localproc kubelets, generic runtimes, and the sweeper threads of
#: the other obs planes (their cost should be visible, not hidden).
_DEFAULT_PREFIXES = ("trainingjob-", "sim-", "localproc-", "runtime",
                     "metrics-http")

#: A top-of-stack frame from one of these stdlib modules means the thread
#: is parked in a wait primitive, not burning CPU.  ``time.sleep`` is
#: C-level (the top Python frame is the caller) and intentionally NOT
#: classified idle: a reconcile path sleeping inside a span is a real
#: latency cost the span table should show.
_IDLE_BASENAMES = frozenset(("threading.py", "queue.py", "selectors.py",
                             "socket.py", "socketserver.py"))

#: Caps on distinct keys retained (stacks are finite in practice; these
#: only bound a pathological churn of unique stacks).
_MAX_STACKS = 2048
_MAX_SPAN_KEYS = 1024


class SpanProfiler:
    """Continuous sampling profiler with span attribution.

    All mutable state behind ``_lock``; ``report()``/``collapsed()`` are
    safe while sampling runs.
    """

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 interval_ms: Optional[float] = None,
                 seed: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics = metrics if metrics is not None else METRICS
        raw = os.environ.get(constants.PROFILE_INTERVAL_MS_ENV, "")
        try:
            self.interval_ms = (interval_ms if interval_ms is not None
                                else (float(raw) if raw else 10.0))
        except ValueError:
            self.interval_ms = 10.0
        seed_raw = os.environ.get(constants.PROFILE_SEED_ENV, "")
        self.seed = (seed if seed is not None
                     else (int(seed_raw) if seed_raw.isdigit() else 0))
        self._extra_prefixes: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started_at: Optional[float] = None
        self._wall = 0.0
        self._sample_cpu = 0.0
        self._samples_total = 0
        self._idle = 0
        self._busy = 0
        self._worker_busy = 0
        self._worker_attr = 0
        self._span_counts: Dict[Tuple[str, ...], int] = {}
        self._stack_counts: Dict[str, int] = {}

    def note_thread_prefix(self, prefix: str) -> None:
        """Register an extra thread-name prefix of interest (runtimes with
        custom ``thread_name``s call this so their kubelet threads are
        sampled without the profiler hard-coding every runtime)."""
        if not prefix:
            return
        with self._lock:
            if len(self._extra_prefixes) < 64:
                self._extra_prefixes.add(prefix)

    # -- sampling ------------------------------------------------------------

    @staticmethod
    def _is_idle(frame) -> bool:
        return (os.path.basename(frame.f_code.co_filename)
                in _IDLE_BASENAMES)

    def _sample_once(self) -> int:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        with self._lock:
            prefixes = _DEFAULT_PREFIXES + tuple(self._extra_prefixes)
        sampled = 0
        results: List[Tuple[str, bool, str, Tuple[str, ...]]] = []
        for ident, frame in frames.items():
            name = names.get(ident, "")
            if ident == me or not name.startswith(prefixes):
                continue
            sampled += 1
            idle = self._is_idle(frame)
            funcs: List[str] = []
            f = frame
            while f is not None and len(funcs) < 48:
                funcs.append(f.f_code.co_name)
                f = f.f_back
            funcs.reverse()
            spans = trace.thread_span_stack(ident)
            results.append((name, idle, ";".join(funcs), spans))
        with self._lock:
            for name, idle, stack, spans in results:
                self._samples_total += 1
                if idle:
                    self._idle += 1
                    continue
                self._busy += 1
                if len(self._stack_counts) < _MAX_STACKS or stack in self._stack_counts:
                    self._stack_counts[stack] = (
                        self._stack_counts.get(stack, 0) + 1)
                key = spans if spans else ("<no-span>",)
                if len(self._span_counts) < _MAX_SPAN_KEYS or key in self._span_counts:
                    self._span_counts[key] = self._span_counts.get(key, 0) + 1
                if name.startswith("trainingjob-worker"):
                    self._worker_busy += 1
                    if spans and spans[0] == "sync_job":
                        self._worker_attr += 1
        return sampled

    # -- reporting -----------------------------------------------------------

    def _wall_seconds(self) -> float:
        wall = self._wall
        if self._started_at is not None:
            wall += time.monotonic() - self._started_at
        return wall

    def overhead_ratio(self) -> float:
        with self._lock:
            wall = self._wall_seconds()
            return (self._sample_cpu / wall) if wall > 0 else 0.0

    def report(self, top: int = 20) -> Dict[str, Any]:
        """Per-span-stack CPU% table plus the numbers the smoke gates on:
        worker span-attribution ratio and profiler overhead."""
        with self._lock:
            busy = self._busy
            rows = sorted(self._span_counts.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:top]
            table = [{"spans": ";".join(key), "samples": n,
                      "cpu_pct": round(100.0 * n / busy, 1) if busy else 0.0}
                     for key, n in rows]
            wall = self._wall_seconds()
            attr = (self._worker_attr / self._worker_busy
                    if self._worker_busy else None)
            return {
                "running": self._thread is not None,
                "interval_ms": self.interval_ms,
                "seed": self.seed,
                "wall_seconds": round(wall, 3),
                "samples_total": self._samples_total,
                "busy_samples": busy,
                "idle_samples": self._idle,
                "overhead_ratio": round(
                    (self._sample_cpu / wall) if wall > 0 else 0.0, 5),
                "span_attribution": {
                    "worker_busy": self._worker_busy,
                    "worker_attributed": self._worker_attr,
                    "ratio": round(attr, 4) if attr is not None else None,
                },
                "top": table,
            }

    def collapsed(self) -> str:
        """flamegraph.pl input: ``func;func;func count`` per line."""
        with self._lock:
            rows = sorted(self._stack_counts.items(),
                          key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {n}" for stack, n in rows) + "\n"

    # -- lifecycle -----------------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self._wall = 0.0
            self._sample_cpu = 0.0
            self._samples_total = 0
            self._idle = 0
            self._busy = 0
            self._worker_busy = 0
            self._worker_attr = 0
            self._span_counts.clear()
            self._stack_counts.clear()

    def start(self, interval_ms: Optional[float] = None) -> None:
        """Spawn the daemon sampler; idempotent while running.  Turns the
        tracer's per-thread span registry on for the duration."""
        if self._thread is not None:
            return
        if interval_ms is not None:
            self.interval_ms = interval_ms
        trace.enable_span_registry()
        self._stop.clear()
        with self._lock:
            self._started_at = time.monotonic()
        self._metrics.gauge("trainingjob_profiler_overhead_ratio",
                            self.overhead_ratio)
        rng = random.Random(self.seed)
        base = self.interval_ms / 1000.0

        def _loop() -> None:
            while True:
                # 0.5x..1.5x the base interval: seeded jitter decorrelates
                # the sampler from periodic controller loops.
                if self._stop.wait(base * (0.5 + rng.random())):
                    return
                t0 = time.perf_counter()
                sampled = self._sample_once()
                with self._lock:
                    self._sample_cpu += time.perf_counter() - t0
                if sampled:
                    self._metrics.inc("trainingjob_profiler_samples_total",
                                      float(sampled))

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="trainingjob-profiler")
        self._thread.start()

    def stop(self) -> None:
        th = self._thread
        if th is None:
            return
        self._stop.set()
        th.join(timeout=2.0)
        self._thread = None
        with self._lock:
            if self._started_at is not None:
                self._wall += time.monotonic() - self._started_at
                self._started_at = None
        trace.disable_span_registry()
        self._metrics.remove_gauge("trainingjob_profiler_overhead_ratio")


#: Process-global profiler (samples this process's own threads).
PROFILER = SpanProfiler()
