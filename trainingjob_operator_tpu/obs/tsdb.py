"""In-process time-series store: fixed-interval ring buffers over the
metrics registry.

Every latency gate in the repo today is a point-in-time percentile computed
after a run; nothing retains *history* inside the process.  This store is
the missing substrate: a daemon sweeper snapshots the registry
(``MetricsRegistry.typed_snapshot``) on a fixed cadence and appends one
point per series into a bounded ring --

- **counters** are deltaified (per-interval rate material, not the
  cumulative total); a counter that went *backwards* (process restart,
  registry swap) clamps the delta at zero instead of recording a huge
  negative spike;
- **gauges** are sampled as-is;
- **histograms** materialize count/sum (deltaified like counters) and
  max/p50/p99 (sampled) as ``<key>_<stat>`` series.

Retention is bounded twice: per-series by the ring length (old points fall
off a full ring) and across series by a cardinality cap -- a new label set
past the cap is *rejected and counted* (``trainingjob_tsdb_series_dropped_
total``, incremented once per unique rejected name so the drop counter
cannot feed back into its own cardinality), never silently dropped.

The burn-rate engine (obs/slo.py) evaluates windows against these rings;
``/debug/timeseries`` serves them (JSON + a ``?format=sparkline`` text
view).  Like GOODPUT/TELEMETRY/INCIDENTS, the store is a no-op unless
started: no thread, no sampling, zero overhead on the hot path.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

#: Deltaified histogram stats (monotone like counters); the rest are
#: point-in-time and sampled directly.
_HIST_DELTA_STATS = ("count", "sum")
_HIST_SAMPLE_STATS = ("max", "p50", "p99")

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw.isdigit() else default


class TimeSeriesStore:
    """Bounded per-series rings fed by registry sweeps.

    All state behind ``_lock``; ``sample()`` may be driven manually (tests,
    end-of-run flushes) or by the daemon sweeper ``start()`` spawns.
    """

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 interval: Optional[float] = None,
                 points: Optional[int] = None,
                 max_series: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics = metrics if metrics is not None else METRICS
        self.interval = interval if interval is not None else _env_float(
            constants.TSDB_INTERVAL_ENV, 0.5)
        self.points = points if points is not None else _env_int(
            constants.TSDB_POINTS_ENV, 240)
        self.max_series = max_series if max_series is not None else _env_int(
            constants.TSDB_MAX_SERIES_ENV, 2048)
        self._series: Dict[str, Deque[Tuple[float, float]]] = {}
        self._last_counters: Dict[str, float] = {}
        self._rejected: set = set()
        self.samples_total = 0
        self.dropped_series = 0
        self.last_sample_ts: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- ingestion -----------------------------------------------------------

    def _put_locked(self, key: str, now: float, value: float) -> None:
        ring = self._series.get(key)
        if ring is None:
            if len(self._series) >= self.max_series:
                # Count each unique rejected name exactly once: the drop
                # counter itself becomes a registry series next sweep, and
                # re-counting it every interval would make the rejection
                # path feed its own cardinality pressure forever.
                if key not in self._rejected:
                    # Bound the rejection memory too; past it we still
                    # drop, just without per-name dedup of the count.
                    if len(self._rejected) < 4 * self.max_series:
                        self._rejected.add(key)
                    self.dropped_series += 1
                    self._metrics.inc("trainingjob_tsdb_series_dropped_total")
                return
            ring = self._series[key] = deque(maxlen=self.points)
        ring.append((now, value))

    def _delta_locked(self, key: str, now: float, value: float) -> None:
        prev = self._last_counters.get(key)
        self._last_counters[key] = value
        if prev is None:
            # First sighting: the cumulative total is history we did not
            # watch accrue, not one interval's worth -- start at zero.
            self._put_locked(key, now, 0.0)
            return
        self._put_locked(key, now, max(value - prev, 0.0))

    def sample(self, now: Optional[float] = None) -> None:
        """One sweep: snapshot the registry, append one point per series.

        A single timestamp is stamped on every point of the sweep so the
        SLO engine can reduce *across* series per tick without fuzzy
        time-alignment.
        """
        snap = self._metrics.typed_snapshot()
        if now is None:
            now = time.time()
        with self._lock:
            self.samples_total += 1
            self.last_sample_ts = now
            for key, value in snap["counters"].items():
                self._delta_locked(key, now, value)
            for key, value in snap["gauges"].items():
                self._put_locked(key, now, value)
            for key, stats in snap["hists"].items():
                for stat in _HIST_DELTA_STATS:
                    self._delta_locked(f"{key}_{stat}", now, stats[stat])
                for stat in _HIST_SAMPLE_STATS:
                    self._put_locked(f"{key}_{stat}", now, stats[stat])

    # -- queries -------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> Optional[List[Tuple[float, float]]]:
        """All retained points of one ring, oldest first; None if unknown."""
        with self._lock:
            ring = self._series.get(name)
            return list(ring) if ring is not None else None

    def window(self, name: str, start: float,
               end: Optional[float] = None) -> List[Tuple[float, float]]:
        """Points of ``name`` with start <= t (<= end); empty if unknown."""
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                return []
            return [(t, v) for t, v in ring
                    if t >= start and (end is None or t <= end)]

    def match(self, prefix: str, suffix: str = "") -> List[str]:
        """Series names with the given name prefix + suffix (the SLO
        spec's matching primitive: label sets live between the two)."""
        with self._lock:
            return sorted(k for k in self._series
                          if k.startswith(prefix) and k.endswith(suffix))

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            series = {k: {"n": len(ring), "last": ring[-1][1]}
                      for k, ring in sorted(self._series.items())}
            return {"interval_s": self.interval, "points": self.points,
                    "max_series": self.max_series,
                    "series_count": len(series),
                    "samples_total": self.samples_total,
                    "dropped_series": self.dropped_series,
                    "last_sample_ts": self.last_sample_ts,
                    "series": series}

    def render_sparklines(self, names: Optional[List[str]] = None,
                          width: int = 60) -> str:
        """One line per ring: name, min..max, and the last ``width`` points
        scaled into unicode block characters."""
        if names is None:
            names = self.names()
        lines: List[str] = []
        for name in names:
            points = self.series(name)
            if not points:
                continue
            values = [v for _, v in points[-width:]]
            lo, hi = min(values), max(values)
            if hi > lo:
                chars = "".join(
                    _SPARK_BLOCKS[min(int((v - lo) / (hi - lo)
                                          * len(_SPARK_BLOCKS)),
                                      len(_SPARK_BLOCKS) - 1)]
                    for v in values)
            else:
                chars = _SPARK_BLOCKS[3] * len(values)
            lines.append(f"{name}  [{lo:g}..{hi:g}]  {chars}")
        return "\n".join(lines) + "\n"

    # -- lifecycle -----------------------------------------------------------

    def reset(self) -> None:
        """Drop all rings and counters (a fresh harness run starts clean)."""
        with self._lock:
            self._series.clear()
            self._last_counters.clear()
            self._rejected.clear()
            self.samples_total = 0
            self.dropped_series = 0
            self.last_sample_ts = None

    def start(self, interval: Optional[float] = None) -> None:
        """Spawn the daemon sweeper; idempotent while running."""
        if self._thread is not None:
            return
        if interval is not None:
            self.interval = interval
        self._stop.clear()
        self._metrics.gauge("trainingjob_tsdb_series",
                            lambda: float(len(self._series)))

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                self.sample()
                self._metrics.inc("trainingjob_tsdb_samples_total")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="trainingjob-tsdb")
        self._thread.start()

    def stop(self) -> None:
        th = self._thread
        if th is None:
            return
        self._stop.set()
        th.join(timeout=2.0)
        self._thread = None
        self._metrics.remove_gauge("trainingjob_tsdb_series")


#: Process-global store (one per controller shard, like METRICS itself).
TSDB = TimeSeriesStore()
