"""Goodput accounting: productive step time vs. restart/rendezvous overhead.

The TPU-training literature (PAPERS.md: Goodput-style accounting) measures a
job not by "did it finish" but by what fraction of its wall clock went into
productive training versus scheduling, restarts, and re-rendezvous.  The
controller is the one component that sees every transition, so goodput is
derived here from the condition trail the status machine already maintains:

- time in phase Running counts as productive;
- an interruption (restart drain, elastic resize) opens a downtime window
  attributed to its restart scope; the next transition back to Running
  closes it into ``trainingjob_restart_downtime_seconds{scope=...}``;
- the first Running transition observes
  ``trainingjob_time_to_first_step_seconds`` (a controller-side proxy: pods
  running, not the literal first optimizer step -- the workload-side step
  spans refine it when tracing is enabled);
- completion registers ``trainingjob_goodput_ratio{job=...}`` = productive
  seconds / wall seconds, clamped to [0, 1].

All methods are idempotent per state transition: the status machine may
re-enter the same branch on consecutive syncs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

#: Downtime-histogram buckets: restarts span ~100 ms (sim) to minutes
#: (full-slice reschedule + compile).
DOWNTIME_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0)


class _JobState:
    __slots__ = ("first_seen", "running_since", "productive",
                 "downtime_since", "downtime_scope", "first_running",
                 "completed", "step_productive", "steps_seen",
                 "ckpt_stall", "ckpt_stalls_seen", "downtime_total")

    def __init__(self) -> None:
        self.first_seen: Optional[float] = None
        self.running_since: Optional[float] = None
        self.productive = 0.0
        self.downtime_since: Optional[float] = None
        self.downtime_scope = ""
        self.first_running = False
        self.completed = False
        # Step-fed ledger (obs/telemetry.py): per-step wall time pushed by
        # the workload's pacer replica.  When any step was seen, it replaces
        # the Running-window approximation -- "productive" then means steps
        # actually completed, not time spent in phase Running.
        self.step_productive = 0.0
        self.steps_seen = 0
        # Step-visible checkpoint-stall ledger (pacer rank): wall time the
        # step loop spent handing checkpoints off -- inside step time, so
        # NOT subtracted from productive; tracked so save-pipeline overhead
        # is attributable per job.
        self.ckpt_stall = 0.0
        self.ckpt_stalls_seen = 0
        # Closed downtime-window sum: the ledger the incident recorder's
        # per-phase attribution must reconcile against (tested in
        # tests/test_incident.py).
        self.downtime_total = 0.0


class GoodputTracker:
    def __init__(self, metrics: Optional[MetricsRegistry] = None):
        self._metrics = metrics or METRICS
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobState] = {}

    def _state_locked(self, key: str) -> _JobState:
        st = self._jobs.get(key)
        if st is None:
            st = self._jobs[key] = _JobState()
        return st

    # -- transition hooks (called by the status machine / controller) --------

    def on_running(self, key: str, now: Optional[float] = None,
                   start_time: Optional[float] = None) -> None:
        """The job transitioned (back) to Running: close any open downtime
        window, observe time-to-first-step once, start accruing productive
        time."""
        now = time.time() if now is None else now
        with self._lock:
            st = self._state_locked(key)
            if st.completed:
                return
            if st.first_seen is None:
                st.first_seen = start_time if start_time is not None else now
            if st.downtime_since is not None:
                window = max(now - st.downtime_since, 0.0)
                self._metrics.observe(
                    "trainingjob_restart_downtime_seconds",
                    window,
                    buckets=DOWNTIME_BUCKETS,
                    scope=st.downtime_scope or "unknown")
                st.downtime_total += window
                st.downtime_since = None
                st.downtime_scope = ""
            if not st.first_running:
                st.first_running = True
                self._metrics.observe(
                    "trainingjob_time_to_first_step_seconds",
                    max(now - st.first_seen, 0.0),
                    buckets=DOWNTIME_BUCKETS)
            if st.running_since is None:
                st.running_since = now

    def on_interruption(self, key: str, scope: str,
                        now: Optional[float] = None) -> None:
        """A restart/resize drain started: stop accruing productive time and
        open a downtime window attributed to ``scope`` (a RestartScope value
        or ``"scale"``)."""
        now = time.time() if now is None else now
        with self._lock:
            st = self._state_locked(key)
            if st.completed:
                return
            if st.first_seen is None:
                st.first_seen = now
            if st.running_since is not None:
                st.productive += max(now - st.running_since, 0.0)
                st.running_since = None
            if st.downtime_since is None:
                st.downtime_since = now
                st.downtime_scope = scope

    def record_step(self, key: str, seconds: float,
                    now: Optional[float] = None) -> None:
        """One completed optimizer step took ``seconds`` of wall time
        (pushed from replica telemetry, pacer rank only).  Refines the
        ledger from condition-transition granularity to per-step goodput:
        a job whose pods sit Running but stuck contributes nothing."""
        if seconds <= 0.0:
            return
        now = time.time() if now is None else now
        with self._lock:
            st = self._state_locked(key)
            if st.completed:
                return
            if st.first_seen is None:
                st.first_seen = now
            st.step_productive += seconds
            st.steps_seen += 1

    def record_checkpoint_stall(self, key: str, seconds: float,
                                now: Optional[float] = None) -> None:
        """One checkpoint save stalled the step loop for ``seconds`` (pushed
        from replica telemetry, pacer rank only; obs/telemetry.py also
        observes it as ``trainingjob_checkpoint_stall_ms``).  Accumulated so
        the save pipeline's step-loop tax is attributable per job -- the
        number the snapshot-donate path (workloads/train.py) drives toward
        the device->host copy floor."""
        if seconds < 0.0:
            return
        now = time.time() if now is None else now
        with self._lock:
            st = self._state_locked(key)
            if st.completed:
                return
            if st.first_seen is None:
                st.first_seen = now
            st.ckpt_stall += seconds
            st.ckpt_stalls_seen += 1

    def checkpoint_stall_seconds(self, key: str) -> float:
        """Accumulated step-visible checkpoint stall (0.0 when none seen)."""
        with self._lock:
            st = self._jobs.get(key)
            return st.ckpt_stall if st is not None else 0.0

    def downtime_seconds(self, key: str) -> float:
        """Sum of CLOSED downtime windows (0.0 when none).  The incident
        recorder's control windows share the same open/close timestamps
        (controller passes one ``now`` to both), so a bundle's
        ``control_downtime_ms`` reconciles against this exactly."""
        with self._lock:
            st = self._jobs.get(key)
            return st.downtime_total if st is not None else 0.0

    @staticmethod
    def _productive_locked(st: _JobState) -> float:
        """Step-fed ledger when populated, Running-window sum otherwise
        (callers fold any open running window into ``st.productive``
        first)."""
        return st.step_productive if st.steps_seen else st.productive

    def on_complete(self, key: str, now: Optional[float] = None) -> None:
        """The job reached a terminal phase: freeze the ledger and publish
        ``trainingjob_goodput_ratio{job=...}``.  Idempotent -- the status
        machine revisits terminal branches on later syncs."""
        now = time.time() if now is None else now
        with self._lock:
            st = self._jobs.get(key)
            if st is None or st.completed:
                return
            st.completed = True
            if st.running_since is not None:
                st.productive += max(now - st.running_since, 0.0)
                st.running_since = None
            if st.first_seen is None:
                return  # never observed a lifecycle; nothing to report
            productive = self._productive_locked(st)
            wall = now - st.first_seen
            if wall <= 0.0:
                ratio = 1.0 if productive > 0.0 else 0.0
            else:
                ratio = min(max(productive / wall, 0.0), 1.0)
            # A pull-gauge closed over the final value: survives until the
            # job is forgotten, so a completed job's ratio stays scrapeable.
            self._metrics.gauge("trainingjob_goodput_ratio",
                                lambda r=ratio: r, job=key)

    def forget(self, key: str) -> None:
        """The job object is gone (deleted/GC'd): drop state and the gauge."""
        with self._lock:
            self._jobs.pop(key, None)
            self._metrics.remove_gauge("trainingjob_goodput_ratio", job=key)

    def ratio(self, key: str) -> Optional[float]:
        """Live or final goodput ratio for tests/debugging."""
        snap = self._metrics.snapshot()
        val = snap.get(f'trainingjob_goodput_ratio{{job="{key}"}}')
        if val is not None:
            return val
        now = time.time()
        with self._lock:
            st = self._jobs.get(key)
            if st is None or st.first_seen is None:
                return None
            if st.steps_seen:
                productive = st.step_productive
            else:
                productive = st.productive
                if st.running_since is not None:
                    productive += max(now - st.running_since, 0.0)
            wall = now - st.first_seen
            return min(max(productive / wall, 0.0), 1.0) if wall > 0 else None


#: Process-global tracker, mirroring METRICS/TRACER.
GOODPUT = GoodputTracker()
