"""Structured logging: stdlib records enriched with trace + job identity.

The reference logs free text through klog; correlating "which reconcile
produced this line" means grepping timestamps.  Production operators
(controller-runtime's zap integration) bind a per-reconcile context to every
record instead.  ``StructuredLogger`` is that adapter for stdlib logging:
each record carries ``trace_id`` (read live from the current span at emit
time), plus any statically-bound fields (``job="ns/name"``, ``rtype``).

Formatting is opt-in: the default keeps the existing human text format with
a ``[trace=... job=...]`` suffix; ``JsonFormatter`` renders one JSON object
per line for log pipelines.  Neither changes what callers write.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict

from trainingjob_operator_tpu.obs.trace import current_span

#: Record attributes the formatters surface (beyond the stdlib ones).
CONTEXT_FIELDS = ("trace_id", "span_id", "job", "rtype")


class StructuredLogger(logging.LoggerAdapter):
    """Adapter binding static context fields and injecting the live trace id.

    ``get_logger("trainingjob.pod", job="default/j1", rtype="trainer")``
    returns an adapter whose every record carries those fields plus the
    ``trace_id``/``span_id`` of whatever span encloses the emit call --
    nesting order, not binding order, decides the trace.
    """

    def __init__(self, logger: logging.Logger, **fields: Any):
        super().__init__(logger, fields)

    def bind(self, **fields: Any) -> "StructuredLogger":
        merged = dict(self.extra or {})
        merged.update(fields)
        return StructuredLogger(self.logger, **merged)

    def process(self, msg, kwargs):
        extra = dict(self.extra or {})
        extra.update(kwargs.get("extra") or {})
        span = current_span()
        if span is not None:
            extra.setdefault("trace_id", span.trace_id)
            extra.setdefault("span_id", span.span_id)
        kwargs["extra"] = extra
        return msg, kwargs


def get_logger(name: str, **fields: Any) -> StructuredLogger:
    return StructuredLogger(logging.getLogger(name), **fields)


class JsonFormatter(logging.Formatter):
    """One JSON object per record: ts/level/logger/message + context fields
    + formatted exception.  Keys are sorted so lines diff cleanly."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for field in CONTEXT_FIELDS:
            value = getattr(record, field, None)
            if value is not None:
                out[field] = value
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, sort_keys=True, default=str)


class ContextTextFormatter(logging.Formatter):
    """Human text with a bracketed context suffix when any field is bound."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        parts = [f"{field}={getattr(record, field)}"
                 for field in CONTEXT_FIELDS
                 if getattr(record, field, None) is not None]
        return f"{base} [{' '.join(parts)}]" if parts else base


def configure_logging(json_output: bool = False,
                      level: int = logging.INFO,
                      stream=None) -> logging.Handler:
    """Install one handler on the root logger (cmd/main.py entry point).

    Returns the handler so callers (tests) can remove it again.
    """
    handler = logging.StreamHandler(stream)
    if json_output:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(ContextTextFormatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(level)
    return handler
