"""Orphan/zombie pod garbage collector.

Reference: pkg/controller/garbage_collection.go -- periodic sweep deleting
(a) group-labeled pods whose deletion timestamp has expired (stuck
terminating), and (b) orphans whose owning job no longer exists, with a
node-health check so pods on temporarily-unready nodes are not nuked while
their kubelet is unreachable (garbage_collection.go:36-106).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.client.tracker import NotFoundError
from trainingjob_operator_tpu.core.objects import Pod

log = logging.getLogger("trainingjob.gc")


class GarbageCollector:
    def __init__(self, clientset: Any, trainingjob_lister: Any):
        self._cs = clientset
        self._job_lister = trainingjob_lister
        self._stop = threading.Event()

    def run(self, interval: float) -> None:
        """Reference: CleanOrphans (garbage_collection.go:28-34); interval is
        10 min in the reference (controller.go:204)."""
        while not self._stop.wait(interval):
            self.clean_garbage_pods()

    def stop(self) -> None:
        self._stop.set()

    def clean_garbage_pods(self) -> None:
        """Reference: CleanGarbagePods (garbage_collection.go:36-76)."""
        for pod in self._cs.pods.list():
            if pod.metadata.labels.get(constants.GROUP_NAME_LABEL) != constants.GROUP_NAME:
                continue

            dt = pod.metadata.deletion_timestamp
            if dt is not None and dt < time.time():
                log.warning("garbage pod %s: terminated expired", pod.name)
                self._delete_pod(pod.namespace, pod.name)
                continue

            ref = pod.metadata.controller_of()
            if ref is None or ref.kind != constants.KIND:
                continue
            if self._job_lister.try_get(pod.metadata.namespace, ref.name) is not None:
                continue
            # Owner is gone.  If the pod is terminating within its grace and
            # its node is healthy, let the kubelet finish; otherwise collect.
            if dt is not None and dt > time.time() and self._check_node(pod):
                continue
            log.info("orphan pod %s (owner %s gone)", pod.name, ref.name)
            self._delete_pod(pod.namespace, pod.name)

    def _delete_pod(self, namespace: str, name: str) -> None:
        """Force delete, grace 0 (garbage_collection.go:78-89)."""
        try:
            self._cs.pods.delete(namespace, name, grace_period=0)
        except NotFoundError:
            pass
        except Exception:
            log.exception("delete pod %s/%s failed", namespace, name)

    def _check_node(self, pod: Pod) -> bool:
        """True when the pod's node is Ready or unknown
        (garbage_collection.go:91-106)."""
        if not pod.spec.node_name:
            return True
        try:
            node = self._cs.nodes.get_node(pod.spec.node_name)
        except NotFoundError:
            return False
        # analyzer: allow[broad-except]: transient apiserver error -> treat
        # the node as healthy; GC must never delete pods on a flaky read.
        except Exception:
            return True
        return node.is_ready()
