"""Per-job pod-phase index maintained from informer deltas.

At fleet scale the controller cannot afford to re-derive replica counters by
walking a job's full pod list on every sync if the lookup itself costs an
O(cluster) relist -- and it doubly cannot afford the O(all-pods) scans the
gauges and the resync loop used to do.  This index is the O(changed) answer:
every pod informer delta updates one record (``observe``/``observe_delete``
are O(1)), and a status recomputation reads the job's compact record set
instead of deepcopied Pod objects.

Consistency model: records are written by the informer dispatch thread (the
same commit-ordered stream the informer cache sees), so a sync racing a
just-delivered event may read counters one event stale -- but that event's
handler re-enqueues the job, so the next sync converges.  That is exactly the
eventual-consistency contract reconciles already live under.  As
belt-and-braces, ``StatusManager.update_status`` only trusts the index when
its population for the (job, group, width) agrees with the claimed-pod
snapshot, falling back to the list recount otherwise.

Records are keyed by the pod's controller owner reference (name + uid), so a
deleted-and-recreated job with the same name never inherits counts from the
old incarnation's lingering pods.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import ReplicaStatus
from trainingjob_operator_tpu.core.objects import Pod, PodPhase


class _PodRecord:
    __slots__ = ("rtype", "index", "phase", "has_node", "owner_uid")

    def __init__(self, rtype: str, index: Optional[int], phase: str,
                 has_node: bool, owner_uid: str):
        self.rtype = rtype
        self.index = index
        self.phase = phase
        self.has_node = has_node
        self.owner_uid = owner_uid


def _owner_job_key(pod: Pod):
    """(job key, owner uid) from the pod's controlling owner reference, or
    None for orphans (they are indexed once adoption lands as a MODIFIED)."""
    ref = pod.metadata.controller_of()
    if ref is None or ref.kind != constants.KIND:
        return None
    return f"{pod.metadata.namespace}/{ref.name}", ref.uid


class PodPhaseIndex:
    def __init__(self):
        self._lock = threading.Lock()
        # job key -> pod "ns/name" -> record
        self._jobs: Dict[str, Dict[str, _PodRecord]] = {}

    # -- maintenance (called from the pod informer handlers) -----------------

    def observe(self, pod: Pod) -> None:
        owner = _owner_job_key(pod)
        if owner is None:
            return
        job_key, uid = owner
        rtype = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL)
        if rtype is None:
            return
        # Mirrors naming.pod_index: absent/garbled -> None (never counted).
        idx_label = pod.metadata.labels.get(constants.REPLICA_INDEX_LABEL, "")
        index: Optional[int] = int(idx_label) if idx_label.isdigit() else None
        rec = _PodRecord(rtype, index, pod.status.phase,
                         bool(pod.spec.node_name), uid)
        pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            self._jobs.setdefault(job_key, {})[pod_key] = rec

    def observe_delete(self, pod: Pod) -> None:
        owner = _owner_job_key(pod)
        if owner is None:
            return
        job_key, _ = owner
        pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            records = self._jobs.get(job_key)
            if records is not None:
                records.pop(pod_key, None)
                if not records:
                    self._jobs.pop(job_key, None)

    def forget_job(self, job_key: str) -> None:
        with self._lock:
            self._jobs.pop(job_key, None)

    # -- reads ---------------------------------------------------------------

    def replica_status(self, job_key: str, owner_uid: str, rtype: str,
                       width: int, restarted: bool
                       ) -> Tuple[ReplicaStatus, int]:
        """(counters, population) for the job's group, counting only records
        below the elastic width (reservation probes and not-yet-drained
        out-of-range pods sit above it) -- the index twin of
        StatusManager._recount_replica_status."""
        rt = rtype.lower()
        rs = ReplicaStatus()
        population = 0
        with self._lock:
            records = self._jobs.get(job_key)
            if not records:
                return rs, 0
            for rec in records.values():
                if rec.rtype != rt or rec.owner_uid != owner_uid:
                    continue
                if rec.index is None or rec.index >= width:
                    continue
                population += 1
                if rec.phase == PodPhase.PENDING:
                    if restarted:
                        rs.restarting += 1
                    elif rec.has_node:
                        rs.scheduled += 1
                    else:
                        rs.pending += 1
                elif rec.phase == PodPhase.RUNNING:
                    rs.active += 1
                elif rec.phase == PodPhase.SUCCEEDED:
                    rs.succeeded += 1
                else:  # Failed / Unknown
                    rs.failed += 1
        return rs, population

    def pod_count(self, job_key: str) -> int:
        with self._lock:
            return len(self._jobs.get(job_key, ()))

    def total_pods(self) -> int:
        with self._lock:
            return sum(len(records) for records in self._jobs.values())

    def job_keys(self):
        with self._lock:
            return list(self._jobs)
