"""The reconcile engine (reference: pkg/controller/)."""

from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.controller.garbage_collection import GarbageCollector

__all__ = ["TrainingJobController", "GarbageCollector"]
