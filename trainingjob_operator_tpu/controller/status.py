"""Status/phase machine: condition CRUD, job-level phase aggregation,
restart-wait, ending arbitration, time limits, termination, write-back.

Reference: pkg/controller/status.go (all of it).  Fixed vs. the reference
(SURVEY.md §8): restart-count initialization covers every replica type
(status.go:315-320 only zeroed the first when the map was nil), and the
write-back goes through the status client method rather than whole-object
Update.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    ENDING_PHASES,
    EndingPolicy,
    PHASE_REASON,
    ReplicaStatus,
    RestartScope,
    TrainingJobPhase,
    TPUTrainingJob,
    is_failed_phase,
)
from trainingjob_operator_tpu.client.tracker import ConflictError, meta_namespace_key
from trainingjob_operator_tpu.controller.naming import (
    effective_replicas,
    filter_for_replica_type,
    full_width,
    live_replicas,
    lost_indices,
    pod_index,
    pods_below_width,
)
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ConditionStatus,
    Pod,
    PodPhase,
    Service,
)
from trainingjob_operator_tpu.obs.goodput import GOODPUT
from trainingjob_operator_tpu.obs.incident import INCIDENTS
from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
from trainingjob_operator_tpu.utils.events import EventRecorder

log = logging.getLogger("trainingjob.status")


def new_condition(ctype: str, reason: str, message: str) -> Condition:
    """Reference: newTrainingJobCondition (status.go:13-22)."""
    now = time.time()
    return Condition(type=ctype, status=ConditionStatus.TRUE, reason=reason,
                     message=message, last_probe_time=now, last_transition_time=now)


def get_condition(status, ctype: str) -> Optional[Condition]:
    """Reference: getTrainingJobCondition (status.go:24-31)."""
    for cond in status.conditions:
        if cond.type == ctype:
            return cond
    return None


def is_job_completed(status) -> bool:
    """Reference: isJobCompleted (status.go:33-58)."""
    for ctype in (TrainingJobPhase.SUCCEEDED, TrainingJobPhase.FAILED,
                  TrainingJobPhase.PREEMPTED, TrainingJobPhase.TIMEOUT):
        cond = get_condition(status, ctype)
        if cond is not None and cond.status == ConditionStatus.TRUE:
            return True
    return False


def set_condition(status, new_cond: Condition) -> None:
    """Append-or-refresh; the latest condition is authoritative and older ones
    flip to False (reference: setTrainingJobCondition, status.go:60-75)."""
    if status.conditions:
        curr = status.conditions[-1]
        if (curr.type == new_cond.type and curr.status == new_cond.status
                and curr.reason == new_cond.reason):
            curr.message = new_cond.message
            curr.last_probe_time = new_cond.last_probe_time
            return
        curr.status = ConditionStatus.FALSE
    status.conditions.append(new_cond)


def update_job_conditions(job: TPUTrainingJob, ctype: str, reason: str,
                          message: str) -> None:
    """Reference: updateTrainingJobConditions (status.go:77-87)."""
    if is_job_completed(job.status):
        return
    set_condition(job.status, new_condition(ctype, reason, message))
    job.status.phase = ctype


class StatusManager:
    """Mixin for TrainingJobController (reference: status.go methods)."""

    # -- small helpers shared with the pod reconciler ------------------------

    @staticmethod
    def _get_condition(status, ctype: str) -> Optional[Condition]:
        return get_condition(status, ctype)

    @staticmethod
    def _initialize_replica_status(job: TPUTrainingJob, rtype: str) -> None:
        """Reference: initializeTrainingJobReplicaStatuses (status.go:307-313)."""
        job.status.replica_statuses[rtype] = ReplicaStatus()

    @staticmethod
    def _initialize_restart_counts(job: TPUTrainingJob, rtype: str) -> None:
        """Fixed version of initializeTrainingJobRestartCountes
        (status.go:315-320): always ensure the key exists."""
        job.status.restart_counts.setdefault(rtype, 0)

    @staticmethod
    def _update_restart_count(job: TPUTrainingJob, rtype: str) -> None:
        """Reference: updateRestartCount (status.go:322-330)."""
        if job.spec.replica_specs[rtype].restart_scope == RestartScope.ALL:
            for rt in job.spec.replica_specs:
                job.status.restart_counts[rt] = job.status.restart_counts.get(rt, 0) + 1
        else:
            job.status.restart_counts[rtype] = job.status.restart_counts.get(rtype, 0) + 1

    @staticmethod
    def _recount_replica_status(job: TPUTrainingJob, rtype: str,
                                pods: List[Pod]) -> None:
        """Reset-and-recount from live pods (reference:
        updateTrainingJobReplicaStatuses, status.go:332-359)."""
        rs = job.status.replica_statuses.setdefault(rtype, ReplicaStatus())
        rs.reset()
        restarted = job.status.restart_counts.get(rtype, 0) > 0
        for pod in pods:
            phase = pod.status.phase
            if phase == PodPhase.PENDING:
                if restarted:
                    rs.restarting += 1
                elif pod.spec.node_name:
                    rs.scheduled += 1
                else:
                    rs.pending += 1
            elif phase == PodPhase.RUNNING:
                rs.active += 1
            elif phase == PodPhase.SUCCEEDED:
                rs.succeeded += 1
            else:  # Failed / Unknown
                rs.failed += 1

    # -- the job-level aggregation (reference: updateStatus, status.go:101) --

    def update_status(self, job: TPUTrainingJob, pods: List[Pod],
                      services: List[Service],
                      ending_phases: Dict[str, str], message: str) -> None:
        phase_index = getattr(self, "pod_phase_index", None)
        job_key = meta_namespace_key(job)
        for rtype in job.spec.replica_specs:
            self._initialize_replica_status(job, rtype)
            rt_pods = filter_for_replica_type(pods, rtype.lower())
            # Reservation (probe) pods and not-yet-drained out-of-range pods
            # sit above the elastic width and must not count.
            width = effective_replicas(job, rtype)
            counted = pods_below_width(rt_pods, width)
            if phase_index is not None:
                # O(changed-pods) fast path: counters from the informer-delta
                # index.  Only trusted when its population agrees with the
                # claimed-pod snapshot (the index may be one event stale; the
                # event that made it stale has already re-enqueued this job).
                rs, population = phase_index.replica_status(
                    job_key, job.metadata.uid, rtype,
                    width, job.status.restart_counts.get(rtype, 0) > 0)
                if population == len(counted):
                    job.status.replica_statuses[rtype] = rs
                    continue
            self._recount_replica_status(job, rtype, counted)

        # Elastic-resize fast path drain (scope Resize, docs/ELASTIC.md):
        # unlike every other drain, the expectation is NOT an empty pod set
        # -- only the pods at the vacated indices must vanish, the
        # survivors stay alive throughout.  Once they are gone, the bumped
        # rendezvous generation (new world size + surviving host list) is
        # republished through the injected generation channel and the job
        # converges back to Running without passing through restart-all.
        if job.status.resize_replica_name:
            rname = job.status.resize_replica_name
            if rname not in job.spec.replica_specs:
                job.status.resize_replica_name = ""
                return
            holes = lost_indices(job, rname)
            rt_pods = filter_for_replica_type(pods, rname.lower())
            width = effective_replicas(job, rname)
            still = [p for p in rt_pods
                     if (idx := pod_index(p)) is not None
                     and (idx in holes or idx >= width)]
            if not still:
                doc = self.publish_generation(job, rname)
                live = width - len(holes)
                self.recorder.event(
                    job, EventRecorder.NORMAL,
                    constants.RESHARD_COMPLETED_REASON,
                    f"{rname.lower()} resize drain complete: republished "
                    f"rendezvous generation {doc['generation']} to {live} "
                    f"survivor(s) (world {doc['world']})")
                update_job_conditions(
                    job, TrainingJobPhase.SCALING, constants.SCALING_REASON,
                    f"{rname.lower()} resized in place to {live} replicas; "
                    f"survivors resharding")
                job.status.resize_replica_name = ""
            else:
                # Converge stragglers (same rationale as the scaling drain).
                for p in still:
                    if p.metadata.deletion_timestamp is None:
                        self.pod_control.delete_pod(p.namespace, p.name, job)
            return

        # Elastic-resize drain: wait for the resized group's pods to vanish,
        # then clear the marker so the next sync recreates the group at the
        # new width with fresh rendezvous env (mirrors the restart drain).
        if job.status.scaling_replica_name:
            rname = job.status.scaling_replica_name
            if rname not in job.spec.replica_specs:
                job.status.scaling_replica_name = ""
                return
            # A resize re-rendezvouses every group whose env references the
            # resized one -- all of them in a multi-group job (pod.py
            # _elastic_resize) -- so wait on the matching pod set.  Succeeded
            # pods of other groups keep their finished work and are excluded.
            if len(job.spec.replica_specs) > 1:
                scope_pods = [
                    p for p in pods
                    if (p.metadata.labels.get(constants.REPLICA_NAME_LABEL)
                        == rname.lower()
                        or p.status.phase != PodPhase.SUCCEEDED)]
            else:
                scope_pods = filter_for_replica_type(pods, rname.lower())
            if len(scope_pods) == 0:
                width = effective_replicas(job, rname)
                update_job_conditions(
                    job, TrainingJobPhase.SCALING, constants.SCALING_REASON,
                    f"{rname.lower()} resized to {width} replicas; recreating")
                job.status.scaling_replica_name = ""
            else:
                # Converge stragglers: a pod created in the same sync that
                # triggered the resize missed the original delete sweep and
                # would wedge the drain forever.
                for p in scope_pods:
                    if p.metadata.deletion_timestamp is None:
                        self.pod_control.delete_pod(p.namespace, p.name, job)
            return

        # Two-phase restart: wait for the scope's pods to drain, then flip to
        # Restarting and clear the marker (status.go:114-143).
        if job.status.restart_replica_name:
            rname = job.status.restart_replica_name
            spec = job.spec.replica_specs.get(rname)
            if spec is None:
                job.status.restart_replica_name = ""
                return
            scope = spec.restart_scope
            if scope == RestartScope.RESIZE:
                # A Resize-scope group only gets here via the width-floor
                # fallback (pod.py _resize_keepalive returning None), which
                # restarts the world -- drain like scope All.
                scope = RestartScope.ALL
            rt_pods = filter_for_replica_type(pods, rname.lower())
            replicas = effective_replicas(job, rname)
            if scope == RestartScope.ALL and len(pods) == 0:
                update_job_conditions(job, TrainingJobPhase.RESTARTING,
                                      PHASE_REASON[TrainingJobPhase.RESTARTING],
                                      "All pods are restarting now")
                job.status.restart_replica_name = ""
            elif scope == RestartScope.REPLICA and len(rt_pods) == 0:
                update_job_conditions(job, TrainingJobPhase.RESTARTING,
                                      PHASE_REASON[TrainingJobPhase.RESTARTING],
                                      f"{rname.lower()} pods are restarting now")
                job.status.restart_replica_name = ""
            elif (scope == RestartScope.POD
                  and len(pods_below_width(rt_pods, replicas)) < replicas):
                update_job_conditions(job, TrainingJobPhase.RESTARTING,
                                      PHASE_REASON[TrainingJobPhase.RESTARTING],
                                      "pod is restarting now")
                job.status.restart_replica_name = ""
            return

        now = time.time()
        spec = job.spec
        completed = sum(1 for p in ending_phases.values()
                        if p == TrainingJobPhase.SUCCEEDED)
        failed = 0
        ending_phase = TrainingJobPhase.NONE
        for p in ending_phases.values():
            if is_failed_phase(p):
                failed += 1
                ending_phase = p
        replica_count = len(spec.replica_specs)

        # CompletePolicy beats FailPolicy (status.go:159-174).
        if spec.complete_policy == EndingPolicy.ANY and completed > 0:
            self.terminate_trainingjob(job, pods, services,
                                       TrainingJobPhase.SUCCEEDED,
                                       f"job {job.name} completed")
            return
        if spec.complete_policy == EndingPolicy.ALL and completed == replica_count:
            self.terminate_trainingjob(job, pods, services,
                                       TrainingJobPhase.SUCCEEDED,
                                       f"job {job.name} completed")
            return
        if spec.fail_policy == EndingPolicy.ANY and failed > 0:
            self.terminate_trainingjob(job, pods, services, ending_phase, message)
            return
        if spec.fail_policy == EndingPolicy.ALL and failed == replica_count:
            self.terminate_trainingjob(job, pods, services, ending_phase, message)
            return

        # Deferred ending: phase stashed in an annotation until pods drain
        # (status.go:176-187).
        for phase in ENDING_PHASES:
            msg = job.metadata.annotations.get(phase)
            if msg is not None:
                if len(pods) == 0:
                    job.status.end_time = now
                    update_job_conditions(job, phase, PHASE_REASON[phase],
                                          f"{msg}; deleted pods")
                    GOODPUT.on_complete(meta_namespace_key(job), now)
                    TELEMETRY.on_complete(meta_namespace_key(job))
                    INCIDENTS.on_complete(meta_namespace_key(job), phase,
                                          now=now)
                else:
                    # Drain progress arrives as pod DELETED events that
                    # re-enqueue this job; the delayed poll is only a safety
                    # net and coalesces per key (add_after).  Rate-limited
                    # requeue here spun at the 5 ms backoff base for every
                    # draining job -- at fleet scale that was most of the
                    # sync volume.
                    self.enqueue_job(job, delay=0.5)
                return

        # Time limit (status.go:189-198).
        if (spec.time_limit is not None and job.status.start_running_time is not None
                and now - job.status.start_running_time >= spec.time_limit):
            self.terminate_trainingjob(
                job, pods, services, TrainingJobPhase.TIMEOUT,
                f"started at {job.status.start_running_time}, current time is "
                f"{now}, timeLimit is {spec.time_limit} second")
            return

        # Live phase classification from counters (status.go:200-244).
        is_scheduled = True
        is_creating = False
        is_running = True
        is_restarting = False
        for rtype in spec.replica_specs:
            # Net of resize holes: a group that resized in place converges
            # at its surviving world size, not the nominal index range.
            replicas = live_replicas(job, rtype)
            rs = job.status.replica_statuses[rtype]
            is_scheduled = is_scheduled and (
                rs.scheduled + rs.active + rs.succeeded + rs.failed
                + rs.restarting == replicas)
            is_creating = is_creating or rs.scheduled > 0
            is_restarting = is_restarting or rs.restarting > 0
            is_running = is_running and replicas == rs.active

        if job.status.phase != TrainingJobPhase.RUNNING and is_running:
            if job.status.start_running_time is None:
                job.status.start_running_time = now
            update_job_conditions(job, TrainingJobPhase.RUNNING,
                                  constants.RUNNING_REASON,
                                  self._running_message(job, now))
            GOODPUT.on_running(meta_namespace_key(job), now,
                               start_time=job.status.start_time)
            # Same ``now`` closes both ledgers' windows: the incident
            # bundle's control_downtime_ms matches the goodput window
            # exactly (tests/test_incident.py reconciles them).
            INCIDENTS.on_running(meta_namespace_key(job), now=now)
        elif is_running and job.status.phase == TrainingJobPhase.RUNNING:
            # Live throughput snapshot in the Running condition: same
            # type/status/reason means set_condition refreshes the message in
            # place (no new condition, no phase churn); the snapshot itself is
            # cached by the aggregator so write-back churn stays bounded.
            update_job_conditions(job, TrainingJobPhase.RUNNING,
                                  constants.RUNNING_REASON,
                                  self._running_message(job, now))
        if is_running and job.status.scale_up_attempts:
            # A group back at FULL width (maxReplicas when set) resets its own
            # re-expand backoff; groups still below it keep backing off.
            job.status.scale_up_attempts = {
                rt: n for rt, n in job.status.scale_up_attempts.items()
                if rt in spec.replica_specs
                and live_replicas(job, rt) < full_width(spec.replica_specs[rt])}

        if (is_creating and is_scheduled
                and job.status.phase not in (TrainingJobPhase.RESTARTING,
                                             TrainingJobPhase.SCALING)):
            update_job_conditions(job, TrainingJobPhase.CREATING,
                                  constants.CREATING_REASON, message)

        if is_restarting and job.status.phase != TrainingJobPhase.RESTARTING:
            update_job_conditions(job, TrainingJobPhase.RESTARTING,
                                  constants.RESTARTING_REASON, message)

        if (not is_scheduled and not is_restarting
                and job.status.phase not in (TrainingJobPhase.RESTARTING,
                                             TrainingJobPhase.SCALING)):
            if job.status.start_time is None:
                job.status.start_time = now
            update_job_conditions(job, TrainingJobPhase.PENDING,
                                  constants.PENDING_REASON,
                                  "all pods are waiting for scheduling")

        # Arm a delayed re-sync at the time-limit expiry (status.go:246-252).
        if spec.time_limit is not None and job.status.start_running_time is not None:
            remaining = spec.time_limit - (now - job.status.start_running_time)
            self.enqueue_job(job, delay=max(remaining, 0.0))

    @staticmethod
    def _running_message(job: TPUTrainingJob, now: float) -> str:
        """Base Running message plus the latest telemetry snapshot, when the
        job's replicas have reported any steps."""
        msg = "all pods are running"
        snapshot = TELEMETRY.status_line(meta_namespace_key(job), now=now)
        if snapshot:
            msg = f"{msg}; {snapshot}"
        return msg

    # -- termination (reference: terminateTrainingJob, status.go:256-283) ----

    def terminate_trainingjob(self, job: TPUTrainingJob, pods: List[Pod],
                              services: List[Service], ending_phase: str,
                              message: str) -> None:
        clean = job.spec.clean_pod_policy
        if ((clean is None or clean == CleanPodPolicy.NONE)
                and ending_phase in (TrainingJobPhase.SUCCEEDED,
                                     TrainingJobPhase.FAILED)):
            update_job_conditions(job, ending_phase, PHASE_REASON[ending_phase],
                                  f"{message}; kept pods")
            if job.status.end_time is None:
                job.status.end_time = time.time()
            GOODPUT.on_complete(meta_namespace_key(job), job.status.end_time)
            TELEMETRY.on_complete(meta_namespace_key(job))
            INCIDENTS.on_complete(meta_namespace_key(job), ending_phase,
                                  now=job.status.end_time)
            return
        job.metadata.annotations[ending_phase] = message
        # The stash is METADATA: on a real apiserver the status-subresource
        # write below ignores it, so it must land through a full update or
        # the deferred ending is lost and the job loops forever (caught by
        # the fake-apiserver e2e; the in-memory tracker masked this).
        self.persist_job_metadata(job)
        self.delete_pods_and_services(job, pods, services)
        update_job_conditions(job, TrainingJobPhase.TERMINATING,
                              PHASE_REASON[TrainingJobPhase.TERMINATING],
                              f"{message}; deleting pods")

    def persist_job_metadata(self, job: TPUTrainingJob) -> None:
        """Write job metadata (the ending-phase annotation stash) through the
        main resource, merging our annotations over fresh state on conflict."""
        work = job
        for _ in range(5):
            try:
                updated = self.clientset.trainingjobs.update(work)
                job.metadata.resource_version = updated.metadata.resource_version
                return
            except ConflictError:
                try:
                    # Live read (not the lister): the informer cache lags the
                    # conflict-winning write on a real apiserver.
                    fresh = self.clientset.trainingjobs.get(job.namespace,
                                                            job.name)
                except KeyError:
                    return  # job deleted under us
                fresh.metadata.annotations = {**fresh.metadata.annotations,
                                              **job.metadata.annotations}
                work = fresh
            except KeyError:
                return  # job deleted under us
        log.error("persisting %s/%s metadata failed after retries",
                  job.namespace, job.name)

    def delete_pods_and_services(self, job: TPUTrainingJob, pods: List[Pod],
                                 services: List[Service]) -> None:
        """Reference: deletePodsAndServices (trainingjob.go:53-73)."""
        if not pods:
            return
        for pod in pods:
            self.pod_control.delete_pod(pod.namespace, pod.name, job)
        for svc in services:
            self.service_control.delete_service(svc.namespace, svc.name, job)

    # -- write-back (reference: updateTrainingJobPhase, status.go:285-305) ---

    def update_trainingjob_phase(self, job: TPUTrainingJob) -> None:
        last_err: Optional[Exception] = None
        for attempt in range(5):
            try:
                self.clientset.trainingjobs.update_status(job)
                return
            except ConflictError as e:
                last_err = e
                fresh = self.trainingjob_lister.try_get(job.namespace, job.name)
                if fresh is None:
                    continue
                fresh.status = job.status
                # Merge, fresh-wins: keep annotations the controller stashed
                # (ending-phase markers) without erasing concurrently-written
                # external ones like the Preempted request (pod.go:160-165) --
                # the reference overwrote wholesale here (status.go:300-302).
                fresh.metadata.annotations = {**job.metadata.annotations,
                                              **fresh.metadata.annotations}
                job = fresh
            except KeyError:
                return  # job deleted under us
        log.error("update job phase %s failed after retries: %s",
                  job.status.phase, last_err)
