"""Pod reconciler: per-replica-group reconcile, restart decisions, container
inspection, rendezvous/TPU env injection.

Reference: pkg/controller/pod.go (all of it).  The decision flow of
``reconcile_pods``/``reconcile_containers`` mirrors pod.go:152-437; the env
contract mirrors setEnv (pod.go:548-652) and adds the TPU/JAX bootstrap set
(SURVEY.md §3.5 "TPU mapping").
"""

from __future__ import annotations

import copy
import json
import logging
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.tpu import resolve_slice_shape
from trainingjob_operator_tpu.api.types import (
    EdlPolicy,
    RestartPolicy,
    RestartScope,
    EndingPolicy,
    TrainingJobPhase,
    TPUTrainingJob,
)
from trainingjob_operator_tpu.client.expectations import pods_key
from trainingjob_operator_tpu.client.retry import RetryPolicy, retry_call
from trainingjob_operator_tpu.client.tracker import meta_namespace_key
from trainingjob_operator_tpu.controller.naming import (
    effective_replicas,
    filter_for_replica_type,
    gen_general_name,
    gen_labels,
    gang_size,
    get_slices,
    full_width,
    is_retryable_exit_code,
    live_replicas,
    lost_indices,
    pod_index,
    pods_below_width,
    round_to_gang,
)
from trainingjob_operator_tpu.controller.service import get_ports_from_container, get_ports_from_job
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ConditionStatus,
    EnvVar,
    Node,
    NodeConditionType,
    Pod,
    PodConditionType,
    PodPhase,
)
from trainingjob_operator_tpu.obs.incident import INCIDENTS
from trainingjob_operator_tpu.obs.telemetry import TELEMETRY, sink_address
from trainingjob_operator_tpu.obs.trace import TRACER, current_context
from trainingjob_operator_tpu.utils.events import EventRecorder

log = logging.getLogger("trainingjob.pod")


def _write_generation_doc(base: str, doc: Dict[str, Any]) -> None:
    """Atomic write of the rendezvous generation doc (tmp + rename); the
    unit publish_generation's bounded retry wraps."""
    os.makedirs(base, exist_ok=True)
    tmp = os.path.join(base, ".generation.tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    os.replace(tmp, os.path.join(base, "generation.json"))


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def resize_dir(job: TPUTrainingJob) -> str:
    """The job's rendezvous-generation channel directory (docs/ELASTIC.md):
    the controller publishes ``generation.json`` here on a scope=Resize
    drain; surviving workload processes watch it from the step loop.  A
    template-set TRAININGJOB_RESIZE_DIR wins (mirroring _merge_env's
    user-override semantics) so the controller writes exactly where the
    pods were told to read."""
    for spec in job.spec.replica_specs.values():
        for container in (spec.template.spec.containers
                          + spec.template.spec.init_containers):
            for e in container.env:
                if e.name == constants.RESIZE_DIR_ENV and e.value:
                    return e.value
    return os.path.join(tempfile.gettempdir(), "tpu-trainingjob-rdv",
                        job.namespace, job.name)


class PodReconciler:
    """Mixin for TrainingJobController (reference: pod.go methods)."""

    # -- informer handlers (reference: pod.go:23-123) ------------------------

    def add_pod(self, pod: Pod) -> None:
        # Index maintenance precedes the ownership gate: the phase index keys
        # on the owner ref directly (it must see deletes even after the owner
        # job is gone from the lister).
        self.pod_phase_index.observe(pod)
        if pod.metadata.deletion_timestamp is not None:
            return
        job = self._resolve_controller_ref(pod.metadata.namespace,
                                           pod.metadata.controller_of())
        if job is None:
            return
        rt = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL)
        if rt is None:
            return
        self.expectations.creation_observed(pods_key(meta_namespace_key(job), rt))
        self.work_queue.add(meta_namespace_key(job))

    def update_pod(self, old: Pod, cur: Pod) -> None:
        if old.metadata.resource_version == cur.metadata.resource_version:
            return
        self.pod_phase_index.observe(cur)
        job = self._resolve_controller_ref(cur.metadata.namespace,
                                           cur.metadata.controller_of())
        if job is None:
            return
        self.enqueue_job(job)

    def delete_pod(self, pod: Pod) -> None:
        self.pod_phase_index.observe_delete(pod)
        job = self._resolve_controller_ref(pod.metadata.namespace,
                                           pod.metadata.controller_of())
        if job is None:
            return
        rt = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL)
        if rt is None:
            return
        self.expectations.deletion_observed(pods_key(meta_namespace_key(job), rt))
        self.work_queue.add(meta_namespace_key(job))

    # -- claiming (reference: pod.go:125-150) --------------------------------

    def get_pods_by_job(self, job: TPUTrainingJob, selector: Dict[str, str]) -> List[Pod]:
        # Indexed informer-cache lookup: O(job's pods), not an O(cluster)
        # tracker relist.  The bucket is keyed on the same two labels as the
        # selector (see controller.job_index_key), so orphans with matching
        # labels still surface for adoption; _claim_pods keeps uid discipline.
        informer = getattr(self, "pod_informer", None)
        if informer is not None:
            all_pods = informer.by_index(
                constants.JOB_INDEX, f"{job.namespace}/{job.name}")
        else:
            all_pods = self.pod_lister.list(job.namespace, selector)
        return self._claim_pods(job, all_pods)

    def _claim_pods(self, job: TPUTrainingJob, pods: List[Pod]) -> List[Pod]:
        """Keep pods controlled by this job; adopt matching orphans (the
        ControllerRefManager's essential behavior, pod.go:134-150)."""
        claimed = []
        for pod in pods:
            ref = pod.metadata.controller_of()
            if ref is not None:
                if ref.uid == job.metadata.uid:
                    claimed.append(pod)
                continue
            if pod.metadata.deletion_timestamp is not None:
                continue
            # Orphan with matching selector: adopt.
            from trainingjob_operator_tpu.controller.control import gen_owner_reference
            pod.metadata.owner_references.append(gen_owner_reference(job))
            try:
                claimed.append(self.clientset.pods.update(pod))
            except Exception:
                log.warning("failed to adopt pod %s", pod.name, exc_info=True)
        return claimed

    # -- the per-replica-group reconcile (reference: pod.go:152-326) ---------

    def reconcile_pods(self, job: TPUTrainingJob, pods: List[Pod],
                       rtype: str) -> Tuple[str, str]:
        """Returns (ending_phase, message); ending_phase "" means live."""
        if job.status.phase == TrainingJobPhase.TERMINATING:
            return TrainingJobPhase.TERMINATING, ""
        # Preemption API: external actor annotates the CR (pod.go:160-165).
        msg = job.metadata.annotations.get(TrainingJobPhase.PREEMPTED)
        if msg is not None:
            return TrainingJobPhase.PREEMPTED, msg
        msg = job.metadata.annotations.get(TrainingJobPhase.FAILED)
        if msg is not None:
            return TrainingJobPhase.FAILED, msg

        rt = rtype.lower()
        spec = job.spec.replica_specs[rtype]
        replica_pods = filter_for_replica_type(pods, rt)
        replicas = effective_replicas(job, rtype)
        self._initialize_replica_status(job, rtype)
        self._initialize_restart_counts(job, rtype)

        # An in-flight re-expand probe provisions reservation slots beyond the
        # elastic width (non-destructive: the running group is untouched until
        # the reservations actually schedule).  Cost note: a probe at full
        # width idles up to slice_hosts TPU hosts for at most
        # 4*scale_pending_time (the canary TTL).  A cheaper capacity signal
        # -- a zero-TPU pod with the same nodeSelector, or cluster-autoscaler
        # status -- would make re-expands free but cannot confirm the
        # SPECIFIC slice topology schedules as a gang, which is the property
        # the commit step needs; we pay for the stronger guarantee.
        probe_target = (job.status.scale_probes.get(rtype, 0)
                        if spec.edl_policy == EdlPolicy.AUTO else 0)
        pod_slices = get_slices(replica_pods, max(replicas, probe_target))
        node_ready = self.get_node_status()
        self._damp_node_flaps(job, rt, replica_pods)
        message = ""
        failed_reasons: List[str] = []
        failed_phase = TrainingJobPhase.FAILED
        creating_msgs: Dict[str, List[str]] = {}
        now = time.time()
        stuck_indices: List[int] = []
        probe_failed = False

        # Capacity loss is evaluated BEFORE any per-pod restart decision:
        # when a node dies, its pods' kills surface as retryable exit codes
        # on the SURVIVORS too (peer-loss collective failures exit 143), and
        # whichever pod the loop visits first would otherwise win -- a
        # full-width restart stranding a replacement on the dead node for
        # scale_pending_time instead of an immediate shrink.
        if spec.edl_policy == EdlPolicy.AUTO:
            ending = self._maybe_shrink_on_capacity_loss(
                job, rtype, rt, spec, replicas, pods, replica_pods,
                node_ready, "node lost capacity")
            if ending:
                self._recount_replica_status(
                    job, rtype, pods_below_width(replica_pods, replicas))
                return ending

        holes = lost_indices(job, rtype)
        for index, pod_slice in enumerate(pod_slices):
            if not pod_slice:
                if index in holes:
                    # Resize hole (scope Resize, docs/ELASTIC.md): the index
                    # was vacated by a survivor-keepalive resize.  Refilling
                    # it would force a full re-rendezvous; holes heal through
                    # the re-expand probe -> restart-the-world path.
                    continue
                log.info("creating pod %s/%s %s-%d", job.namespace, job.name, rt, index)
                self.create_new_pod(job, rt, str(index),
                                    str(job.status.restart_counts.get(rtype, 0)),
                                    spec, reservation=index >= replicas)
                continue

            pod = pod_slice[0]
            if index >= replicas:
                # Reservation slot: capacity canary only -- none of the policy
                # machinery applies until the group re-rendezvouses.
                created = pod.metadata.creation_timestamp
                stale = (created is not None and
                         now - created > self.options.scale_pending_time)
                dead_node = (pod.spec.node_name
                             and pod.spec.node_name not in node_ready)
                if ((stale and self.get_pod_scheduling_message(pod))
                        or dead_node or pod.status.phase == PodPhase.FAILED):
                    probe_failed = True
                continue
            sched_msg = self.get_pod_scheduling_message(pod)
            if sched_msg:
                message = f"{rt}: {sched_msg} "
                created = pod.metadata.creation_timestamp
                if (created is not None
                        and now - created > self.options.scale_pending_time):
                    stuck_indices.append(index)
            phase, is_restart, cmsg = self.reconcile_containers(job, pod, rtype, node_ready)
            if cmsg:
                failed_reasons.append(cmsg)

            # NODE_FAIL under EdlPolicy.AUTO was already resolved by the
            # pre-loop _maybe_shrink_on_capacity_loss (same snapshot, same
            # sync); a NODE_FAIL reaching here is the non-elastic restart/
            # fail path below.
            if is_restart:
                limit = spec.restart_limit
                if limit is None or job.status.restart_counts.get(rtype, 0) < limit:
                    ending = self._restart_pods(job, rtype, rt, pod, pods, pod_slices,
                                                phase, cmsg, node_ready)
                    if ending:
                        self._recount_replica_status(
                            job, rtype, pods_below_width(replica_pods, replicas))
                        return ending

            if phase == TrainingJobPhase.CREATING:
                creating_msgs.setdefault(cmsg, []).append(pod.name)

            # Per-pod ending policies (pod.go:260-287).
            if (phase == TrainingJobPhase.SUCCEEDED
                    and pod.status.phase == PodPhase.SUCCEEDED
                    and spec.complete_policy == EndingPolicy.ANY):
                return TrainingJobPhase.SUCCEEDED, f"pod {pod.name} have completed"
            if (phase in (TrainingJobPhase.FAILED, TrainingJobPhase.NODE_FAIL)
                    and spec.fail_policy == EndingPolicy.ANY):
                return phase, f"pod {pod.name} is failed, {cmsg}"
            if index == 0:
                if (phase == TrainingJobPhase.SUCCEEDED
                        and pod.status.phase == PodPhase.SUCCEEDED
                        and spec.complete_policy == EndingPolicy.RANK0):
                    return TrainingJobPhase.SUCCEEDED, f"rank0 pod {pod.name} have completed"
                if (phase in (TrainingJobPhase.FAILED, TrainingJobPhase.NODE_FAIL)
                        and spec.fail_policy == EndingPolicy.RANK0):
                    return phase, f"rank0 pod {pod.name} is failed, {cmsg}"

            if phase == TrainingJobPhase.NODE_FAIL:
                failed_phase = TrainingJobPhase.NODE_FAIL

        self._recount_replica_status(
            job, rtype, pods_below_width(replica_pods, replicas))
        rs = job.status.replica_statuses[rtype]
        # World size net of resize holes: whole-group policies and the
        # stability checks below must count what can actually exist.
        live = replicas - len(holes)

        # Whole-group ending policies (pod.go:298-315).
        if spec.complete_policy == EndingPolicy.ALL and rs.succeeded == live:
            return TrainingJobPhase.SUCCEEDED, f"All {rtype} pods have completed"
        if spec.fail_policy == EndingPolicy.ALL and rs.failed == live:
            if failed_reasons:
                message = ", ".join(failed_reasons)
            return failed_phase, f"All {rtype} pods are failed, {message}"

        # Resolve an in-flight re-expand probe: all reservations scheduled ->
        # commit (the only destructive step, taken exactly when capacity is
        # confirmed); any reservation starved/failed -> discard reservations,
        # keep the running group untouched, back off.
        if probe_target:
            ending = self._resolve_expand_probe(job, rtype, rt, replicas,
                                                probe_target, probe_failed,
                                                pods, replica_pods,
                                                node_ready, now)
            if ending:
                return ending

        # Elastic starvation shrink: replicas stuck unschedulable past the
        # grace window give their slots back (shrink to scheduled capacity,
        # floor min_replicas).  Covers initial admission onto a partial
        # cluster.  Never fires once part of the group has succeeded -- a
        # resize would discard and re-run the finished work.  Multi-host TPU
        # groups shrink in whole slices; when shrink is unavailable, a
        # partially placed slice is torn down whole so its hosts are not
        # held hostage by an unschedulable sibling (gang atomicity --
        # improves on pod.go:186-193's per-index gap fill).
        gang = gang_size(spec)
        if stuck_indices and spec.edl_policy == EdlPolicy.AUTO \
                and rs.succeeded == 0:
            if gang > 1:
                stuck = len({i // gang for i in stuck_indices}) * gang
            else:
                stuck = len(stuck_indices)
            new_width = max(replicas - stuck, self._min_width(spec))
            if new_width < replicas:
                return self._elastic_resize(
                    job, rtype, rt, new_width, pods, replica_pods, force=False,
                    msg=f"{len(stuck_indices)} {rt} pods unschedulable for "
                        f">{self.options.scale_pending_time:.0f}s; shrinking "
                        f"{replicas}->{new_width}")
        if gang > 1 and stuck_indices and rs.succeeded == 0:
            # Succeeded guard: releasing a gang that contains finished pods
            # would discard and re-run completed work.
            ending = self._release_partial_gangs(job, rtype, rt, gang,
                                                 stuck_indices, replica_pods,
                                                 now)
            if ending:
                return ending
        elif not stuck_indices and rs.active == live:
            # Reset the release backoff only once the group actually RUNS at
            # full width -- "no stuck pods this sync" also describes freshly
            # recreated pods that have not aged past the grace window yet,
            # and resetting there would let the release loop thrash at
            # scale_pending_time period forever.
            # analyzer: allow[unguarded-shared-state] keyed by job and the
            # workqueue serializes a job onto one worker at a time
            self._gang_release_backoff.pop(
                f"{meta_namespace_key(job)}/{rtype}", None)

        # Traffic-aware serve scaling: a "serve" replica group with live
        # serving telemetry is scaled by queue depth, not by the capacity
        # re-expand probe (which would drag a deliberately scaled-in group
        # back to full width against the traffic signal).
        if not self._maybe_scale_serve(job, rtype, rt, spec, replicas,
                                       replica_pods, now):
            # Elastic re-expand: a degraded group that is stably running
            # starts a non-destructive capacity probe after a (backed-off)
            # delay.
            self._maybe_start_expand_probe(job, rtype, rt, spec, replicas,
                                           rs, now)

        if creating_msgs:
            msgs = [f"pods {pods_} {m}" for m, pods_ in creating_msgs.items()]
            return TrainingJobPhase.NONE, ", ".join(msgs)
        return TrainingJobPhase.NONE, message

    # -- elastic resize (TPU extension; SURVEY.md §2.6, §5.3 "Gap vs.
    #    elastic" -- the north-star <90s recovery path) ----------------------

    @staticmethod
    def _min_width(spec: Any) -> int:
        """Shrink floor: never below 1 -- a group elastically resized to zero
        could neither probe back up nor distinguish itself from completion.
        For multi-host TPU groups the floor is a whole slice (rounded UP):
        a sub-slice of hosts is not a runnable unit."""
        desired = spec.replicas if spec.replicas is not None else 1
        lo = max(spec.min_replicas if spec.min_replicas is not None else desired, 1)
        gang = gang_size(spec)
        if gang > 1:
            lo = max(round_to_gang(lo, gang, up=True), gang)
        return lo

    @staticmethod
    def _full_width(spec: Any) -> int:
        return full_width(spec)

    def _maybe_shrink_on_capacity_loss(self, job: TPUTrainingJob, rtype: str,
                                       rt: str, spec: Any, replicas: int,
                                       all_pods: List[Pod],
                                       replica_pods: List[Pod],
                                       node_ready: Dict[str, bool],
                                       msg: str) -> Optional[Tuple[str, str]]:
        if spec.edl_policy != EdlPolicy.AUTO:
            return None
        base_pods = pods_below_width(replica_pods, replicas)
        if any(p.status.phase == PodPhase.SUCCEEDED for p in base_pods):
            return None  # resizing would discard finished work
        gang = gang_size(spec)
        lost_pods = [p for p in base_pods
                     if p.spec.node_name and p.spec.node_name not in node_ready]
        if gang > 1:
            # Slice-granular loss: losing ANY host of a slice loses the whole
            # slice -- its survivors keep a nodeSelector demanding the full
            # slice topology, which JAX/ICI cannot initialize below full host
            # count.  The unit of account is the slice (VERDICT r3 item 3).
            lost_gangs = {idx // gang for p in lost_pods
                          if (idx := pod_index(p)) is not None}
            lost = len(lost_gangs) * gang
            unit = f"{len(lost_gangs)} {rt} slice(s)"
        else:
            lost = len(lost_pods)
            unit = f"{lost} {rt} pods"
        new_width = max(replicas - lost, self._min_width(spec))
        if lost == 0 or new_width >= replicas:
            return None  # nothing lost, or already at the floor -> restart path
        return self._elastic_resize(
            job, rtype, rt, new_width, all_pods, replica_pods, force=True,
            msg=f"{unit} lost their node ({msg}); shrinking "
                f"{replicas}->{new_width}", node_ready=node_ready)

    def _release_partial_gangs(self, job: TPUTrainingJob, rtype: str,
                               rt: str, gang: int,
                               stuck_indices: List[int],
                               replica_pods: List[Pod], now: float,
                               ) -> Optional[Tuple[str, str]]:
        """Gang atomicity for multi-host slices (SURVEY §7 hard-part (a)).

        A slice with one member stuck Unschedulable past the grace window
        while siblings already hold TPU hosts is deadlock-shaped: the placed
        members pin capacity the scheduler may need to place the gang
        elsewhere, and the slice can never run partial.  Tear the whole
        slice down (all-or-nothing) and let the next sync recreate it
        atomically.  Fully-unplaced stuck slices hold nothing and stay
        pending.  Used when elastic shrink is unavailable (non-Auto policy
        or already at the width floor).

        Releases back off exponentially per replica group (in controller
        memory): a cluster persistently one host short must not thrash
        delete/recreate at scale_pending_time period forever."""
        backoffs = self._gang_release_backoff
        key = f"{meta_namespace_key(job)}/{rtype}"
        last, attempts = backoffs.get(key, (0.0, 0))
        delay = self.options.scale_pending_time * (2 ** attempts)
        if now - last < min(delay, 900.0):
            self.enqueue_job(job, delay=max(delay - (now - last), 1.0))
            return None
        released = []
        for g in sorted({i // gang for i in stuck_indices}):
            members = [p for p in replica_pods
                       if (idx := pod_index(p)) is not None
                       and g * gang <= idx < (g + 1) * gang]
            if not any(p.spec.node_name for p in members):
                continue  # nothing placed: the gang holds no capacity
            for p in members:
                self.pod_control.delete_pod(p.namespace, p.name, job)
            released.append(g)
        if not released:
            return None
        backoffs[key] = (now, min(attempts + 1, 10))
        self.metrics.inc("trainingjob_gang_releases_total")
        msg = (f"slice(s) {released} of {rt} partially scheduled for "
               f">{self.options.scale_pending_time:.0f}s; releasing for "
               f"atomic retry (attempt {attempts + 1})")
        self.recorder.event(job, EventRecorder.NORMAL,
                            constants.SCALING_REASON, msg)
        log.info("gang release %s/%s: %s", job.namespace, job.name, msg)
        return TrainingJobPhase.NONE, msg

    def _maybe_start_expand_probe(self, job: TPUTrainingJob, rtype: str,
                                  rt: str, spec: Any, replicas: int,
                                  rs: Any, now: float) -> None:
        """Arm a non-destructive capacity probe: reservation pods beyond the
        current width are provisioned on the next sync; the running group is
        only re-rendezvoused once they all schedule."""
        full = self._full_width(spec)
        live = live_replicas(job, rtype)
        if (spec.edl_policy != EdlPolicy.AUTO
                or (replicas >= full and live == replicas)
                or rs.active != live or live == 0
                or rtype in job.status.scale_probes):
            # ``live < replicas`` (resize holes) arms the probe even at
            # nominal full width: committing it restart-the-worlds the group
            # at full width, which is how holes heal (docs/ELASTIC.md).
            return
        last = job.status.last_scale_times.get(rtype)
        if last is None:
            return
        attempts = job.status.scale_up_attempts.get(rtype, 0)
        delay = min(self.options.scale_up_delay * (2 ** attempts), 900.0)
        if now - last < delay:
            # Re-check when the backoff expires.
            self.enqueue_job(job, delay=max(delay - (now - last), 1.0))
            return
        job.status.scale_probes[rtype] = full
        job.status.last_scale_times[rtype] = now
        self.recorder.event(
            job, EventRecorder.NORMAL, constants.SCALING_REASON,
            f"probing capacity to re-expand {rt} {replicas}->{full} "
            f"(attempt {attempts + 1})")
        self.enqueue_job(job)

    def _maybe_scale_serve(self, job: TPUTrainingJob, rtype: str, rt: str,
                           spec: Any, replicas: int,
                           replica_pods: List[Pod], now: float) -> bool:
        """Traffic-aware scale-out/in for a serving replica group
        (docs/SERVING.md).  Returns True when this policy OWNS the group's
        scaling (a ``serve`` group under edlPolicy Auto + restartScope
        Resize with live serving telemetry) -- the caller then skips the
        training-oriented re-expand probe.

        Serve replicas are independent decode servers behind a shared
        queue, so both directions ride the PR 9 survivor-keepalive
        contract: scale-OUT just raises the elastic width (the missing-pod
        loop creates the new index next sync; survivors keep serving,
        never re-prefill, never re-rendezvous), scale-IN deletes the
        highest index and lowers the width -- no drain, no restart-all.
        Signals come from the telemetry plane's serve snapshots
        (queue depth; p99 rides along in the event message): scale out at
        ``TRAININGJOB_SERVE_SCALE_UP_QUEUE`` (default 8) backlogged
        requests, back in when the queue sits at/below
        ``TRAININGJOB_SERVE_SCALE_DOWN_QUEUE`` (default 0) with idle
        slots.  A per-group cooldown
        (``TRAININGJOB_SERVE_SCALE_COOLDOWN_S``, default 30) damps
        flapping on bursty open-loop arrivals.
        """
        if (rt != "serve" or spec.edl_policy != EdlPolicy.AUTO
                or spec.restart_scope != RestartScope.RESIZE):
            return False
        snap = TELEMETRY.serve_stats(meta_namespace_key(job))
        if snap is None:
            return False
        cooldown = _env_float(constants.SERVE_SCALE_COOLDOWN_ENV, 30.0)
        if now - snap.get("at", 0.0) > max(cooldown * 4.0, 120.0):
            return True  # stale snapshot: own the group, but don't act
        last = job.status.last_scale_times.get(rtype)
        if last is not None and now - last < cooldown:
            self.enqueue_job(job, delay=max(cooldown - (now - last), 1.0))
            return True
        up = _env_float(constants.SERVE_SCALE_UP_QUEUE_ENV, 8.0)
        down = _env_float(constants.SERVE_SCALE_DOWN_QUEUE_ENV, 0.0)
        depth = snap.get("queue_depth", 0.0)
        full = self._full_width(spec)
        gang = gang_size(spec)
        if depth >= up and replicas < full:
            new_width = min(replicas + max(gang, 1), full)
            desired = spec.replicas if spec.replicas is not None else 1
            if new_width == desired:
                job.status.elastic_replicas.pop(rtype, None)
            else:
                job.status.elastic_replicas[rtype] = new_width
            job.status.last_scale_times[rtype] = now
            self.metrics.inc("trainingjob_serve_scales_total",
                             direction="out")
            self.recorder.event(
                job, EventRecorder.NORMAL, constants.SCALING_REASON,
                f"serve queue depth {depth:.0f} >= {up:.0f} "
                f"(p99 {snap.get('p99_ms', 0.0):.1f} ms); scaling out "
                f"{rt} {replicas}->{new_width}")
            self.enqueue_job(job)  # next sync creates the new index
            return True
        idle = snap.get("active_slots", 0.0) < snap.get("slots", 0.0)
        floor = self._resize_floor(spec)
        if depth <= down and idle and replicas - max(gang, 1) >= floor:
            new_width = replicas - max(gang, 1)
            desired = spec.replicas if spec.replicas is not None else 1
            if new_width == desired:
                job.status.elastic_replicas.pop(rtype, None)
            else:
                job.status.elastic_replicas[rtype] = new_width
            job.status.last_scale_times[rtype] = now
            self.metrics.inc("trainingjob_serve_scales_total",
                             direction="in")
            self.recorder.event(
                job, EventRecorder.NORMAL, constants.SCALING_REASON,
                f"serve queue idle (depth {depth:.0f} <= {down:.0f}); "
                f"scaling in {rt} {replicas}->{new_width}")
            # Survivor-keepalive scale-in: only the highest indices go;
            # the lowered width stops the creation loop refilling them.
            for p in replica_pods:
                idx = pod_index(p)
                if idx is not None and idx >= new_width:
                    self.pod_control.delete_pod(p.namespace, p.name, job)
        return True

    def _resolve_expand_probe(self, job: TPUTrainingJob, rtype: str, rt: str,
                              replicas: int, probe_target: int,
                              probe_failed: bool, all_pods: List[Pod],
                              replica_pods: List[Pod],
                              node_ready: Dict[str, bool],
                              now: float) -> Optional[Tuple[str, str]]:
        probe_pods = [p for p in replica_pods
                      if (idx := pod_index(p)) is not None and idx >= replicas]
        if any(p.status.phase == PodPhase.SUCCEEDED
               for p in pods_below_width(replica_pods, replicas)):
            # The group started completing while the probe was in flight:
            # committing would discard finished work.  Cancel the probe.
            for p in probe_pods:
                self.pod_control.delete_pod(p.namespace, p.name, job)
            job.status.scale_probes.pop(rtype, None)
            return None
        landed = [p for p in probe_pods
                  if p.spec.node_name and p.spec.node_name in node_ready
                  and p.status.phase != PodPhase.FAILED]
        if (not probe_failed
                and len(probe_pods) == probe_target - replicas
                and len(landed) == len(probe_pods)):
            # Full capacity confirmed: commit (the one destructive step).
            job.status.scale_probes.pop(rtype, None)
            return self._elastic_resize(
                job, rtype, rt, probe_target, all_pods, replica_pods,
                force=False,
                msg=f"capacity confirmed; re-expanding {rt} "
                    f"{replicas}->{probe_target}")
        if probe_failed:
            spec = job.spec.replica_specs[rtype]
            committable = round_to_gang(len(landed), gang_size(spec))
            if committable:
                # Partial capacity: commit what actually landed rather than
                # training below available capacity forever (the remaining
                # gap re-probes with backoff from the new width).  Multi-host
                # groups commit whole slices only -- a partial slice of
                # landed reservations is not runnable.
                job.status.scale_probes.pop(rtype, None)
                return self._elastic_resize(
                    job, rtype, rt, replicas + committable, all_pods,
                    replica_pods, force=False,
                    msg=f"partial capacity; re-expanding {rt} "
                        f"{replicas}->{replicas + committable} "
                        f"(wanted {probe_target})")
            for p in probe_pods:
                self.pod_control.delete_pod(p.namespace, p.name, job)
            job.status.scale_probes.pop(rtype, None)
            job.status.scale_up_attempts[rtype] = (
                job.status.scale_up_attempts.get(rtype, 0) + 1)
            job.status.last_scale_times[rtype] = now
            self.recorder.event(
                job, EventRecorder.NORMAL, constants.SCALING_REASON,
                f"re-expand probe of {rt} to {probe_target} found no "
                f"capacity; staying at {replicas}")
        return None

    def _elastic_resize(self, job: TPUTrainingJob, rtype: str, rt: str,
                        new_width: int, all_pods: List[Pod],
                        replica_pods: List[Pod], force: bool, msg: str,
                        node_ready: Optional[Dict[str, bool]] = None,
                        ) -> Tuple[str, str]:
        """Record the new width and drain: a width change invalidates the
        rendezvous env (world size, host lists) of every pod that names this
        group, so the resized group -- and, in a multi-group job, every other
        group whose env cross-references it (setEnv injects all groups' host
        lists, pod.go:548-652) -- restarts together and re-assembles at the
        new size.  Already-succeeded pods of other groups keep their finished
        work.  Reuses the two-phase drain machinery
        (status.scaling_replica_name, mirroring the reference's
        RestartReplicaName flow, status.go:114-143).
        """
        spec = job.spec.replica_specs[rtype]
        desired = spec.replicas if spec.replicas is not None else 1
        if new_width == desired:
            job.status.elastic_replicas.pop(rtype, None)
        else:
            job.status.elastic_replicas[rtype] = new_width
        # A resize supersedes any in-flight probe (its reservations are
        # deleted with the rest of the group below).  Resize holes clear
        # too: the restart-the-world recreate fills every index < width.
        job.status.scale_probes.pop(rtype, None)
        job.status.lost_indices.pop(rtype, None)
        job.status.last_scale_times[rtype] = time.time()
        self.metrics.inc("trainingjob_elastic_resizes_total")
        self.recorder.event(job, EventRecorder.NORMAL, constants.SCALING_REASON, msg)
        log.info("elastic resize %s/%s %s: %s", job.namespace, job.name, rt, msg)
        targets = list(replica_pods)
        if len(job.spec.replica_specs) > 1:
            targets += [p for p in all_pods
                        if p.metadata.labels.get(constants.REPLICA_NAME_LABEL)
                        != rt and p.status.phase != PodPhase.SUCCEEDED]
        for p in targets:
            # Force (grace 0) only where termination cannot be observed --
            # pods stranded on a dead node.  Survivors on live nodes get the
            # normal SIGTERM drain so their preemption checkpoint
            # (train.GracefulShutdown) can commit the current step.
            dead_node = (node_ready is not None and p.spec.node_name
                         and p.spec.node_name not in node_ready)
            grace = 0 if (force and (node_ready is None or dead_node)) else None
            self.pod_control.delete_pod(p.namespace, p.name, job, grace_period=grace)
        return TrainingJobPhase.SCALING, msg

    def _damp_node_flaps(self, job: TPUTrainingJob, rt: str,
                         replica_pods: List[Pod]) -> None:
        """Bookkeeping for flap suppression (get_node_status): when a pod
        of this group sits on a node inside its flap grace, re-reconcile
        at the grace deadline (recovered by then, or NODE_FAIL fires one
        grace late), surface one ``NodeFlapSuppressed`` event per
        (node, episode), and declare the window to the incident recorder
        so suppressed time is attributed to the fault plane instead of
        counting as unattributed downtime."""
        pending = self._flap_pending
        if not pending:
            return
        episodes = self._flap_episodes
        now_ts = time.time()
        for p in replica_pods:
            entry = pending.get(p.spec.node_name or "")
            if entry is None:
                continue
            since, deadline = entry
            self.enqueue_job(job, delay=max(deadline - now_ts, 0.1))
            ep_key = f"{p.spec.node_name}/{since:.3f}"
            if ep_key in episodes:
                continue
            while len(episodes) >= 1024:  # bound across flap churn
                episodes.pop(next(iter(episodes)))
            episodes[ep_key] = True
            self.metrics.inc("trainingjob_node_flaps_suppressed_total")
            INCIDENTS.record_chaos_window("flap_suppressed", since, deadline)
            self.recorder.event(
                job, EventRecorder.NORMAL,
                constants.NODE_FLAP_SUPPRESSED_REASON,
                f"node {p.spec.node_name} NotReady for {now_ts - since:.1f}s; "
                f"suppressing NODE_FAIL for {rt} until the "
                f"{deadline - since:.1f}s flap grace expires")

    def _crashloop_gate(self, job: TPUTrainingJob, rtype: str, rt: str,
                        now_ts: float) -> Optional[Tuple[str, str]]:
        """Crash-loop quarantine (the PR 14 workqueue-quarantine pattern
        applied to the restart state machine): ``TRAININGJOB_CRASHLOOP_AFTER``
        consecutive restarts each landing within
        ``TRAININGJOB_CRASHLOOP_WINDOW_S`` of the previous park the replica
        group at a flat ``TRAININGJOB_CRASHLOOP_DELAY_S`` cadence -- one
        ``CrashLoopQuarantined`` event per episode -- instead of burning
        the restart limit at reconcile speed.  A clean window (the
        incarnation outliving WINDOW before its next failure) releases.
        Returns the parked (phase, msg) while holding, else None."""
        after = int(_env_float(constants.CRASHLOOP_AFTER_ENV, 0.0))
        if after <= 0:
            return None
        window = _env_float(constants.CRASHLOOP_WINDOW_ENV, 30.0)
        delay = _env_float(constants.CRASHLOOP_DELAY_ENV, 60.0)
        table = self._crashloop
        key = f"{job.metadata.uid or meta_namespace_key(job)}/{rtype}"
        entry = table.get(key)
        if entry is None:
            while len(table) >= 1024:  # bound across job churn
                table.pop(next(iter(table)))
            entry = table[key] = {"last": 0.0, "fails": 0, "parked": False}
        if entry["last"] and now_ts - entry["last"] >= window:
            # The last incarnation ran a clean window before failing again:
            # the loop is broken, release the episode.
            if entry["parked"]:
                self.metrics.inc("trainingjob_crashloop_released_total")
                self.recorder.event(
                    job, EventRecorder.NORMAL,
                    constants.CRASHLOOP_RELEASED_REASON,
                    f"{rt} ran {now_ts - entry['last']:.1f}s without "
                    f"restarting; releasing crash-loop quarantine")
            entry["fails"] = 0
            entry["parked"] = False
        if entry["fails"] >= after:
            if not entry["parked"]:
                entry["parked"] = True
                self.metrics.inc("trainingjob_crashloop_quarantined_total")
                self.recorder.event(
                    job, EventRecorder.WARNING,
                    constants.CRASHLOOP_QUARANTINED_REASON,
                    f"{rt} restarted {entry['fails']} times in under "
                    f"{window:.0f}s each; parking restarts at a flat "
                    f"{delay:.0f}s cadence until a clean run")
            hold = entry["last"] + delay - now_ts
            if hold > 0:
                self.enqueue_job(job, delay=max(hold, 0.1))
                return (TrainingJobPhase.NONE,
                        f"{rt} crash-loop quarantined; next restart "
                        f"attempt in {hold:.1f}s")
        return None

    def _crashloop_note(self, job: TPUTrainingJob, rtype: str,
                        now_ts: float) -> None:
        """Record that a restart actually happened (feeds _crashloop_gate)."""
        table = self._crashloop
        entry = table.get(
            f"{job.metadata.uid or meta_namespace_key(job)}/{rtype}")
        if entry is not None:
            entry["fails"] += 1
            entry["last"] = now_ts

    def _restart_pods(self, job: TPUTrainingJob, rtype: str, rt: str, pod: Pod,
                      all_pods: List[Pod], pod_slices: List[List[Pod]],
                      phase: str, msg: str,
                      node_ready: Optional[Dict[str, bool]] = None,
                      ) -> Optional[Tuple[str, str]]:
        """Delete pods per RestartScope; NodeFail forces grace=0
        (reference: pod.go:208-250).  Scope Resize takes the
        survivor-keepalive fast path (docs/ELASTIC.md) and only downgrades
        to the ALL drain when survivors would fall below the width floor."""
        now_ts = time.time()
        parked = self._crashloop_gate(job, rtype, rt, now_ts)
        if parked is not None:
            return parked
        force = phase == TrainingJobPhase.NODE_FAIL
        grace = 0 if force else None
        self._update_restart_count(job, rtype)
        self.metrics.inc("trainingjob_restarts_total")
        self._crashloop_note(job, rtype, now_ts)
        msg = f"restart times is {job.status.restart_counts.get(rtype, 0)}, {msg} "
        spec = job.spec.replica_specs[rtype]
        scope = spec.restart_scope
        if scope == RestartScope.RESIZE:
            ending = self._resize_keepalive(job, rtype, rt, pod, pod_slices,
                                            grace, node_ready or {}, msg)
            if ending is not None:
                return ending
            # Survivors can't form a quorum: restart the world instead.
            self.recorder.event(
                job, EventRecorder.WARNING, constants.RESHARD_FELL_BACK_REASON,
                f"resize of {rt} would drop survivors below the width floor; "
                f"falling back to scope=All restart")
            scope = RestartScope.ALL
        self.recorder.event(job, EventRecorder.WARNING, constants.RESTARTING_REASON,
                            f"restarting scope={scope} trigger={pod.name}: {msg}")
        if scope == RestartScope.POD:
            victims = [pod]
            if force and node_ready is not None:
                # Domain-aware teardown: a slice-wide failure downs every
                # node in the domain together, so take down ALL of this
                # group's pods stranded on dead nodes in this one pass --
                # one restart count, one event, one reconcile -- instead of
                # N independent NODE_FAIL discoveries.
                victims += [p for pslice in pod_slices for p in pslice
                            if p is not pod and p.spec.node_name
                            and p.spec.node_name not in node_ready]
            for p in victims:
                self.pod_control.delete_pod(p.namespace, p.name, job,
                                            grace_period=grace)
            if len(victims) > 1:
                msg += f"(domain teardown: {len(victims)} pods on dead nodes) "
            return TrainingJobPhase.RESTARTING, msg
        if scope == RestartScope.REPLICA:
            for pslice in pod_slices:
                for p in pslice:
                    self.pod_control.delete_pod(p.namespace, p.name, job, grace_period=grace)
            return TrainingJobPhase.RESTARTING, msg
        # RestartScope.ALL
        for p in all_pods:
            self.pod_control.delete_pod(p.namespace, p.name, job, grace_period=grace)
        return TrainingJobPhase.RESTARTING, msg

    # -- elastic resize fast path (scope Resize, docs/ELASTIC.md) ------------

    @staticmethod
    def _resize_floor(spec: Any) -> int:
        """Width floor for the survivor-keepalive path: min_replicas when
        set, else 1 (unlike _min_width, not pinned to the declared width --
        scope Resize is meaningful without elastic min/max config).  Multi-
        host groups floor at a whole slice."""
        lo = max(spec.min_replicas if spec.min_replicas is not None else 1, 1)
        gang = gang_size(spec)
        if gang > 1:
            lo = max(round_to_gang(lo, gang, up=True), gang)
        return lo

    def _resize_keepalive(self, job: TPUTrainingJob, rtype: str, rt: str,
                          trigger: Pod, pod_slices: List[List[Pod]],
                          grace: Optional[int], node_ready: Dict[str, bool],
                          msg: str) -> Optional[Tuple[str, str]]:
        """The survivor-keepalive drain: delete only the failed pods (and
        their gang siblings), record the vacated indices as holes, bump the
        rendezvous generation, and hand off to status.py's resize
        expectation logic.  Returns None when survivors would fall below
        the floor -- the caller then restarts the world."""
        spec = job.spec.replica_specs[rtype]
        replicas = effective_replicas(job, rtype)
        gang = gang_size(spec)
        holes = set(job.status.lost_indices.get(rtype, ()))
        newly_lost: set = set()
        for index, pslice in enumerate(pod_slices[:replicas]):
            if index in holes:
                continue
            dead = any(
                p.status.phase == PodPhase.FAILED
                or (p.spec.node_name and p.spec.node_name not in node_ready)
                or p is trigger
                for p in pslice)
            if dead:
                newly_lost.add(index)
        if gang > 1:
            # Slice-granular loss: any dead host loses the whole slice (its
            # survivors' nodeSelector still demands the full topology).
            for g in {i // gang for i in newly_lost}:
                newly_lost.update(range(g * gang, min((g + 1) * gang, replicas)))
        if not newly_lost:
            return None
        holes |= newly_lost
        survivors = replicas - len(holes)
        if survivors < self._resize_floor(spec) or survivors <= 0:
            return None
        # Victims: every pod at a lost index, plus any reservation pods an
        # in-flight probe parked above the width (the probe is cancelled --
        # its capacity answer predates the loss).
        victims = [p for index, pslice in enumerate(pod_slices)
                   for p in pslice
                   if index in holes or index >= replicas]
        job.status.lost_indices[rtype] = sorted(holes)
        job.status.rendezvous_generation += 1
        job.status.resize_replica_name = rtype
        job.status.scale_probes.pop(rtype, None)
        job.status.last_scale_times[rtype] = time.time()
        self.metrics.inc("trainingjob_resizes_inplace_total")
        self.recorder.event(
            job, EventRecorder.NORMAL, constants.RESIZE_STARTED_REASON,
            f"resize scope=Resize trigger={trigger.name}: draining "
            f"{sorted(newly_lost)} of {rt}, keeping {survivors} survivor(s) "
            f"alive; rendezvous generation -> "
            f"{job.status.rendezvous_generation}")
        with TRACER.span("resize.drain", job=meta_namespace_key(job),
                         rtype=rt, victims=len(victims)):
            for p in victims:
                dead_node = (p.spec.node_name
                             and p.spec.node_name not in node_ready)
                g = 0 if (grace == 0 or dead_node
                          or p.status.phase == PodPhase.FAILED) else grace
                self.pod_control.delete_pod(p.namespace, p.name, job,
                                            grace_period=g)
        return TrainingJobPhase.SCALING, msg

    def publish_generation(self, job: TPUTrainingJob,
                           rtype: str) -> Dict[str, Any]:
        """Atomically publish the bumped rendezvous generation -- new world
        size + surviving host list -- into the job's resize dir.  Survivors
        poll the file from the step loop (workloads/rendezvous.py) and
        re-form the mesh in place; this is the injected-env/DNS analogue of
        republishing the rendezvous without recreating pods."""
        rt = rtype.lower()
        replicas = effective_replicas(job, rtype)
        holes = lost_indices(job, rtype)
        world = [i for i in range(replicas) if i not in holes]
        ports = get_ports_from_job(job, rtype)
        coord_port = ports[0] if ports else constants.DEFAULT_COORDINATOR_PORT
        instances = [f"{gen_general_name(job.name, rt, str(i))}.{job.namespace}"
                     for i in world]
        doc = {
            "generation": job.status.rendezvous_generation,
            "replica": rt,
            "world": world,
            "num_processes": len(world),
            "hosts": instances,
            "coordinator": f"{instances[0]}:{coord_port}" if instances else "",
        }
        base = resize_dir(job)
        # Bounded retry via the shared policy (client/retry.py): survivors
        # poll this file from the step loop, so a swallowed write failure
        # leaves them waiting on a generation that never arrives.  Three
        # jittered attempts ride out a transient filer hiccup without
        # stalling the reconcile worker; on exhaustion the failure becomes a
        # visible job event (ResizePublishFailed) instead of a log line
        # nobody watches.
        try:
            retry_call(
                _write_generation_doc, base, doc,
                policy=RetryPolicy(attempts=3, base_delay=0.05, max_delay=0.2),
                retryable=lambda err: isinstance(err, OSError),
                verb="publish_generation")
        except OSError as err:
            log.warning("failed to publish generation for %s/%s under %s",
                        job.namespace, job.name, base, exc_info=True)
            self.recorder.event(
                job, EventRecorder.WARNING,
                constants.RESIZE_PUBLISH_FAILED_REASON,
                f"failed to publish rendezvous generation "
                f"{job.status.rendezvous_generation} under {base} after 3 "
                f"attempts ({type(err).__name__}: {err}); survivors cannot "
                "re-rendezvous until the next reconcile republish")
        return doc

    # -- container inspection (reference: pod.go:328-437) --------------------

    def reconcile_containers(self, job: TPUTrainingJob, pod: Pod, rtype: str,
                             node_ready: Dict[str, bool]) -> Tuple[str, bool, str]:
        """Returns (phase, is_restart, message); phase "" means running/live."""
        spec = job.spec.replica_specs[rtype]
        exit_codes: List[int] = []
        failed_reasons: List[str] = []
        is_restart = False
        is_succeeded = True
        is_creating = False

        for status in pod.status.container_statuses:
            state = status.state
            if status.name.startswith(constants.CONTAINER_PREFIX):
                is_succeeded = is_succeeded and state.terminated
                if state.terminated:
                    code = state.terminated_exit_code or 0
                    is_succeeded = is_succeeded and code == 0
                    exit_codes.append(code)
                    if code != 0:
                        failed_reasons.append(
                            f"container {status.name} on node {pod.spec.node_name} "
                            f"exited with reason {state.terminated_reason} exitcode {code}")
            if state.waiting:
                is_creating = True
                if state.waiting_reason in constants.ERROR_CONTAINER_STATUS:
                    # Creation-failure backoff (pod.go:355-378).
                    ending = self._check_creating_failure(job, pod, state.waiting_reason)
                    if ending == "restart":
                        is_restart = True
                    elif ending == "fail":
                        return (TrainingJobPhase.FAILED, is_restart,
                                f"pod {pod.name} create container failed"
                                f"[{state.waiting_reason}] and has been retrying for "
                                f"{self.options.creating_restart_time} seconds")
                    failed_reasons.append(state.waiting_reason)

        restarting_exit_code = job.spec.restarting_exit_code

        # A resolved waiting error must clear its first-seen timer, or a later
        # recurrence on the same pod would inherit the stale timestamp and
        # restart instantly instead of after creating_duration_time.
        waiting_errors = self._waiting_errors
        if waiting_errors and not any(
                s.state.waiting
                and s.state.waiting_reason in constants.ERROR_CONTAINER_STATUS
                for s in pod.status.container_statuses):
            prefix = f"{pod.metadata.uid or pod.name}/"
            for k in [k for k in waiting_errors if k.startswith(prefix)]:
                waiting_errors.pop(k, None)

        if (pod.spec.node_name and pod.spec.node_name not in node_ready
                and (spec.edl_policy == EdlPolicy.AUTO
                     or pod.status.phase != PodPhase.FAILED)):
            # Node-failure detection (pod.go:407-419) -- for ELASTIC groups,
            # checked before the pod-failure branch: a pod that died
            # *because* its node died (SIGKILL exit 137 + node NotReady) is
            # capacity loss, and must take the shrink path, not a full-width
            # exit-code restart that would strand a replacement
            # Unschedulable for scale_pending_time.  Non-elastic groups keep
            # the reference order (pod.go:385-419): their FAILED branch
            # below still owns restart-or-fail, so a dead pod on a dead node
            # is not wedged with is_restart=False.
            if spec.restart_policy in (RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
                                       RestartPolicy.ON_NODE_FAIL,
                                       RestartPolicy.ALWAYS):
                is_restart = True
            elif pod.status.phase == PodPhase.FAILED:
                # The shrink path can decline (already at the width floor, or
                # a base pod SUCCEEDED); restartability must then come from
                # the pod-failure evaluation, or the group wedges with
                # is_restart=False.
                if (spec.restart_policy == RestartPolicy.EXIT_CODE
                        and is_retryable_exit_code(exit_codes,
                                                   restarting_exit_code)):
                    is_restart = True
                elif spec.restart_policy == RestartPolicy.ON_FAILURE:
                    is_restart = True
            return (TrainingJobPhase.NODE_FAIL, is_restart,
                    f"Node {pod.spec.node_name} is failed and offline")

        if pod.status.phase == PodPhase.FAILED:
            # Restart policy evaluation on pod failure (pod.go:385-405).
            if (spec.restart_policy in (RestartPolicy.EXIT_CODE,
                                        RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE)
                    and is_retryable_exit_code(exit_codes, restarting_exit_code)):
                is_restart = True
            elif spec.restart_policy in (RestartPolicy.ON_FAILURE, RestartPolicy.ALWAYS):
                is_restart = True
            if failed_reasons:
                message = "; ".join(failed_reasons)
            elif pod.status.reason:
                message = pod.status.reason
                if pod.status.message:
                    message = f"{pod.status.reason}, {pod.status.message}"
            else:
                message = ""
            if any(code != 0 for code in exit_codes):
                self._record_exited_with_code(job, pod, exit_codes, message)
            return TrainingJobPhase.FAILED, is_restart, message

        if is_creating:
            if failed_reasons:
                return TrainingJobPhase.CREATING, is_restart, "; ".join(failed_reasons)
            return TrainingJobPhase.CREATING, is_restart, "creating containers"
        if is_succeeded:
            return TrainingJobPhase.SUCCEEDED, is_restart, ""
        return TrainingJobPhase.NONE, is_restart, ""

    def _record_exited_with_code(self, job: TPUTrainingJob, pod: Pod,
                                 exit_codes: List[int], message: str) -> None:
        """One ExitedWithCode event per failed pod incarnation.

        reconcile_containers re-evaluates a Failed pod on every resync until
        it is deleted, and EventRecorder has no dedup -- without the per-uid
        guard a pod lingering at its restart limit would emit the same event
        every sync period.
        """
        reported = self._exited_reported
        uid = f"{pod.metadata.uid or pod.name}"
        if uid in reported:
            return
        while len(reported) >= 2048:   # bound memory across job churn
            reported.pop(next(iter(reported)))
        reported[uid] = True
        codes = sorted({code for code in exit_codes if code != 0})
        self.recorder.event(
            job, EventRecorder.WARNING, constants.EXITED_WITH_CODE_REASON,
            f"pod {pod.name} container(s) exited with code(s) "
            f"{', '.join(str(c) for c in codes)}"
            + (f": {message}" if message else ""))

    def _check_creating_failure(self, job: TPUTrainingJob, pod: Pod,
                                reason: str) -> str:
        """'', 'restart' or 'fail' (reference: pod.go:355-378).

        Unlike the reference, a waiting error is also handled when the job is
        already past Creating: a container that enters ImagePullBackOff after
        Running (image GC + node reboot) would otherwise never trigger
        restart-or-fail (VERDICT r3 Weak #6; ref pod.go:355-378 wedges).  The
        error is timed from when this controller first observed it.
        """
        now = time.time()
        creating = self._get_condition(job.status, TrainingJobPhase.CREATING)
        if creating is None or creating.status != ConditionStatus.TRUE:
            waiting = self._waiting_errors
            key = f"{pod.metadata.uid or pod.name}/{reason}"
            first = waiting.setdefault(key, now)
            if len(waiting) > 4096:  # bound memory across pod churn
                # Prune against the TIMER horizon: anything older than twice
                # creating_duration_time is a dead entry (a live one fires
                # "restart" and pops itself at the horizon).
                cutoff = now - 2 * max(self.options.creating_duration_time, 60.0)
                for k in [k for k, t in waiting.items() if t < cutoff]:
                    waiting.pop(k, None)
            if now - first > self.options.creating_duration_time:
                waiting.pop(key, None)
                log.warning("pod %s container waiting [%s] after Running; "
                            "restarting", pod.name, reason)
                return "restart"
            return ""
        since_creating = now - (creating.last_transition_time or now)
        if since_creating < self.options.creating_restart_time:
            started = pod.status.start_time or now
            if now - started > self.options.creating_duration_time:
                log.warning("pod %s create container failed: %s", pod.name, reason)
                return "restart"
        elif self.options.enable_creating_failed:
            return "fail"
        return ""

    # -- node health (reference: pod.go:439-455, via informer per SURVEY §8) -

    def get_node_status(self) -> Dict[str, bool]:
        """Ready-node map, flap-damped: a node NotReady for less than
        ``TRAININGJOB_NODE_FLAP_GRACE_S`` (default 0 = damping off, the
        historical behavior) is still reported ready, so a transient flap
        debounces instead of amplifying into a NODE_FAIL restart storm.
        Suppressed nodes land in ``self._flap_pending`` (name ->
        (not_ready_since, grace_deadline)); reconcile_pods re-queues
        affected jobs at the deadline so the suppression RESOLVES -- the
        node either recovered by then or NODE_FAIL fires one grace late."""
        grace = _env_float(constants.NODE_FLAP_GRACE_ENV, 0.0)
        now_ts = time.time()
        first_seen = self._flap_first_seen
        ready: Dict[str, bool] = {}
        pending: Dict[str, Tuple[float, float]] = {}
        for node in self.node_lister.list():
            if node.is_ready():
                ready[node.name] = True
                first_seen.pop(node.name, None)
                continue
            if grace <= 0.0:
                continue
            since = self._not_ready_since(node)
            if since is None:
                # No stamped transition (e.g. a conditionless node): time
                # the grace from our own first observation.
                while len(first_seen) >= 1024:  # bound across node churn
                    first_seen.pop(next(iter(first_seen)))
                since = first_seen.setdefault(node.name, now_ts)
            if now_ts - since < grace:
                ready[node.name] = True
                pending[node.name] = (since, since + grace)
        # analyzer: allow[unguarded-shared-state] whole-map swap is a
        # GIL-atomic rebind; node reconcile runs under the dedicated node
        # sync key, serialized to one worker at a time by the workqueue
        self._flap_pending = pending
        return ready

    @staticmethod
    def _not_ready_since(node: Node) -> Optional[float]:
        for cond in node.status.conditions:
            if (cond.type == NodeConditionType.READY
                    and cond.status != ConditionStatus.TRUE):
                return cond.last_transition_time
        return None

    def get_pod_scheduling_message(self, pod: Pod) -> str:
        """Reference: pod.go:457-467."""
        if pod.status.phase == PodPhase.PENDING and not pod.spec.node_name:
            for cond in pod.status.conditions:
                if (cond.type == PodConditionType.SCHEDULED
                        and cond.status == ConditionStatus.FALSE):
                    return cond.message
        return ""

    # -- pod creation (reference: pod.go:483-546) ----------------------------

    def create_new_pod(self, job: TPUTrainingJob, rt: str, index: str,
                       restart_count: str, spec: Any,
                       reservation: bool = False) -> None:
        job_key = meta_namespace_key(job)
        self.expectations.expect_creations(pods_key(job_key, rt), 1)

        labels = gen_labels(job.name)
        labels["JobName"] = job.name
        labels[constants.POD_ROLE_LABEL] = rt
        labels[constants.RESTART_COUNT_LABEL] = restart_count
        labels[constants.REPLICA_NAME_LABEL] = rt
        labels[constants.REPLICA_INDEX_LABEL] = index
        if job.spec.priority:
            labels[constants.PRIORITY_LABEL] = job.spec.priority

        template = copy.deepcopy(spec.template)
        pod = Pod(metadata=template.metadata, spec=template.spec)
        pod.metadata.name = gen_general_name(job.name, rt, index)
        pod.metadata.generate_name = gen_general_name(job.name, rt, "")
        pod.metadata.namespace = job.namespace
        for k, v in labels.items():
            pod.metadata.labels[k] = v
        for k, v in job.metadata.labels.items():
            pod.metadata.labels.setdefault(k, v)

        if job.spec.scheduler_name:
            pod.spec.scheduler_name = job.spec.scheduler_name

        self.set_env(pod, job, spec, rt, index, restart_count)
        if reservation:
            # Re-expand capacity canary: the workload idles instead of joining
            # a rendezvous whose world it is not part of
            # (rendezvous.hold_reservation_if_needed).  The TTL bounds how
            # long an orphaned canary (controller died mid-probe) can burn a
            # TPU host: it exits 143 -> Failed -> probe cancel on resync.
            ttl = max(self.options.scale_pending_time * 4, 120.0)
            for container in pod.spec.init_containers + pod.spec.containers:
                container.env.append(EnvVar(constants.RESERVATION_ENV, "1"))
                container.env.append(
                    EnvVar(constants.RESERVATION_TTL_ENV, str(ttl)))
        self.set_tpu_provisioning(pod, job, spec, rt, index)

        if spec.restart_policy:
            # The job-level restart machinery owns restarts; the kubelet must
            # not restart containers underneath it (pod.go:532-535).
            if pod.spec.restart_policy and pod.spec.restart_policy != "Never":
                # The user's template asked the kubelet for something else:
                # surface the override (once per pod creation) instead of
                # silently dropping their setting.
                self.recorder.event(
                    job, EventRecorder.WARNING,
                    constants.POD_TEMPLATE_RESTART_POLICY_REASON,
                    f"pod template restartPolicy "
                    f"{pod.spec.restart_policy!r} of {pod.metadata.name} is "
                    f"overridden to 'Never': the replica spec restart policy "
                    f"({spec.restart_policy}) owns restarts")
            pod.spec.restart_policy = "Never"

        self.pod_control.create_pod(job.namespace, pod, job)

    def force_delete_pod(self, namespace: str, name: str) -> None:
        """Reference: pod.go:469-481 (grace 0)."""
        try:
            self.clientset.pods.delete(namespace, name, grace_period=0)
        except KeyError:
            pass

    # -- env injection (reference: pod.go:548-652 + TPU mapping §3.5) --------

    def set_env(self, pod: Pod, job: TPUTrainingJob, spec: Any, rtype: str,
                index: str, restart_count: str) -> None:
        hosts_env: List[EnvVar] = []
        for rt_name in sorted(job.spec.replica_specs):
            rt = rt_name.lower()
            ports = get_ports_from_job(job, rt_name)
            n = effective_replicas(job, rt_name)
            instances = [f"{gen_general_name(job.name, rt, str(i))}.{job.namespace}"
                         for i in range(n)]
            hosts = [f"{name}:{port}" for name in instances for port in ports]
            upper = rt.upper()
            hosts_env += [
                EnvVar(f"{upper}_INSTANCES", ",".join(instances)),
                EnvVar(f"{upper}_INSTANCES_NUM", str(len(instances))),
                EnvVar(f"{upper}_PORTS", ",".join(str(p) for p in ports)),
                EnvVar(f"{upper}_PORTS_NUM", str(len(ports))),
                EnvVar(f"{upper}_HOSTS", ",".join(hosts)),
                EnvVar(f"{upper}_HOSTS_NUM", str(len(hosts))),
            ]
        hosts_env += [
            EnvVar(constants.REPLICA_NAME_ENV, rtype),
            EnvVar(constants.REPLICA_INDEX_ENV, index),
            EnvVar(constants.REPLICA_RESTART_COUNT_ENV, restart_count),
            EnvVar(constants.SERVICE_ENV,
                   f"{gen_general_name(job.name, rtype, index)}.{job.namespace}"),
            EnvVar(constants.JOB_NAME_ENV, job.name),
            EnvVar(constants.JOB_NAMESPACE_ENV, job.namespace),
            # Elastic-resize generation channel (docs/ELASTIC.md): where the
            # controller publishes bumped rendezvous generations, and the
            # epoch this pod is born into (it reacts only to greater ones).
            EnvVar(constants.RESIZE_DIR_ENV, resize_dir(job)),
            EnvVar(constants.RENDEZVOUS_GENERATION_ENV,
                   str(job.status.rendezvous_generation)),
        ]
        # Trace context, rendezvous-style: baked into the pod spec at create
        # time (we are inside the reconcile's sync_job span here), so the
        # workload's spans join the reconcile trace that created its pod.
        trace_ctx = current_context()
        if trace_ctx:
            hosts_env.append(EnvVar(constants.TRACE_CONTEXT_ENV, trace_ctx))
        # Telemetry sink address, same rendezvous pattern: the runtime that
        # will launch this pod published where step records should go
        # (obs/telemetry.py); absent -> the workload emitter is a no-op.
        telemetry_addr = sink_address()
        if telemetry_addr:
            hosts_env.append(EnvVar(constants.TELEMETRY_ADDR_ENV,
                                    telemetry_addr))
        hosts_env += self._jax_bootstrap_env(job, rtype, index)

        # Template env wins: the operator injects only names the user did not
        # set explicitly (e.g. a bench/test overriding TRAININGJOB_CHECKPOINT_DIR
        # must not be clobbered by the injected default -- stale shared
        # checkpoint dirs otherwise leak state across jobs).
        for container in pod.spec.init_containers:
            self._merge_env(container, hosts_env)
        for container in pod.spec.containers:
            self._merge_env(container, hosts_env + [
                EnvVar(constants.PORTS_ENV,
                       ",".join(get_ports_from_container(container)))])

    @staticmethod
    def _merge_env(container: Any, injected: List[EnvVar]) -> None:
        existing = {e.name for e in container.env}
        container.env.extend(copy.deepcopy(e) for e in injected
                             if e.name not in existing)

    def _jax_bootstrap_env(self, job: TPUTrainingJob, rtype: str,
                           index: str) -> List[EnvVar]:
        """TPU-native rendezvous: worker identity + coordinator address for
        ``jax.distributed.initialize`` (SURVEY.md §5.8)."""
        rt_key = self._match_replica_key(job, rtype)
        if rt_key is None:
            return []
        spec = job.spec.replica_specs[rt_key]
        n = effective_replicas(job, rt_key)
        ports = get_ports_from_job(job, rt_key)
        coord_port = ports[0] if ports else constants.DEFAULT_COORDINATOR_PORT
        instances = [f"{gen_general_name(job.name, rtype, str(i))}.{job.namespace}"
                     for i in range(n)]
        env = [
            EnvVar(constants.NUM_PROCESSES_ENV, str(n)),
            EnvVar(constants.PROCESS_ID_ENV, index),
            EnvVar(constants.COORDINATOR_ADDRESS_ENV, f"{instances[0]}:{coord_port}"),
            EnvVar(constants.TPU_WORKER_ID_ENV, index),
            EnvVar(constants.TPU_WORKER_HOSTNAMES_ENV, ",".join(instances)),
            EnvVar(constants.ELASTIC_REPLICAS_ENV, str(n)),
            EnvVar(constants.CHECKPOINT_DIR_ENV,
                   job.metadata.annotations.get(
                       "checkpoint-dir", f"/tmp/tpu-trainingjob/{job.namespace}/{job.name}")),
        ]
        if spec.tpu is not None:
            shape = resolve_slice_shape(spec.tpu)
            env += [
                EnvVar(constants.TPU_ACCELERATOR_ENV, shape.accelerator),
                EnvVar(constants.TPU_TOPOLOGY_ENV, shape.topology),
            ]
            # EFFECTIVE slice count: elastic width n is a whole number of
            # slices, so after a slice-granular shrink the megascale env
            # reflects the surviving DCN-dp width, not the declared one.
            num_slices = max(n // shape.hosts, 1) if shape.hosts else 1
            if spec.tpu.slice_count > 1:
                # Multislice: DCN data-parallel across slices (megascale env).
                slice_id = int(index) // shape.hosts
                env += [
                    EnvVar(constants.SLICE_ID_ENV, str(slice_id)),
                    EnvVar(constants.NUM_SLICES_ENV, str(num_slices)),
                    EnvVar(constants.MEGASCALE_COORDINATOR_ENV,
                           f"{instances[0]}:{constants.DEFAULT_COORDINATOR_PORT + 1}"),
                ]
        return env

    def set_tpu_provisioning(self, pod: Pod, job: TPUTrainingJob, spec: Any,
                             rt: str, index: str) -> None:
        """GKE TPU nodeSelectors + google.com/tpu resources + gang labels."""
        if spec.tpu is None:
            return
        shape = resolve_slice_shape(spec.tpu)
        pod.spec.node_selector.update(shape.node_selectors(spec.tpu.preemptible))
        for container in pod.spec.containers:
            limits = container.resources.setdefault("limits", {})
            requests = container.resources.setdefault("requests", {})
            for k, v in shape.tpu_resources().items():
                limits.setdefault(k, v)
                requests.setdefault(k, v)
        slice_id = int(index) // shape.hosts
        pod.metadata.labels[constants.SLICE_ID_LABEL] = str(slice_id)
        pod.metadata.labels[constants.GANG_LABEL] = gen_general_name(
            job.name, rt, f"slice{slice_id}")
        pod.metadata.labels[constants.GANG_SIZE_LABEL] = str(shape.hosts)

    @staticmethod
    def _match_replica_key(job: TPUTrainingJob, rt_lower: str) -> Optional[str]:
        for key in job.spec.replica_specs:
            if key.lower() == rt_lower:
                return key
        return None
