"""Pod/Service control: create/delete with owner references + events.

Reference: kubeflow-common ``RealPodControl`` / ``RealServiceControl``
(controller.go:94-102) -- the layer that stamps controller owner refs on
created objects and records events for every create/delete.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.core.objects import OwnerReference, Pod, Service
from trainingjob_operator_tpu.obs.trace import TRACER
from trainingjob_operator_tpu.utils.events import EventRecorder
from trainingjob_operator_tpu.utils.metrics import METRICS

log = logging.getLogger("trainingjob.control")


def gen_owner_reference(job: Any) -> OwnerReference:
    """Reference: GenOwnerReference (controller.go:161-173)."""
    return OwnerReference(
        api_version=constants.API_VERSION,
        kind=constants.KIND,
        name=job.metadata.name,
        uid=job.metadata.uid,
        controller=True,
        block_owner_deletion=True,
    )


def is_controlled_by(obj: Any, job: Any) -> bool:
    ref = obj.metadata.controller_of()
    return ref is not None and ref.uid == job.metadata.uid


class PodControl:
    def __init__(self, clientset: Any, recorder: EventRecorder):
        self._cs = clientset
        self._recorder = recorder

    def create_pod(self, namespace: str, pod: Pod, job: Any) -> Pod:
        with TRACER.span("create_pod", pod=pod.metadata.name):
            pod.metadata.namespace = namespace
            pod.metadata.owner_references = [gen_owner_reference(job)]
            created = self._cs.pods.create(pod)
        METRICS.inc("trainingjob_pods_created_total")
        self._recorder.event(job, EventRecorder.NORMAL, constants.SUCCESSFUL_CREATE_POD_REASON,
                             f"Created pod: {created.name}")
        return created

    def delete_pod(self, namespace: str, name: str, job: Any,
                   grace_period: Optional[int] = None) -> None:
        try:
            with TRACER.span("delete_pod", pod=name):
                self._cs.pods.delete(namespace, name, grace_period=grace_period)
        except KeyError:
            return
        METRICS.inc("trainingjob_pods_deleted_total")
        self._recorder.event(job, EventRecorder.NORMAL, constants.SUCCESSFUL_DELETE_POD_REASON,
                             f"Deleted pod: {name}")


class ServiceControl:
    def __init__(self, clientset: Any, recorder: EventRecorder):
        self._cs = clientset
        self._recorder = recorder

    def create_service(self, namespace: str, service: Service, job: Any) -> Service:
        with TRACER.span("create_service", service=service.metadata.name):
            service.metadata.namespace = namespace
            service.metadata.owner_references = [gen_owner_reference(job)]
            created = self._cs.services.create(service)
        self._recorder.event(job, EventRecorder.NORMAL, constants.SUCCESSFUL_CREATE_SERVICE_REASON,
                             f"Created service: {created.name}")
        return created

    def delete_service(self, namespace: str, name: str, job: Any) -> None:
        try:
            with TRACER.span("delete_service", service=name):
                self._cs.services.delete(namespace, name)
        except KeyError:
            return
        self._recorder.event(job, EventRecorder.NORMAL, constants.SUCCESSFUL_DELETE_SERVICE_REASON,
                             f"Deleted service: {name}")
