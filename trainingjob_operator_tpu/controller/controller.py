"""TrainingJobController: wiring, worker loop, sync gate, reconcile driver.

Reference: pkg/controller/controller.go + trainingjob.go.  The reconcile
semantics (sync-gate phases, restart-wait short-circuit, per-replica ending
aggregation, status write-back on change) follow controller.go:270-388; the
validation FIXME (trainingjob.go:21,33) is implemented for real: invalid specs
fail the job with a recorded event instead of being silently reconciled.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.defaults import set_defaults
from trainingjob_operator_tpu.api.types import (
    RECONCILABLE_PHASES,
    RestartScope,
    TrainingJobPhase,
    TPUTrainingJob,
)
from trainingjob_operator_tpu.api.validation import validate_job
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.expectations import (
    ControllerExpectations,
    pods_key,
    services_key,
)
from trainingjob_operator_tpu.client.informers import InformerFactory
from trainingjob_operator_tpu.client.retry import retrying_clientset
from trainingjob_operator_tpu.client.tracker import (
    meta_namespace_key,
    split_meta_namespace_key,
)
from trainingjob_operator_tpu.client.workqueue import RateLimitingQueue
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.control import PodControl, ServiceControl
from trainingjob_operator_tpu.controller.garbage_collection import GarbageCollector
from trainingjob_operator_tpu.api.tpu import resolve_slice_shape
from trainingjob_operator_tpu.controller.naming import effective_replicas, job_selector
from trainingjob_operator_tpu.controller.pod import PodReconciler
from trainingjob_operator_tpu.controller.pod_index import PodPhaseIndex
from trainingjob_operator_tpu.controller.service import ServiceReconciler
from trainingjob_operator_tpu.controller.status import StatusManager, update_job_conditions
from trainingjob_operator_tpu.core.objects import Node, OwnerReference, Pod, Service
from trainingjob_operator_tpu.obs.goodput import GOODPUT
from trainingjob_operator_tpu.obs.incident import INCIDENTS
from trainingjob_operator_tpu.obs.slo import SLOS, FleetSLO
from trainingjob_operator_tpu.obs.telemetry import TELEMETRY, peak_flops_for_accelerator
from trainingjob_operator_tpu.obs.trace import TRACER, current_context
from trainingjob_operator_tpu.utils.events import EventRecorder

log = logging.getLogger("trainingjob.controller")

# Buckets for millisecond-valued latency histograms (the registry default is
# seconds-scaled and would collapse everything into its top bucket).
LATENCY_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 15000.0, 60000.0)


def _material_status(status: Any) -> dict:
    """Status dict with the per-sync-volatile timestamps stripped, for the
    did-anything-change write gate.  ``update_job_conditions`` refreshes the
    current condition's lastProbeTime on every sync; writing that refresh
    back would echo a MODIFIED event that re-enqueues the job, whose sync
    refreshes the probe time again -- a self-sustaining write loop per idle
    job.  Phase, reasons, messages, counters, and transition times all still
    count as material."""
    d = status.to_dict()
    d.pop("lastReconcileTime", None)
    conds = d.get("conditions")
    if conds:
        d["conditions"] = [{k: v for k, v in c.items() if k != "lastProbeTime"}
                           for c in conds]
    return d


def job_index_key(obj: Any) -> Optional[str]:
    """Informer index key: "ns/jobname" from the operator's two-label
    selector (naming.job_selector), None for objects we never own.  Orphans
    carrying the labels index too -- adoption must still see them."""
    labels = obj.metadata.labels
    if labels.get(constants.GROUP_NAME_LABEL) != constants.GROUP_NAME:
        return None
    job_name = labels.get(constants.JOB_NAME_LABEL)
    if not job_name:
        return None
    return f"{obj.metadata.namespace}/{job_name}"


class TrainingJobController(PodReconciler, ServiceReconciler, StatusManager):
    """Reference: TrainingJobController (controller.go:37-159)."""

    def __init__(self, clientset: Clientset,
                 informer_factory: Optional[InformerFactory] = None,
                 options: Optional[OperatorOptions] = None):
        # All controller writes ride the shared bounded-retry-with-jitter
        # policy (client/retry.py): transient API faults (5xx, timeouts) are
        # absorbed at the clientset boundary instead of failing a whole sync
        # and re-running it through the workqueue ladder.
        self.clientset = retrying_clientset(clientset)
        self.options = options or OperatorOptions()
        self.informer_factory = informer_factory or InformerFactory(clientset.tracker)
        self.recorder = EventRecorder(self.clientset, constants.CONTROLLER_NAME)
        self.pod_control = PodControl(self.clientset, self.recorder)
        self.service_control = ServiceControl(self.clientset, self.recorder)
        self.expectations = ControllerExpectations()
        self.work_queue = RateLimitingQueue(
            constants.KIND,
            quarantine_after=self.options.quarantine_after,
            quarantine_delay=self.options.quarantine_delay)

        job_informer = self.informer_factory.informer(constants.KIND)
        pod_informer = self.informer_factory.informer(Pod.KIND)
        service_informer = self.informer_factory.informer(Service.KIND)
        self.trainingjob_lister = job_informer.lister
        self.pod_lister = pod_informer.lister
        self.service_lister = service_informer.lister
        node_informer = self.informer_factory.informer(Node.KIND)
        self.node_lister = node_informer.lister
        # Indexed cache lookups (get_pods_by_job/get_services_by_job read
        # these instead of relisting the store per reconcile).
        self.pod_informer = pod_informer
        self.service_informer = service_informer
        pod_informer.add_index(constants.JOB_INDEX, job_index_key)
        service_informer.add_index(constants.JOB_INDEX, job_index_key)
        pod_informer.add_index(constants.NODE_INDEX,
                               lambda pod: pod.spec.node_name or None)
        # O(changed-pods) status recomputation: one record per pod, updated
        # from informer deltas by the pod handlers below.
        self.pod_phase_index = PodPhaseIndex()
        # Job-key set maintained from informer add/delete deltas: feeds the
        # trainingjob_jobs gauge and the resync snapshot without O(all-jobs)
        # lister relists per scrape/tick.
        self._job_keys: Set[str] = set()
        self._job_keys_lock = threading.Lock()
        # Per-job reconcile memory consumed by the PodReconciler mixin.
        # Created here rather than lazily at first use so construction
        # happens-before the worker pool: two workers lazily installing
        # the same table would each get their own dict and silently drop
        # the other's entries.  Every key derives from the job (uid or
        # namespace/name) and the workqueue serializes a given job onto
        # one worker at a time, so per-key access needs no extra lock.
        self._gang_release_backoff: Dict[str, Tuple[float, int]] = {}
        self._crashloop: Dict[str, dict] = {}
        self._exited_reported: Dict[str, bool] = {}
        self._waiting_errors: Dict[str, float] = {}
        self._flap_episodes: Dict[str, dict] = {}
        self._flap_first_seen: Dict[str, float] = {}
        self._flap_pending: Dict[str, Tuple[float, float]] = {}

        # Handler registration (reference: controller.go:118-156).
        job_informer.add_event_handler(
            on_add=self.add_trainingjob,
            on_update=self.update_trainingjob,
            on_delete=self.delete_trainingjob,
        )
        pod_informer.add_event_handler(
            on_add=self.add_pod,
            on_update=self.update_pod,
            on_delete=self.delete_pod,
        )
        service_informer.add_event_handler(
            on_add=self.add_service,
            on_delete=self.on_service_deleted,
        )
        # Node readiness transitions drive NODE_FAIL detection event-style:
        # jobs with pods on the transitioning node reconcile NOW instead of
        # waiting out the resync period (docs/CHAOS.md hardened path).
        node_informer.add_event_handler(
            on_update=self.update_node,
            on_delete=self.delete_node,
        )

        self._workers: List[threading.Thread] = []
        self._resync_thread: Optional[threading.Thread] = None
        self._gc: Optional[GarbageCollector] = None
        self._stop = threading.Event()
        # Readiness gate for /readyz: set once run() has handlers registered
        # and workers started (in-process informers deliver synchronously, so
        # "started" is "synced"; a kube-backed informer factory would gate on
        # its own has_synced here).
        self._ready = threading.Event()
        # Observability: per-sync latency (SURVEY.md §5.1 asks for better than
        # the reference's V(4) log line).
        self.sync_count = 0
        self.sync_seconds_total = 0.0
        from trainingjob_operator_tpu.utils.metrics import METRICS

        self.metrics = METRICS

    # -- job event handlers (reference: trainingjob.go:17-51) ----------------

    def add_trainingjob(self, job: TPUTrainingJob) -> None:
        with self._job_keys_lock:
            self._job_keys.add(meta_namespace_key(job))
        self.enqueue_job(job)

    def update_trainingjob(self, old: TPUTrainingJob, cur: TPUTrainingJob) -> None:
        if old.metadata.resource_version == cur.metadata.resource_version:
            return
        # Deviation from the reference (trainingjob.go:29 AddRateLimited):
        # plain add.  Most MODIFIED events are echoes of our own status
        # writes; the delayed-heap path re-fires each echo individually,
        # while add() dedups against the ready queue and the in-flight key
        # (dirty-mark), collapsing a write burst into one re-sync.  Under
        # fleet churn this halves the sync count (docs/FLEET.md).
        self.enqueue_job(cur)
        # TimeLimit added/changed while running: arm a delayed re-sync
        # (trainingjob.go:38-45).
        if (cur.status.start_running_time is not None
                and cur.spec.time_limit is not None
                and (old.spec.time_limit is None
                     or old.spec.time_limit != cur.spec.time_limit)):
            passed = time.time() - cur.status.start_running_time
            self.enqueue_job(cur, delay=max(cur.spec.time_limit - passed, 0.0))

    def delete_trainingjob(self, job: TPUTrainingJob) -> None:
        with self._job_keys_lock:
            self._job_keys.discard(meta_namespace_key(job))
        self.enqueue_job(job)

    def enqueue_job(self, job: TPUTrainingJob, rate_limited: bool = False,
                    delay: float = 0.0) -> None:
        """Reference: enqueueJob (controller.go:406-421)."""
        key = meta_namespace_key(job)
        if rate_limited:
            self.work_queue.add_rate_limited(key)
            self.metrics.inc("trainingjob_workqueue_retries_total")
        elif delay > 0:
            self.work_queue.add_after(key, delay)
        else:
            self.work_queue.add(key)

    # -- node event handlers -------------------------------------------------

    def update_node(self, old: Node, cur: Node) -> None:
        if old.is_ready() == cur.is_ready():
            return
        self._enqueue_jobs_on_node(cur.name)

    def delete_node(self, node: Node) -> None:
        # A node object going away entirely is a readiness transition too.
        self._enqueue_jobs_on_node(node.name)

    def _enqueue_jobs_on_node(self, node_name: str) -> None:
        """Enqueue every job owning a pod placed on ``node_name`` (indexed
        lookup, O(pods-on-node))."""
        keys = set()
        for pod in self.pod_informer.by_index(constants.NODE_INDEX,
                                              node_name):
            job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL)
            if job_name:
                keys.add(f"{pod.metadata.namespace}/{job_name}")
        for key in keys:
            self.work_queue.add(key)

    def _resolve_controller_ref(self, namespace: str,
                                ref: Optional[OwnerReference]) -> Optional[TPUTrainingJob]:
        """Reference: resolveControllerRef (controller.go:424-440)."""
        if ref is None or ref.kind != constants.KIND:
            return None
        job = self.trainingjob_lister.try_get(namespace, ref.name)
        if job is None or job.metadata.uid != ref.uid:
            return None
        return job

    # -- run loop (reference: controller.go:182-268) -------------------------

    def run(self, workers: Optional[int] = None, wait: bool = False) -> None:
        n = workers or self.options.thread_num
        log.info("starting training-job controller with %d workers", n)
        # Gauges live exactly as long as the controller runs (a closure held
        # by the process-global registry would otherwise pin a stopped
        # instance and shadow the running one).
        self.metrics.gauge("trainingjob_workqueue_depth",
                           lambda: float(len(self.work_queue)))
        self.metrics.gauge("trainingjob_workqueue_depth_high_water",
                           lambda: float(self.work_queue.depth_high_water))
        # Counter maintained from informer add/delete deltas -- a scrape must
        # not pay an O(all-jobs) lister relist (at 10k jobs that is 10k
        # deepcopies per scrape).
        self.metrics.gauge("trainingjob_jobs",
                           lambda: float(len(self._job_keys)))
        self.metrics.gauge("trainingjob_quarantined_keys",
                           lambda: float(self.work_queue.num_quarantined()))
        # Telemetry watchdog findings (StepStalled/StepResumed) become job
        # events and a reconcile kick so the Running message refreshes.
        TELEMETRY.set_event_sink(self._telemetry_event)
        # Incident flight recorder: every recorded job event feeds its
        # timeline ring (the create/delete/restart markers attribution
        # needs), and assembled bundles announce themselves back through the
        # same event plumbing as IncidentRecorded.
        self.recorder.set_sink(self._incident_event_tap)
        INCIDENTS.set_event_sink(self._telemetry_event)
        # Fleet SLO plane (docs/SLO.md): burn-rate breach/recovery verdicts
        # surface as fleet-scoped events through the same recorder.  The
        # engine itself only runs when something starts it (harness --slo,
        # cmd --slo-plane); wiring the sink is free.
        SLOS.set_event_sink(self._slo_event)
        for i in range(n):
            th = threading.Thread(target=self._worker, daemon=True,
                                  name=f"trainingjob-worker-{i}")
            th.start()
            self._workers.append(th)
        self._resync_thread = threading.Thread(target=self._resync_loop, daemon=True,
                                               name="trainingjob-resync")
        self._resync_thread.start()
        self._gc = GarbageCollector(self.clientset, self.trainingjob_lister)
        gc_thread = threading.Thread(
            target=self._gc.run, args=(self.options.gc_interval,), daemon=True,
            name="trainingjob-gc")
        gc_thread.start()
        self._ready.set()
        if wait:
            # analyzer: allow[reconcile-purity]: parks the *caller's* thread
            # until stop(); reconcile runs on the workqueue workers above.
            self._stop.wait()

    def ready(self) -> bool:
        """Informer-synced gate backing the /readyz endpoint."""
        return self._ready.is_set() and not self._stop.is_set()

    def stop(self) -> None:
        self.metrics.remove_gauge("trainingjob_workqueue_depth")
        self.metrics.remove_gauge("trainingjob_workqueue_depth_high_water")
        self.metrics.remove_gauge("trainingjob_jobs")
        self.metrics.remove_gauge("trainingjob_quarantined_keys")
        TELEMETRY.set_event_sink(None)
        INCIDENTS.set_event_sink(None)
        SLOS.set_event_sink(None)
        self.recorder.set_sink(None)
        self._ready.clear()
        self._stop.set()
        if self._gc is not None:
            self._gc.stop()
        self.work_queue.shut_down()
        for th in self._workers:
            th.join(timeout=2)
        if self._resync_thread is not None:
            self._resync_thread.join(timeout=2)
            self._resync_thread = None

    def _incident_event_tap(self, obj: Any, reason: str,
                            message: str) -> None:
        """EventRecorder sink: mirror every job-scoped event into the
        incident flight recorder's timeline ring.  Pod create/delete events
        are recorded against the owning job (controller/control.py), so one
        KIND filter captures every marker attribution needs.  Reasons the
        recorder itself raised (IncidentRecorded) land in the ring too but
        trigger nothing -- no feedback loop."""
        if getattr(obj, "KIND", None) != constants.KIND:
            return
        INCIDENTS.record_event(meta_namespace_key(obj), reason, message)

    def _telemetry_event(self, key: str, reason: str, message: str) -> None:
        """Telemetry watchdog callback (runs on sink/runtime threads): record
        the finding as a job event and wake the reconciler so the Running
        condition message picks up the new snapshot."""
        namespace, name = split_meta_namespace_key(key)
        job = self.trainingjob_lister.try_get(namespace, name)
        if job is None:
            return
        etype = (EventRecorder.WARNING
                 if reason == constants.STEP_STALLED_REASON
                 else EventRecorder.NORMAL)
        self.recorder.event(job, etype, reason, message)
        self.enqueue_job(job, rate_limited=True)

    def _slo_event(self, slo_name: str, reason: str, message: str) -> None:
        """SLO engine callback (runs on the engine's timer thread): a
        breach/recovery transition becomes a fleet-scoped event against a
        synthetic FleetSLO object -- kubectl-visible without attributing a
        fleet property to any one job.  The incident tap's KIND filter
        keeps these out of per-job incident rings."""
        etype = (EventRecorder.WARNING
                 if reason == constants.SLO_BREACH_REASON
                 else EventRecorder.NORMAL)
        self.recorder.event(FleetSLO(slo_name), etype, reason, message)

    def _resync_loop(self) -> None:
        """Periodic full re-enqueue (reference: informer resync, 10 s),
        sharded and jittered for fleet scale: one snapshot of the informer-
        maintained key set per period (no O(all-jobs) lister relist), split
        into ``resync_shards`` hash-stable buckets enqueued evenly across the
        period -- 10k jobs arrive as a drizzle the workers absorb, not a
        single enqueue-storm that spikes queue depth and event->visible
        latency for everything behind it."""
        shards = max(1, int(self.options.resync_shards))
        interval = self.options.resync_period / shards
        while not self._stop.is_set():
            with self._job_keys_lock:
                keys = list(self._job_keys)
            namespace = self.options.namespace
            if namespace:
                keys = [k for k in keys if k.split("/", 1)[0] == namespace]
            buckets: List[List[str]] = [[] for _ in range(shards)]
            for key in keys:
                # crc32, not hash(): per-key phase must be stable across runs
                # (PYTHONHASHSEED randomizes str hashing per process).
                buckets[zlib.crc32(key.encode("utf-8")) % shards].append(key)
            for bucket in buckets:
                if self._stop.wait(interval):
                    return
                for key in bucket:
                    self.work_queue.add(key)

    def _worker(self) -> None:
        """Reference: worker/processNextWorkItem (controller.go:236-268)."""
        while self.process_next_work_item():
            pass

    def process_next_work_item(self, timeout: Optional[float] = None) -> bool:
        item, shutdown = self.work_queue.get(timeout=timeout)
        if shutdown:
            return False
        if item is None:
            return True
        started = time.monotonic()
        queue_wait = self.work_queue.pop_wait(item) or 0.0
        try:
            forget = self.sync_handler(item)
            if forget:
                self.work_queue.forget(item)
            else:
                if self.work_queue.add_rate_limited(item):
                    self._note_quarantined(item)
                self.metrics.inc("trainingjob_workqueue_retries_total")
        except Exception:
            log.exception("sync %r failed", item)
            if self.work_queue.add_rate_limited(item):
                self._note_quarantined(item)
            self.metrics.inc("trainingjob_workqueue_retries_total")
        finally:
            self.work_queue.done(item)
            # Enqueue -> reconcile-finished: queue wait plus sync duration.
            self.metrics.observe(
                "trainingjob_reconcile_latency_ms",
                (queue_wait + time.monotonic() - started) * 1000.0,
                buckets=LATENCY_MS_BUCKETS)
        return True

    def _note_quarantined(self, key: str) -> None:
        """A key just crossed the quarantine threshold: surface it once per
        episode (the workqueue reports only the transition) so a poisoned
        job is visible on the job's event stream, not just in logs."""
        log.warning("sync %r failed %d consecutive times; quarantined for %.0fs",
                    key, self.work_queue.num_requeues(key),
                    self.options.quarantine_delay)
        try:
            namespace, name = split_meta_namespace_key(key)
        except ValueError:
            return  # unkeyable item: the log line above is all we can say
        job = self.trainingjob_lister.try_get(namespace, name)
        if job is not None:
            self.recorder.event(
                job, EventRecorder.WARNING, constants.SYNC_QUARANTINED_REASON,
                f"sync failed {self.work_queue.num_requeues(key)} consecutive "
                f"times; retrying every {self.options.quarantine_delay:.0f}s "
                "until one succeeds")

    # -- sync (reference: syncHandler, controller.go:270-312) ----------------

    def sync_handler(self, key: str) -> bool:
        start = time.time()
        try:
            # Root span of the reconcile trace; every child below (expectation
            # check, pod diff, control calls, status write) auto-parents.
            with TRACER.span("sync_job", job=key) as root:
                namespace, name = split_meta_namespace_key(key)
                job = self.trainingjob_lister.try_get(namespace, name)
                if job is None:
                    self.expectations.delete_expectations(key)
                    GOODPUT.forget(key)
                    TELEMETRY.forget(key)
                    INCIDENTS.forget(key)
                    root.set_attribute("outcome", "gone")
                    return True

                with TRACER.span("check_expectations"):
                    satisfied = self.satisfied_expectations(job)
                if not satisfied:
                    root.set_attribute("outcome", "expectations_pending")
                    return True

                with TRACER.span("validate"):
                    set_defaults(job)
                    violations = validate_job(job)
                if violations:
                    # Real validation (reference FIXME, trainingjob.go:21).
                    msg = "; ".join(violations)
                    self.recorder.event(job, EventRecorder.WARNING,
                                        constants.VALIDATION_FAILED_REASON, msg)
                    root.set_attribute("outcome", "invalid")
                    if job.status.phase != TrainingJobPhase.FAILED:
                        update_job_conditions(job, TrainingJobPhase.FAILED,
                                              constants.FAILED_REASON,
                                              f"invalid spec: {msg}")
                        self.update_trainingjob_phase(job)
                    return True

                if (job.metadata.deletion_timestamp is None
                        and job.status.phase in RECONCILABLE_PHASES):
                    self.reconcile_trainingjobs(job)
                root.set_attribute("phase", job.status.phase)
                return True
        finally:
            self.sync_count += 1
            dt = time.time() - start
            self.sync_seconds_total += dt
            self.metrics.inc("trainingjob_syncs_total")
            self.metrics.observe("trainingjob_reconcile_seconds", dt)

    def satisfied_expectations(self, job: TPUTrainingJob) -> bool:
        """All replica groups' in-flight creates/deletes observed
        (reference: controller.go:390-404; the reference ORs which can sync
        too early -- AND is the correct gate)."""
        key = meta_namespace_key(job)
        for rtype in job.spec.replica_specs:
            rt = rtype.lower()
            if not self.expectations.satisfied(pods_key(key, rt)):
                return False
            if not self.expectations.satisfied(services_key(key, rt)):
                return False
        return True

    # -- reconcile driver (reference: reconcileTrainingJobs,
    #    controller.go:314-388) ----------------------------------------------

    def _register_peak_flops(self, job: TPUTrainingJob, job_key: str) -> None:
        """Derive the job's aggregate peak FLOP/s from its TPU specs so the
        aggregator can turn achieved FLOPs into an MFU ratio.  Replica specs
        without a TPU (or with an unknown accelerator) contribute nothing;
        workloads may still self-report a peak via TRAININGJOB_PEAK_FLOPS."""
        peak = 0.0
        for rtype, spec in job.spec.replica_specs.items():
            if spec.tpu is None:
                continue
            try:
                shape = resolve_slice_shape(spec.tpu)
            except ValueError:
                continue
            per_chip = peak_flops_for_accelerator(shape.accelerator)
            peak += (effective_replicas(job, rtype)
                     * shape.chips_per_host * per_chip)
        if peak > 0.0:
            TELEMETRY.set_peak_flops(job_key, peak)

    def reconcile_trainingjobs(self, job: TPUTrainingJob) -> None:
        old_status = job.deepcopy().status
        old_annotations = dict(job.metadata.annotations)
        selector = job_selector(job.name)
        with TRACER.span("list_owned") as sp:
            pods = self.get_pods_by_job(job, selector)
            services = self.get_services_by_job(job, selector)
            sp.set_attribute("pods", len(pods))
            sp.set_attribute("services", len(services))

        job_key = meta_namespace_key(job)
        self._register_peak_flops(job, job_key)
        ending_phases: Dict[str, str] = {}
        aggregation_msg: List[str] = []
        if (not job.status.restart_replica_name
                and not job.status.scaling_replica_name
                and not job.status.resize_replica_name):
            for rtype in sorted(job.spec.replica_specs):
                with TRACER.span("reconcile_pods", rtype=rtype) as sp:
                    ending_phase, msg = self.reconcile_pods(job, pods, rtype)
                    if ending_phase:
                        sp.set_attribute("ending_phase", ending_phase)
                if msg and msg not in aggregation_msg:
                    aggregation_msg.append(msg)
                if ending_phase == TrainingJobPhase.RESTARTING:
                    # Two-phase restart: deletes issued; flip to Terminating
                    # and stall further reconcile until pods drain
                    # (controller.go:362-366).
                    update_job_conditions(
                        job, TrainingJobPhase.TERMINATING,
                        constants.TERMINATING_REASON, msg)
                    job.status.restart_replica_name = rtype
                    # One shared clock for both ledgers: the incident
                    # bundle's control window must reconcile byte-for-byte
                    # against the goodput downtime window.
                    now = time.time()
                    scope = job.spec.replica_specs[rtype].restart_scope
                    GOODPUT.on_interruption(job_key, scope, now=now)
                    INCIDENTS.on_interruption(
                        job_key, scope, constants.RESTARTING_REASON,
                        now=now, trace=current_context())
                    TELEMETRY.on_interruption(job_key)
                    break
                if ending_phase == TrainingJobPhase.SCALING:
                    if job.status.resize_replica_name == rtype:
                        # In-place resize (scope Resize): survivors stay up,
                        # so no Terminating flip and no scaling marker --
                        # the resize drain in update_status waits only for
                        # the victim pods before republishing the
                        # rendezvous generation.
                        update_job_conditions(
                            job, TrainingJobPhase.SCALING,
                            constants.SCALING_REASON, msg)
                        now = time.time()
                        GOODPUT.on_interruption(
                            job_key, RestartScope.RESIZE, now=now)
                        INCIDENTS.on_interruption(
                            job_key, RestartScope.RESIZE,
                            constants.RESIZE_STARTED_REASON,
                            now=now, trace=current_context())
                        TELEMETRY.on_interruption(job_key)
                        break
                    # Elastic resize: same two-phase drain, scaling marker.
                    update_job_conditions(
                        job, TrainingJobPhase.SCALING,
                        constants.SCALING_REASON, msg)
                    job.status.scaling_replica_name = rtype
                    now = time.time()
                    GOODPUT.on_interruption(job_key, "scale", now=now)
                    INCIDENTS.on_interruption(
                        job_key, "scale", constants.SCALING_REASON,
                        now=now, trace=current_context())
                    TELEMETRY.on_interruption(job_key)
                    break
                if ending_phase:
                    ending_phases[rtype] = ending_phase
                    continue
                with TRACER.span("reconcile_services", rtype=rtype):
                    self.reconcile_services(job, services, rtype)

        message = "; ".join(aggregation_msg)
        with TRACER.span("update_status"):
            self.update_status(job, pods, services, ending_phases, message)
        if (_material_status(job.status) != _material_status(old_status)
                or job.metadata.annotations != old_annotations):
            job.status.last_reconcile_time = time.time()
            with TRACER.span("write_status", phase=job.status.phase):
                self.update_trainingjob_phase(job)
