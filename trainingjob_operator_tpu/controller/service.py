"""Service reconciler: per-index headless Services for stable DNS.

Reference: pkg/controller/service.go -- port extraction from ``aitj-``-prefixed
containers/ports (service.go:19-52), claim/adopt (service.go:90-115),
create-if-missing per index (service.go:117-196).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.client.expectations import services_key
from trainingjob_operator_tpu.client.tracker import meta_namespace_key
from trainingjob_operator_tpu.controller.naming import (
    effective_replicas,
    filter_for_replica_type,
    gen_general_name,
    gen_labels,
    get_slices,
    pod_index,
)
from trainingjob_operator_tpu.core.objects import Container, Service, ServicePort, ServiceSpec

log = logging.getLogger("trainingjob.service")


def get_ports_from_job(job: TPUTrainingJob, rtype: str) -> List[int]:
    """Ports of ``aitj-``-prefixed ports in ``aitj-``-prefixed containers
    (reference: service.go:19-31)."""
    ports: List[int] = []
    for container in job.spec.replica_specs[rtype].template.spec.containers:
        if container.name.startswith(constants.CONTAINER_PREFIX):
            for port in container.ports:
                if port.name.startswith(constants.PORT_PREFIX):
                    ports.append(port.container_port)
    return ports


def get_ports_from_container(container: Container) -> List[str]:
    """Reference: service.go:33-43."""
    if not container.name.startswith(constants.CONTAINER_PREFIX):
        return []
    return [str(p.container_port) for p in container.ports
            if p.name.startswith(constants.PORT_PREFIX)]


def has_container_port(job: TPUTrainingJob, rtype: str) -> bool:
    """Reference: service.go:45-52."""
    return any(c.name.startswith(constants.CONTAINER_PREFIX)
               for c in job.spec.replica_specs[rtype].template.spec.containers)


class ServiceReconciler:
    """Mixin for TrainingJobController (reference: service.go methods)."""

    def add_service(self, service: Service) -> None:
        """Reference: service.go:54-81."""
        if service.metadata.deletion_timestamp is not None:
            return
        job = self._resolve_controller_ref(service.metadata.namespace,
                                           service.metadata.controller_of())
        if job is None:
            return
        rt = service.metadata.labels.get(constants.REPLICA_NAME_LABEL)
        if rt is None:
            return
        self.expectations.creation_observed(
            services_key(meta_namespace_key(job), rt))
        self.work_queue.add(meta_namespace_key(job))

    # updateService/deleteService are empty stubs in the reference
    # (service.go:83-88); a deleted service is recreated on the next sync via
    # resync, so we enqueue on delete to converge faster.
    def on_service_deleted(self, service: Service) -> None:
        job = self._resolve_controller_ref(service.metadata.namespace,
                                           service.metadata.controller_of())
        if job is not None:
            self.work_queue.add(meta_namespace_key(job))

    def get_services_by_job(self, job: TPUTrainingJob,
                            selector: Dict[str, str]) -> List[Service]:
        # Indexed informer-cache lookup, same shape as get_pods_by_job.
        informer = getattr(self, "service_informer", None)
        if informer is not None:
            all_services = informer.by_index(
                constants.JOB_INDEX, f"{job.namespace}/{job.name}")
        else:
            all_services = self.service_lister.list(job.namespace, selector)
        claimed = []
        for svc in all_services:
            ref = svc.metadata.controller_of()
            if ref is not None and ref.uid == job.metadata.uid:
                claimed.append(svc)
        return claimed

    def reconcile_services(self, job: TPUTrainingJob, services: List[Service],
                           rtype: str) -> None:
        """Reference: service.go:117-146."""
        ports = get_ports_from_job(job, rtype)
        rt = rtype.lower()
        replicas = effective_replicas(job, rtype)
        rt_services = filter_for_replica_type(services, rt)
        service_slices = get_slices(rt_services, replicas)
        for index, service_slice in enumerate(service_slices):
            if not service_slice and has_container_port(job, rtype):
                self.create_new_service(job, rtype, str(index), ports)
        # Elastic shrink leaves services beyond the current width; remove them
        # so DNS reflects the live world (the reference never deletes services,
        # service.go:83-88 -- but it also never resizes).
        for svc in rt_services:
            idx = pod_index(svc)
            if idx is not None and idx >= replicas:
                self.service_control.delete_service(svc.metadata.namespace,
                                                    svc.metadata.name, job)

    def create_new_service(self, job: TPUTrainingJob, rtype: str, index: str,
                           ports: List[int]) -> None:
        """Headless service selecting the one pod at (rtype, index)
        (reference: service.go:148-196)."""
        rt = rtype.lower()
        self.expectations.expect_creations(
            services_key(meta_namespace_key(job), rt), 1)
        labels = gen_labels(job.name)
        labels[constants.REPLICA_NAME_LABEL] = rt
        labels[constants.REPLICA_INDEX_LABEL] = index
        service = Service(
            spec=ServiceSpec(
                cluster_ip="None",
                selector=dict(labels),
                ports=[ServicePort(name=f"{constants.PORT_PREFIX}{p}", port=p)
                       for p in ports],
            ),
        )
        service.metadata.name = gen_general_name(job.name, rt, index)
        service.metadata.labels = labels
        self.service_control.create_service(job.namespace, service, job)
