"""Naming and selection helpers shared across the controller.

Reference: GenGeneralName (trainingjob.go:12-15), GenLabels
(controller.go:175-180), FilterPodsForReplicaType / GetPodSlices
(pod.go:654-696), exit-code matching (controller.go:442-462).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from trainingjob_operator_tpu.api import constants


def gen_general_name(job_name: str, rtype: str, index: str) -> str:
    """'job-rtype-index' (reference: trainingjob.go:12-15)."""
    return f"{job_name}-{rtype}-{index}".replace("/", "-")


def gen_labels(job_name: str) -> Dict[str, str]:
    """Reference: controller.go:175-180."""
    return {
        constants.GROUP_NAME_LABEL: constants.GROUP_NAME,
        constants.JOB_NAME_LABEL: job_name.replace("/", "-"),
    }


def job_selector(job_name: str) -> Dict[str, str]:
    """Reference: reconcileTrainingJobs selector (controller.go:318-323)."""
    return gen_labels(job_name)


def filter_for_replica_type(objects: Sequence[Any], replica_type: str) -> List[Any]:
    """Reference: FilterPodsForReplicaType (pod.go:654-674)."""
    return [o for o in objects
            if o.metadata.labels.get(constants.REPLICA_NAME_LABEL) == replica_type]


def get_slices(objects: Sequence[Any], replicas: int) -> List[List[Any]]:
    """Bucket objects by their index label into ``replicas`` slots; out-of-range
    indices are dropped (reference: GetPodSlices, pod.go:676-696)."""
    slices: List[List[Any]] = [[] for _ in range(replicas)]
    for obj in objects:
        raw = obj.metadata.labels.get(constants.REPLICA_INDEX_LABEL)
        if raw is None:
            continue
        try:
            index = int(raw)
        except ValueError:
            continue
        if 0 <= index < replicas:
            slices[index].append(obj)
    return slices


def full_width(spec: Any) -> int:
    """Elastic expansion target: maxReplicas when set (live semantics, unlike
    the reference's dead field, SURVEY.md §2.6), else the declared width."""
    desired = spec.replicas if spec.replicas is not None else 1
    if spec.max_replicas is not None:
        return max(desired, spec.max_replicas)
    return desired


def pod_index(obj: Any) -> Optional[int]:
    """The replica-index label as an int, or None when absent/garbled."""
    raw = obj.metadata.labels.get(constants.REPLICA_INDEX_LABEL, "")
    return int(raw) if raw.isdigit() else None


def pods_below_width(objects: Sequence[Any], width: int) -> List[Any]:
    """Objects whose index is inside the current elastic width.  Reservation
    (probe) pods and not-yet-drained out-of-range pods sit above it and must
    not count toward the group's replica status."""
    return [o for o in objects
            if (idx := pod_index(o)) is not None and idx < width]


def is_retryable_exit_code(exit_codes: Sequence[int], restarting_exit_code: str) -> bool:
    """True iff every observed non-zero exit code is in the configured retry
    set (reference: isRetryableExitCode, controller.go:442-452 -- AND over
    codes, False when no codes observed)."""
    if not exit_codes:
        return False
    allowed = {tok.strip() for tok in restarting_exit_code.split(",") if tok.strip()}
    return all(str(code) in allowed for code in exit_codes)


def gang_size(spec: Any) -> int:
    """Pods per co-scheduled gang: hosts-per-slice for multi-host TPU groups
    (every TPU-VM host of a slice must run together -- ICI is slice-wide and
    JAX cannot initialize below full host count), else 1.

    This is the unit of account for elastic width changes: a multi-host
    group only ever resizes by whole slices (VERDICT r3 Missing #2 -- a
    sub-slice of stranded hosts is not physically runnable on GKE, the
    surviving pods' gke-tpu-topology nodeSelector still demands the full
    slice)."""
    tpu = getattr(spec, "tpu", None)
    if tpu is None:
        return 1
    from trainingjob_operator_tpu.api.tpu import resolve_slice_shape

    return resolve_slice_shape(tpu).hosts


def round_to_gang(width: int, gang: int, up: bool = False) -> int:
    """Clamp a width to a whole number of gangs (floor by default)."""
    if gang <= 1:
        return width
    if up:
        return -(-width // gang) * gang
    return width // gang * gang


def lost_indices(job: Any, rtype: str) -> frozenset:
    """Replica indices vacated by an in-place resize (scope Resize,
    docs/ELASTIC.md): holes inside the nominal width that the reconciler
    must not refill -- recreating a lost middle index would force a full
    re-rendezvous and defeat the survivor-keepalive fast path.  Holes heal
    through the re-expand probe -> restart-the-world path."""
    return frozenset(job.status.lost_indices.get(rtype, ()))


def live_replicas(job: Any, rtype: str) -> int:
    """The group's actual world size: the elastic width minus resize holes.
    This is what convergence (``rs.active == live``) and the published
    rendezvous world must count -- ``effective_replicas`` still spans the
    index *range* including holes."""
    return max(effective_replicas(job, rtype) - len(lost_indices(job, rtype)), 0)


def effective_replicas(job: Any, rtype: str) -> int:
    """Elastic width: the number of replicas currently provisioned.

    Defaults to ``spec.replicas``; while elastically degraded the controller
    records a narrower width in ``status.elastic_replicas`` (clamped to
    [min_replicas, max_replicas]).  New semantics -- the reference never
    resizes (SURVEY.md §2.6).
    """
    spec = job.spec.replica_specs[rtype]
    desired = spec.replicas if spec.replicas is not None else 1
    width = job.status.elastic_replicas.get(rtype, desired)
    lo = spec.min_replicas if spec.min_replicas is not None else desired
    hi = spec.max_replicas if spec.max_replicas is not None else desired
    return max(min(width, hi), min(lo, hi), 0)
