"""Sample from a trained Llama checkpoint -- the serve half of the loop.

Loads the llama_elastic checkpoint (same shared path contract,
workloads/train.py CheckpointState) and autoregressively decodes with the KV
cache (models/decode.py).  The reference operator never serves models (it is
a control plane, SURVEY.md §0); this exists so a checkpoint produced by the
elastic trainer is demonstrably usable, end to end, inside the same
framework.

Run: ``python -m trainingjob_operator_tpu.workloads.generate``.
Env: GEN_FAMILY=llama|moe (which trainer's checkpoint to sample --
llama_elastic's or moe_pretrain's), LLAMA_CONFIG=tiny|7b /
MOE_CONFIG=tiny|8x7b, GEN_STEPS (tokens to sample, default 32),
GEN_BATCH (parallel samples, default 1), GEN_TEMPERATURE (0 = greedy),
GEN_TOP_K / GEN_TOP_P (restrict the sampling support; need temperature),
GEN_SEED, GEN_PROMPT (comma-separated token ids; default "1"),
GEN_QUANT=1 (weight-only int8 decode, models/quant.py -- halves the HBM
bytes that bound decode throughput), LLAMA_WINDOW (sliding-window span;
MUST match the value the checkpoint was trained with),
TRAININGJOB_CHECKPOINT_DIR (the trainer's checkpoint root).
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import jax.numpy as jnp

    family = os.environ.get("GEN_FAMILY", "llama")
    if family == "moe":
        from trainingjob_operator_tpu.models import moe
        from trainingjob_operator_tpu.models import moe_decode as decode_mod

        cfg = (moe.MoEConfig.mixtral_8x7b()
               if os.environ.get("MOE_CONFIG", "tiny") == "8x7b"
               else moe.MoEConfig.tiny())
        init_params, subdir = moe.init_params, "moe"
        window = int(os.environ.get("MOE_WINDOW", "0"))
    else:
        from trainingjob_operator_tpu.models import decode as decode_mod
        from trainingjob_operator_tpu.models import llama

        cfg = (llama.LlamaConfig.llama2_7b()
               if os.environ.get("LLAMA_CONFIG", "tiny") == "7b"
               else llama.LlamaConfig.tiny())
        init_params, subdir = llama.init_params, "llama"
        window = int(os.environ.get("LLAMA_WINDOW", "0"))
    if window:
        # Decode with the same attention pattern the checkpoint was
        # trained with (the trainer's {P}_WINDOW).
        import dataclasses

        cfg = dataclasses.replace(cfg, sliding_window=window)
    steps = int(os.environ.get("GEN_STEPS", "32"))
    batch = int(os.environ.get("GEN_BATCH", "1"))
    temperature = float(os.environ.get("GEN_TEMPERATURE", "0"))
    top_k = int(os.environ.get("GEN_TOP_K", "0"))
    top_p = float(os.environ.get("GEN_TOP_P", "0"))
    seed = int(os.environ.get("GEN_SEED", "0"))
    quantize = os.environ.get("GEN_QUANT", "") in ("1", "true")
    prompt_ids = [int(x) for x in
                  os.environ.get("GEN_PROMPT", "1").split(",")]

    # The placeholder skips the AdamW moments entirely: a 7B checkpoint holds
    # ~2x the params in optimizer state the sampler never uses -- restoring
    # it would triple restore IO and can OOM a host that fits params alone.
    state = train.CheckpointState.restore_or_init(
        rdv, {"params": init_params(cfg, jax.random.PRNGKey(0)),
              "opt_state": train.ckpt_placeholder(), "step": 0},
        subdir=subdir)
    step = int(state.value["step"])
    params = state.value["params"]
    if step == 0:
        print("warning: no checkpoint found, sampling from random init",
              flush=True)
    else:
        print(f"sampling from checkpoint at step {step}", flush=True)

    prompt = jnp.broadcast_to(jnp.asarray(prompt_ids, jnp.int32)[None, :],
                              (batch, len(prompt_ids)))
    gen_kwargs = dict(steps=steps, temperature=temperature, top_k=top_k,
                      top_p=top_p,
                      key=jax.random.PRNGKey(seed) if temperature > 0
                      else None)
    if family != "moe":
        # Weight-only int8 is the Llama decode path's knob (models/quant.py)
        gen_kwargs["quantize"] = quantize
        if quantize:
            print("decoding with weight-only int8", flush=True)
    elif quantize:
        print("warning: GEN_QUANT is not supported for GEN_FAMILY=moe; "
              "decoding in full precision", flush=True)
    out = decode_mod.generate(params, prompt, cfg, **gen_kwargs)
    for row in out:
        print("tokens:", ",".join(str(int(t)) for t in row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
