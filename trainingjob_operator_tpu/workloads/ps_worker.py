"""Parameter-server / worker MNIST -- BASELINE config 2 (the reference's
TF2-style PS+worker ReplicaSpecs job on 4 CPU pods).

Exercises the multi-group rendezvous contract end-to-end: two replica groups
("pserver", "worker"), each pod finding the other group through the injected
``{RT}_HOSTS`` lists (reference: setEnv, pod.go:548-652).  The data plane is a
minimal real parameter-server protocol over TCP -- parameters are sharded
across pservers by key; workers pull shards, compute gradients on synthetic
MNIST, and push updates.  Deliberately numpy-only: PS architectures predate
the all-reduce style that XLA compiles natively, so this workload exists for
capability parity on CPU replica groups, not for the TPU fast path (that's
resnet_dp/bert_pretrain/llama_elastic).

Run: ``python -m trainingjob_operator_tpu.workloads.ps_worker`` inside a pod
of either group; the entrypoint dispatches on TRAININGJOB_REPLICA_NAME.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import sys
import threading
import time
from typing import Any, Dict, List

import numpy as np

PSERVER_GROUP = "PSERVER"
WORKER_GROUP = "WORKER"


# -- framing ----------------------------------------------------------------
#
# NON-EXECUTABLE wire format: a JSON metadata document plus raw array bytes
# (frame = >II lengths | json | blobs).  Pickle framing would let any pod
# that can reach the pserver port execute code in it (pickle.loads runs
# arbitrary reduce callables); JSON + frombuffer can only produce dicts,
# scalars and numeric arrays.  Array dtypes are whitelisted for the same
# reason ("object" would re-open the door).

_SAFE_DTYPES = frozenset(
    f"{k}{n}" for k, sizes in (("float", (16, 32, 64)),
                               ("int", (8, 16, 32, 64)),
                               ("uint", (8, 16, 32, 64)))
    for n in sizes) | {"bool"}


def send_msg(sock: socket.socket, obj: Any) -> None:
    arrays: List[np.ndarray] = []

    def strip(x):
        if isinstance(x, dict):
            return {k: strip(v) for k, v in x.items()}
        if isinstance(x, np.ndarray):
            a = np.ascontiguousarray(x)
            arrays.append(a)
            return {"__nd__": len(arrays) - 1, "dtype": str(a.dtype),
                    "shape": list(a.shape)}
        if isinstance(x, (np.floating, np.integer)):
            return x.item()
        return x

    meta = json.dumps(strip(obj)).encode()
    blobs = b"".join(a.tobytes() for a in arrays)
    sock.sendall(struct.pack(">II", len(meta), len(blobs)) + meta + blobs)


#: Per-section frame cap.  The MNIST protocol moves ~200 KiB of parameters;
#: an unauthenticated peer claiming a 4 GiB section (the >II ceiling) would
#: otherwise make _recv_exact buffer it all before any validation runs.
MAX_FRAME_BYTES = 64 << 20


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    meta_len, blob_len = struct.unpack(">II", header)
    if meta_len > MAX_FRAME_BYTES or blob_len > MAX_FRAME_BYTES:
        raise ValueError(f"refusing oversized frame (meta={meta_len}, "
                         f"blobs={blob_len} bytes)")
    meta = _recv_exact(sock, meta_len)
    blobs = _recv_exact(sock, blob_len) if blob_len else b""
    if meta is None or blobs is None:
        return None
    offsets = [0]  # filled in document order, matching send_msg's append order

    def build(x):
        if isinstance(x, dict) and "__nd__" in x:
            dtype = str(x["dtype"])
            if dtype not in _SAFE_DTYPES:
                raise ValueError(f"refusing non-numeric dtype {dtype!r}")
            shape = tuple(int(s) for s in x["shape"])
            if any(s < 0 for s in shape):
                # A negative entry would slice blobs with a negative stop
                # and silently desynchronize every later array's offset.
                raise ValueError(f"refusing negative shape {shape}")
            n = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
            start = offsets[0]
            offsets[0] = start + n
            return np.frombuffer(
                blobs[start:start + n], dtype=dtype).reshape(shape).copy()
        if isinstance(x, dict):
            return {k: build(v) for k, v in x.items()}
        return x

    out = build(json.loads(meta))
    if offsets[0] != len(blobs):
        # Raise (not assert: -O strips asserts) so a frame whose metadata
        # doesn't account for every blob byte is rejected, not truncated.
        raise ValueError(f"frame desync: metadata consumed {offsets[0]} of "
                         f"{len(blobs)} blob bytes")
    return out


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# -- model (numpy MLP with hand-rolled gradients) ---------------------------

def init_params(hidden: int, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    return {
        "w1": rng.randn(784, hidden).astype(np.float32) * 0.05,
        "b1": np.zeros(hidden, np.float32),
        "w2": rng.randn(hidden, 10).astype(np.float32) * 0.05,
        "b2": np.zeros(10, np.float32),
    }


def loss_and_grads(params, x, y):
    z1 = x @ params["w1"] + params["b1"]
    h = np.maximum(z1, 0.0)
    logits = h @ params["w2"] + params["b2"]
    logits -= logits.max(axis=1, keepdims=True)
    e = np.exp(logits)
    p = e / e.sum(axis=1, keepdims=True)
    n = x.shape[0]
    loss = -np.log(np.maximum(p[np.arange(n), y], 1e-9)).mean()
    dlogits = p
    dlogits[np.arange(n), y] -= 1.0
    dlogits /= n
    grads = {
        "w2": h.T @ dlogits,
        "b2": dlogits.sum(0),
    }
    dh = dlogits @ params["w2"].T
    dz1 = dh * (z1 > 0)
    grads["w1"] = x.T @ dz1
    grads["b1"] = dz1.sum(0)
    return loss, grads


def synthetic_batch(rng, batch: int):
    labels = rng.randint(0, 10, size=batch)
    centers = np.random.RandomState(1234).randn(10, 784).astype(np.float32) * 0.5
    images = centers[labels] + rng.randn(batch, 784).astype(np.float32) * 0.3
    return images.astype(np.float32), labels


def shard_keys(keys: List[str], num_shards: int) -> List[List[str]]:
    """Deterministic key -> pserver assignment (round-robin over sorted)."""
    shards: List[List[str]] = [[] for _ in range(num_shards)]
    for i, key in enumerate(sorted(keys)):
        shards[i % num_shards].append(key)
    return shards


# -- pserver ----------------------------------------------------------------

def run_pserver(rdv) -> int:
    hidden = int(os.environ.get("MNIST_HIDDEN", "64"))
    my_hosts = rdv.hosts(PSERVER_GROUP)
    n_ps = len(my_hosts)
    bind_port = int(my_hosts[rdv.replica_index].rsplit(":", 1)[1])
    expected_workers = len(rdv.group_instances.get(WORKER_GROUP, [])) or 1

    full = init_params(hidden)
    mine = set(shard_keys(list(full), n_ps)[rdv.replica_index])
    params = {k: v for k, v in full.items() if k in mine}
    lock = threading.Lock()
    done = threading.Event()
    done_count = [0]

    def handle(conn: socket.socket) -> None:
        try:
            with conn:
                while True:
                    msg = recv_msg(conn)
                    if msg is None:
                        return
                    op = msg.get("op")
                    if op == "pull":
                        # Snapshot under the lock, serialize+send outside it:
                        # one worker's congested socket must not block every
                        # other handler thread on the shard lock.  The copy
                        # is required -- push mutates the arrays in place.
                        with lock:
                            snap = {k: v.copy() for k, v in params.items()}
                        send_msg(conn, {"params": snap})
                    elif op == "push":
                        lr = float(msg.get("lr", 1e-2))
                        with lock:
                            for k, g in msg["grads"].items():
                                if k in params:
                                    params[k] -= lr * g
                        send_msg(conn, {"ok": True})
                    elif op == "done":
                        with lock:
                            done_count[0] += 1
                            if done_count[0] >= expected_workers:
                                done.set()
                        send_msg(conn, {"ok": True})
                    else:
                        send_msg(conn, {"error": f"unknown op {op!r}"})
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as e:
            # A malformed/oversized/torn frame from one peer must not kill
            # this thread silently -- drop the connection, keep serving.
            print(f"pserver handler: dropping connection: {e!r}", flush=True)

    server = socket.socket()
    try:
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind(("", bind_port))
        server.listen(16)
        server.settimeout(0.5)
        print(f"pserver {rdv.replica_index}/{n_ps} serving {sorted(mine)} "
              f"on :{bind_port}", flush=True)

        threads: List[threading.Thread] = []
        deadline = time.time() + float(os.environ.get("PS_TIMEOUT", "300"))
        while not done.is_set():
            if time.time() > deadline:
                print("pserver: timed out waiting for workers", flush=True)
                return 1
            try:
                conn, _ = server.accept()
            except socket.timeout:
                continue
            th = threading.Thread(target=handle, args=(conn,), daemon=True)
            th.start()
            threads.append(th)
    finally:
        server.close()
    print(f"pserver {rdv.replica_index}: all {expected_workers} workers done",
          flush=True)
    return 0


# -- worker -----------------------------------------------------------------

def _connect(host_port: str, timeout: float) -> socket.socket:
    host, port = host_port.rsplit(":", 1)
    deadline = time.time() + timeout
    while True:
        try:
            return socket.create_connection((host, int(port)), timeout=5)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def run_worker(rdv) -> int:
    steps = int(os.environ.get("MNIST_STEPS", "30"))
    batch = int(os.environ.get("MNIST_BATCH", "64"))
    lr = float(os.environ.get("MNIST_LR", "0.05"))
    ps_hosts = rdv.hosts(PSERVER_GROUP)
    if not ps_hosts:
        print("worker: no pserver hosts injected", flush=True)
        return 1
    conns = [_connect(hp, timeout=float(os.environ.get("PS_TIMEOUT", "120")))
             for hp in ps_hosts]
    rng = np.random.RandomState(1000 + rdv.replica_index)

    loss = float("nan")
    t0 = time.time()
    for i in range(steps):
        params: Dict[str, np.ndarray] = {}
        for conn in conns:
            send_msg(conn, {"op": "pull"})
            params.update(recv_msg(conn)["params"])
        x, y = synthetic_batch(rng, batch)
        loss, grads = loss_and_grads(params, x, y)
        shards = shard_keys(list(grads), len(conns))
        for conn, keys in zip(conns, shards):
            send_msg(conn, {"op": "push", "lr": lr,
                            "grads": {k: grads[k] for k in keys}})
            recv_msg(conn)
        if (i + 1) % 10 == 0 or i == steps - 1:
            print(f"worker {rdv.replica_index} step {i+1}/{steps} "
                  f"loss {loss:.4f}", flush=True)
    for conn in conns:
        send_msg(conn, {"op": "done"})
        recv_msg(conn)
        conn.close()
    dt = time.time() - t0
    print(f"worker {rdv.replica_index} done: {steps} steps in {dt:.2f}s "
          f"final_loss={loss:.4f}", flush=True)
    return 0


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous

    rdv = rendezvous.from_env()
    rdv.hold_reservation_if_needed()
    role = (rdv.replica_name or "worker").lower()
    if role.startswith("pserver") or role.startswith("ps"):
        return run_pserver(rdv)
    return run_worker(rdv)


if __name__ == "__main__":
    sys.exit(main())
