"""Serving plane: open-loop request queue + continuous-batching decode.

The decode stack (models/decode.py, moe_decode.py, quant.py) ran only
offline at fixed batch inside bench.py; this module is the request path --
the "millions of users" leg of the north star (ROADMAP item 3).  The design
is Orca-style continuous batching mapped onto static-shape XLA:

- ONE fixed-shape batched decode executable (``serve_step``) runs every
  scheduler tick; the scheduler owns a slot map over the batch axis.  A
  sequence occupies one slot from admission to EOS/max-tokens; the step
  after it finishes, its slot's K/V rows and position counter are reset
  (``reset_slot`` -- per-slot cache paging via ``dynamic_update_slice``)
  and the next queued request is admitted.  Survivors are NEVER
  re-prefilled: their rows and positions simply persist across admissions.
- Prompts prefill in fixed-size chunks (``prefill_chunk``, one slot per
  tick) interleaved with the running batch's decode step, so a long prompt
  delays the batch by at most one chunk per tick instead of stalling it.
- The admission queue is bounded: ``submit`` raises ``QueueFull`` (explicit
  backpressure callers can retry/shed on) instead of growing until OOM.
- Per-request latency accounting: queue wait, time-to-first-token, and
  inter-token gaps feed sliding-window p50/p99 plus aggregate tokens/s,
  pushed over the telemetry plane (obs/telemetry.py serve records) so the
  controller's traffic-aware scale policy (controller/pod.py
  ``_maybe_scale_serve``) and ``/debug/serve`` see live load.

``policy="static"`` is the A/B baseline bench.py scores against: classic
static batching -- admit only into an ALL-free batch, then run it to the
last straggler.  The continuous win is structural (freed slots do useful
work while stragglers finish), so the >=1.5x gate holds on CPU.

Decoding is greedy (argmax): a serving replica must be reproducible for the
stale-KV self-check (identical request -> identical tokens, whichever slot
it lands in); sampling policies live client-side.

Run: ``python -m trainingjob_operator_tpu.workloads.serve``.
Env (declared in api/constants.py): TRAININGJOB_SERVE_SLOTS,
_MAX_LEN, _PREFILL_CHUNK, _QUEUE_CAP, _RATE (mean arrivals per tick,
open-loop Poisson), _REQUESTS (0 = serve forever), _QUANT (weight-only
int8 decode -- models/quant.py qmatmul keeps it a win at every batch),
plus GEN_FAMILY / LLAMA_CONFIG / MOE_CONFIG and
TRAININGJOB_CHECKPOINT_DIR from workloads/generate.py's loading contract.
"""

from __future__ import annotations

import os
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.utils.metrics import METRICS

#: Slot states.  FREE slots ride the batched step as masked junk rows
#: (static shapes); PREFILL slots consume one prompt chunk per tick;
#: DECODE slots emit one token per tick.
FREE, PREFILL, DECODE = 0, 1, 2


class QueueFull(Exception):
    """Raised by ``submit`` when the bounded admission queue is at
    capacity -- the backpressure contract: callers shed or retry, the
    service never buffers unboundedly toward OOM."""


#: Cap on per-request phase-transition entries: enough for admission,
#: every prefill chunk of a max-length prompt at default chunking, first
#: token, and the terminal edge; a ring (oldest dropped) past that.
PHASE_LOG_CAP = 64


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0       # wall-clock submit time
    admitted: float = 0.0      # wall-clock slot assignment
    first_token_at: float = 0.0
    finished: float = 0.0
    slot: int = -1
    tokens: List[int] = field(default_factory=list)
    #: Bounded ring of (phase, wall-time) lifecycle transitions:
    #: enqueued -> admitted -> prefill_chunk* -> first_token -> terminal.
    phase_log: Deque[Tuple[str, float]] = field(
        default_factory=lambda: deque(maxlen=PHASE_LOG_CAP))

    def mark(self, phase: str, now: float) -> None:
        self.phase_log.append((phase, now))

    @property
    def ttft_ms(self) -> float:
        return max(self.first_token_at - self.arrival, 0.0) * 1000.0

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean inter-token decode gap, ms; None before the second token
        (absence is not zero)."""
        if not self.finished or not self.first_token_at \
                or len(self.tokens) < 2:
            return None
        span = max(self.finished - self.first_token_at, 0.0) * 1000.0
        return span / (len(self.tokens) - 1)

    def phase_attribution(self, now: float) -> Dict[str, float]:
        """Per-phase wall ms for the lifecycle so far -- the request-level
        analogue of the incident recorder's downtime phases.  Only phases
        the request actually entered appear (no zero-filled keys)."""
        out: Dict[str, float] = {}
        if self.admitted:
            out["queued"] = max(self.admitted - self.arrival, 0.0) * 1000.0
            if self.first_token_at:
                out["prefill"] = max(
                    self.first_token_at - self.admitted, 0.0) * 1000.0
                end = self.finished or now
                out["decode"] = max(
                    end - self.first_token_at, 0.0) * 1000.0
            else:
                out["prefill"] = max(now - self.admitted, 0.0) * 1000.0
        elif self.arrival:
            out["queued"] = max(now - self.arrival, 0.0) * 1000.0
        return out


class _Slot:
    __slots__ = ("state", "req", "t", "pending", "prefill_pos", "last_emit")

    def __init__(self) -> None:
        self.state = FREE
        self.req: Optional[Request] = None
        self.t = 0             # next cache position this slot writes
        self.pending = 0       # last sampled token (next decode input)
        self.prefill_pos = 0   # prompt tokens already prefilled
        self.last_emit = 0.0   # wall time of this slot's last token


class DecodeService:
    """Continuous-batching scheduler over one fixed-shape decode batch.

    ``params`` may be fp or weight-only int8 (models/quant.py); ``family``
    picks the model module ("llama" -> models.decode, "moe" ->
    models.moe_decode).  The KV cache is allocated once ([L, slots,
    max_len, Hkv, Dh]) and owned here; model code never sees request
    identity, only (token, position, slot) triples.
    """

    def __init__(self, params, config, *, slots: int = 4,
                 max_len: Optional[int] = None, prefill_chunk: int = 16,
                 queue_cap: int = 64, eos_id: int = -1,
                 family: str = "llama", policy: str = "continuous",
                 emitter=None, emit_every: int = 32):
        import jax
        import jax.numpy as jnp

        if family == "moe":
            from trainingjob_operator_tpu.models import moe_decode as mod
        else:
            from trainingjob_operator_tpu.models import decode as mod
        if policy not in ("continuous", "static"):
            raise ValueError(f"unknown policy {policy!r}")
        if config.sliding_window:
            raise ValueError(
                "the serving plane requires a full-causal cache "
                "(sliding_window == 0): chunked prefill and per-slot "
                "paging do not compose with the ring layout")
        self.params = params
        self.config = config
        self.slots = [_Slot() for _ in range(slots)]
        self.max_len = max_len or config.max_seq_len
        self.prefill_chunk = prefill_chunk
        self.queue_cap = queue_cap
        self.eos_id = eos_id
        self.policy = policy
        self.emitter = emitter
        self.emit_every = emit_every

        c = config
        dtype = jnp.dtype(c.dtype)
        shape = (c.n_layers, slots, self.max_len, c.n_kv_heads, c.head_dim)
        self.cache = {"k": jnp.zeros(shape, dtype),
                      "v": jnp.zeros(shape, dtype)}
        # Three executables serve the whole plane: slot/position/chunk
        # indices are traced operands, so admission order and prompt
        # lengths never trigger a recompile.  The K/V cache operand is
        # DONATED (TJA022): every call site immediately rebinds
        # ``self.cache`` to the returned cache, so XLA aliases the input
        # buffer to the output instead of holding two copies of the
        # plane's largest array in HBM while a step runs.
        self._step_fn = jax.jit(
            lambda p, cache, tok, ts: mod.serve_step(p, cache, tok, ts, c),
            donate_argnums=(1,))
        self._prefill_fn = jax.jit(
            lambda p, cache, toks, slot, t0: mod.prefill_chunk(
                p, cache, toks, slot, t0, c),
            donate_argnums=(1,))
        self._reset_fn = jax.jit(mod.reset_slot, donate_argnums=(0,))

        self.queue: Deque[Request] = deque()
        self._next_rid = 0
        #: Request-id stream identity (obs/reqtrace.py): ids are monotonic
        #: per (job, epoch), and a restarted replica starts a NEW epoch,
        #: so its id reset can never masquerade as the old stream's gap.
        self.epoch = f"{os.getpid()}-{id(self):x}"
        #: Job label for the request plane's counters; the emitter knows
        #: the real ns/name identity when running under the operator.
        self.job_label = (emitter.job if emitter is not None
                          and getattr(emitter, "job", "") else "local/serve")
        self._prefill_rr = 0   # round-robin cursor over PREFILL slots
        self.step_count = 0
        self.completed_total = 0
        self.rejected_total = 0
        self.tokens_total = 0
        #: Sliding windows feeding p50/p99 and tokens/s.
        self._latency_ms: Deque[float] = deque(maxlen=2048)
        self._emit_times: Deque[float] = deque(maxlen=2048)

    def warmup(self) -> None:
        """Compile the three serving executables before traffic arrives.
        Slot / position / chunk indices are traced operands, so one
        dispatch each covers every future admission pattern.  The cache
        operand is donated, so the warmup dispatches thread the cache
        through all three calls and rebind ``self.cache`` at the end --
        the pre-warmup buffer is dead once the first call returns.
        Latency-sensitive deployments (and the bench A/B, which must not
        time XLA compilation) call this once at startup."""
        import jax
        import jax.numpy as jnp

        n = len(self.slots)
        zeros = jnp.zeros((n,), jnp.int32)
        chunk = jnp.zeros((self.prefill_chunk,), jnp.int32)
        cache = self.cache
        _, cache = self._prefill_fn(self.params, cache, chunk, 0, 0)
        _, cache = self._step_fn(self.params, cache, zeros, zeros)
        cache = self._reset_fn(cache, 0)
        jax.block_until_ready(cache["k"])
        self.cache = cache

    # -- request surface ------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               now: Optional[float] = None) -> Request:
        """Enqueue one request; raises ``QueueFull`` at capacity and
        ``ValueError`` when it could never fit the cache."""
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"max_len {self.max_len}")
        if max_new_tokens < 1 or not prompt:
            raise ValueError("need a non-empty prompt and max_new >= 1")
        now = time.time() if now is None else now
        # Ids are assigned BEFORE the capacity check: a rejected request
        # still consumes one and files a terminal record, so every id in
        # the stream has exactly one outcome and the audit ledger's gap
        # detection never mistakes backpressure for a dropped request.
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, arrival=now)
        self._next_rid += 1
        req.mark("enqueued", now)
        if len(self.queue) >= self.queue_cap:
            self.rejected_total += 1
            METRICS.inc("trainingjob_serve_rejected_total",
                        job=self.job_label, reason="QueueFull")
            self._emit_request(req, "rejected", now)
            raise QueueFull(
                f"queue at capacity {self.queue_cap}; retry or shed")
        self.queue.append(req)
        return req

    # -- scheduler ------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> List[Request]:
        """One scheduler tick: admit -> one prefill chunk -> one batched
        decode step.  Returns the requests that completed this tick."""
        now = time.time() if now is None else now
        self._admit(now)
        self._prefill_one(now)
        done = self._decode(now)
        self.step_count += 1
        if (self.emitter is not None
                and self.step_count % self.emit_every == 0):
            s = self.stats(now)
            self.emitter.emit_serve(
                queue_depth=s["queue_depth"],
                active_slots=s["active_slots"], slots=s["slots"],
                p50_ms=s["token_latency_ms_p50"],
                p99_ms=s["token_latency_ms_p99"],
                tokens_per_sec=s["tokens_per_sec"],
                completed=s["completed_total"])
        return done

    def _admit(self, now: float) -> None:
        if self.policy == "static":
            # Static re-prefill batching (the A/B baseline): a new batch
            # forms only once EVERY slot is free -- freed slots idle while
            # stragglers finish, which is exactly the cost continuous
            # batching removes.
            if any(sl.state != FREE for sl in self.slots):
                return
        for idx, sl in enumerate(self.slots):
            if not self.queue:
                return
            if sl.state != FREE:
                continue
            req = self.queue.popleft()
            # Per-slot cache paging: zero just this slot's K/V rows; the
            # position counter restarts at 0.  Survivor slots are never
            # touched (the no-re-prefill contract).
            self.cache = self._reset_fn(self.cache, idx)
            sl.state = PREFILL
            sl.req = req
            sl.t = 0
            sl.prefill_pos = 0
            req.admitted = now
            req.slot = idx
            req.mark("admitted", now)

    def _prefill_one(self, now: float) -> None:
        """Advance at most ONE slot by one prompt chunk per tick: prefill
        interleaves with decode instead of stalling it (a long prompt costs
        the running batch one chunk of latency per tick, bounded)."""
        import jax.numpy as jnp

        n = len(self.slots)
        for off in range(n):
            idx = (self._prefill_rr + off) % n
            sl = self.slots[idx]
            if sl.state != PREFILL:
                continue
            req = sl.req
            chunk = req.prompt[sl.prefill_pos:
                               sl.prefill_pos + self.prefill_chunk]
            valid = len(chunk)
            chunk = chunk + [0] * (self.prefill_chunk - valid)
            logits, self.cache = self._prefill_fn(
                self.params, self.cache, jnp.asarray(chunk, jnp.int32),
                idx, sl.prefill_pos)
            sl.prefill_pos += valid
            req.mark("prefill_chunk", now)
            if sl.prefill_pos >= len(req.prompt):
                # Prompt fully cached: the last VALID chunk offset's logit
                # is the prompt's next-token distribution.
                import numpy as np

                # analyzer: allow[host-sync-in-hot-loop] the sampler is
                # host-side by design (docs/SERVING.md): one first-token
                # argmax per completed prefill, a bounded D2H.
                first = int(np.argmax(np.asarray(logits[valid - 1])))
                sl.state = DECODE
                sl.t = len(req.prompt)
                sl.pending = first
                req.first_token_at = now
                req.mark("first_token", now)
                self._emit_token(sl, first, now)
            self._prefill_rr = (idx + 1) % n
            return

    def _decode(self, now: float) -> List[Request]:
        import numpy as np

        active = [i for i, sl in enumerate(self.slots)
                  if sl.state == DECODE]
        if not active:
            return []
        import jax.numpy as jnp

        # Fixed-shape batch: every row steps.  FREE / mid-PREFILL rows get
        # their next UNWRITTEN position, so the junk K/V they write lands
        # exactly where admission's reset or the next prefill chunk
        # overwrites it, and their own mask never reaches it.
        toks, ts = [], []
        for sl in self.slots:
            if sl.state == DECODE:
                toks.append(sl.pending)
                ts.append(sl.t)
            elif sl.state == PREFILL:
                toks.append(0)
                ts.append(sl.prefill_pos)
            else:
                toks.append(0)
                ts.append(0)
        logits, self.cache = self._step_fn(
            self.params, self.cache, jnp.asarray(toks, jnp.int32),
            jnp.asarray(ts, jnp.int32))
        # analyzer: allow[host-sync-in-hot-loop] the per-tick sampler is
        # host-side by design: exactly one batched logits D2H + argmax per
        # decode tick, the documented serving cost (docs/SERVING.md).
        picks = np.argmax(np.asarray(logits), axis=-1)
        done: List[Request] = []
        for i in active:
            sl = self.slots[i]
            if sl.req.finished:
                # Completed during this tick's prefill phase (single-token
                # request): the batched step already ran with its row, but
                # nothing reads its output.
                done.append(self._release(sl, now))
                continue
            sl.t += 1
            nxt = int(picks[i])
            sl.pending = nxt
            self._emit_token(sl, nxt, now)
            if sl.req.finished:
                done.append(self._release(sl, now))
        return done

    def _emit_token(self, sl: _Slot, tok: int, now: float) -> None:
        req = sl.req
        req.tokens.append(tok)
        self.tokens_total += 1
        if len(req.tokens) > 1:
            self._latency_ms.append((now - sl.last_emit) * 1000.0)
        else:
            self._latency_ms.append(req.ttft_ms)
        sl.last_emit = now
        self._emit_times.append(now)
        if (tok == self.eos_id
                or len(req.tokens) >= req.max_new_tokens
                or len(req.prompt) + len(req.tokens) >= self.max_len):
            req.finished = now

    def _release(self, sl: _Slot, now: float) -> Request:
        """Free the slot; the NEXT tick's admission pass may re-page it.
        The K/V rows are left as-is here -- admission's ``reset_slot`` is
        the paging point, so a slot freed and never reused costs nothing."""
        req = sl.req
        sl.state = FREE
        sl.req = None
        self.completed_total += 1
        req.mark("completed", now)
        self._emit_request(req, "completed", now)
        return req

    # -- request-lifecycle plane (obs/reqtrace.py) ----------------------------

    def _emit_request(self, req: Request, outcome: str, now: float) -> None:
        """Push one terminal-state record over the telemetry wire.  Every
        record carries ``submitted_hwm`` (the highest id this incarnation
        handed out) so the audit ledger can see ids this process never
        got to flush."""
        if self.emitter is None:
            return
        self.emitter.emit_request(
            outcome, req.rid, self.epoch, self._next_rid - 1,
            ttft_ms=req.ttft_ms if req.first_token_at else None,
            tpot_ms=req.tpot_ms, tokens=len(req.tokens),
            arrival=req.arrival, phase_ms=req.phase_attribution(now))

    def drain_abort(self, now: Optional[float] = None) -> List[Request]:
        """Abandon all in-flight work at a drain/scale-in/restart boundary:
        every queued or slotted request files an explicit ``evicted``
        terminal record (never silently lost -- the audit contract), the
        slots are freed, and the evicted requests are returned so a router
        tier could retry them elsewhere."""
        now = time.time() if now is None else now
        evicted: List[Request] = []
        while self.queue:
            evicted.append(self.queue.popleft())
        for sl in self.slots:
            if sl.state != FREE and sl.req is not None:
                evicted.append(sl.req)
                sl.state = FREE
                sl.req = None
        for req in evicted:
            req.mark("evicted", now)
            self._emit_request(req, "evicted", now)
        return evicted

    # -- introspection --------------------------------------------------------

    def stats(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.time() if now is None else now
        lat = sorted(self._latency_ms)

        def q(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(int(p * len(lat)), len(lat) - 1)]

        span = (self._emit_times[-1] - self._emit_times[0]
                if len(self._emit_times) > 1 else 0.0)
        tps = (len(self._emit_times) - 1) / span if span > 0 else 0.0
        active = sum(1 for sl in self.slots if sl.state != FREE)
        return {
            "policy": self.policy,
            "slots": len(self.slots),
            "active_slots": active,
            "occupancy": active / max(len(self.slots), 1),
            "queue_depth": len(self.queue),
            "steps": self.step_count,
            "completed_total": self.completed_total,
            "rejected_total": self.rejected_total,
            "tokens_total": self.tokens_total,
            "tokens_per_sec": round(tps, 2),
            "token_latency_ms_p50": round(q(0.5), 3),
            "token_latency_ms_p99": round(q(0.99), 3),
        }


# -- synthetic open-loop traffic ---------------------------------------------

def synthetic_traffic(n: int, *, seed: int = 0, rate: float = 0.5,
                      vocab: int = 256, templates: int = 6,
                      prompt_lens: Tuple[int, int] = (4, 16),
                      out_tokens: Tuple[int, int] = (4, 32),
                      long_frac: float = 0.0,
                      long_out_tokens: Tuple[int, int] = (48, 96)
                      ) -> List[Tuple[int, List[int], int]]:
    """``n`` requests as (arrival_tick, prompt, max_new) triples.

    Open-loop: arrivals are Poisson in TICK time (mean ``rate`` per tick),
    fixed up front -- load does not slacken when the service falls behind,
    which is what makes queue depth a real signal.  Prompts are drawn from
    ``templates`` deterministic token patterns so the same prompt recurs
    across different slots; a serving run can then self-check that repeats
    decode identically (the stale-KV detector tools/serve_smoke.py pins).
    Mixed prompt/output lengths are the point: the straggler spread is what
    continuous batching monetizes.  ``long_frac`` > 0 makes the mix
    bimodal -- that fraction of requests draws its budget from
    ``long_out_tokens`` instead (the chat-vs-completion shape real serving
    traffic has, and the worst case for static batching: one long request
    strands a whole batch of short ones).
    """
    import random

    rng = random.Random(seed)
    tick = 0
    out: List[Tuple[int, List[int], int]] = []
    for _ in range(n):
        # Geometric inter-arrival ~ Poisson process in discrete ticks.
        while rng.random() > rate:
            tick += 1
        g = rng.randrange(templates)
        plen = rng.randint(*prompt_lens)
        # Template g's prompt: deterministic in (g, plen) only, so equal
        # (g, plen) pairs are byte-identical requests.
        prompt = [1 + (g * 37 + 7 * i) % (vocab - 1) for i in range(plen)]
        budget = (rng.randint(*long_out_tokens)
                  if long_frac and rng.random() < long_frac
                  else rng.randint(*out_tokens))
        out.append((tick, prompt, budget))
    return out


def run_traffic(service: DecodeService,
                traffic: List[Tuple[int, List[int], int]],
                max_ticks: int = 100000) -> Dict[str, Any]:
    """Drive ``service`` through an open-loop trace: submissions fire by
    tick regardless of service progress (QueueFull rejections are dropped
    and counted), then the loop drains until every admitted request
    completes.  Returns stats + completed requests + the stale-KV
    self-check verdict."""
    completed: List[Request] = []
    submitted = 0
    i = 0
    tick = 0
    t0 = time.time()
    while i < len(traffic) or any(sl.state != FREE for sl in service.slots) \
            or service.queue:
        while i < len(traffic) and traffic[i][0] <= tick:
            _, prompt, max_new = traffic[i]
            try:
                service.submit(prompt, max_new)
                submitted += 1
            except QueueFull:
                pass  # open-loop shed; counted in rejected_total
            i += 1
        completed.extend(service.step())
        tick += 1
        if tick > max_ticks:
            raise RuntimeError(f"traffic did not drain in {max_ticks} ticks")
    wall = time.time() - t0
    stats = service.stats()
    stats.update({
        "submitted": submitted,
        "wall_s": round(wall, 3),
        "aggregate_tokens_per_sec": round(
            service.tokens_total / wall, 1) if wall > 0 else 0.0,
        "stale_kv_violations": count_stale_kv_violations(completed),
        "ttft_ms_p50": _quantile([r.ttft_ms for r in completed], 0.5),
    })
    return {"stats": stats, "completed": completed}


def count_stale_kv_violations(completed: List[Request]) -> int:
    """Identical (prompt, max_new) requests must decode identically no
    matter which slot they landed in or what occupied it before -- greedy
    decode is deterministic, so ANY divergence means a slot leaked state
    into its next occupant.  Returns the number of divergent requests."""
    reference: Dict[Tuple[Tuple[int, ...], int], List[int]] = {}
    violations = 0
    for req in completed:
        key = (tuple(req.prompt), req.max_new_tokens)
        ref = reference.setdefault(key, req.tokens)
        if req.tokens != ref:
            violations += 1
    return violations


def _quantile(values: List[float], p: float) -> float:
    if not values:
        return 0.0
    v = sorted(values)
    return round(v[min(int(p * len(v)), len(v) - 1)], 3)


# -- operator entrypoint ------------------------------------------------------

def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax

    family = os.environ.get("GEN_FAMILY", "llama")
    if family == "moe":
        from trainingjob_operator_tpu.models import moe

        cfg = (moe.MoEConfig.mixtral_8x7b()
               if os.environ.get("MOE_CONFIG", "tiny") == "8x7b"
               else moe.MoEConfig.tiny())
        init_params, subdir = moe.init_params, "moe"
    else:
        from trainingjob_operator_tpu.models import llama

        cfg = (llama.LlamaConfig.llama2_7b()
               if os.environ.get("LLAMA_CONFIG", "tiny") == "7b"
               else llama.LlamaConfig.tiny())
        init_params, subdir = llama.init_params, "llama"

    env = os.environ
    slots = int(env.get(constants.SERVE_SLOTS_ENV, "4"))
    max_len = int(env.get(constants.SERVE_MAX_LEN_ENV, "0")) or None
    chunk = int(env.get(constants.SERVE_PREFILL_CHUNK_ENV, "16"))
    queue_cap = int(env.get(constants.SERVE_QUEUE_CAP_ENV, "64"))
    rate = float(env.get(constants.SERVE_RATE_ENV, "0.5"))
    n_requests = int(env.get(constants.SERVE_REQUESTS_ENV, "200"))
    quantize = env.get(constants.SERVE_QUANT_ENV, "") in ("1", "true")

    # Same checkpoint contract as workloads/generate.py: serve the trained
    # weights when a checkpoint exists, random init otherwise (smoke runs).
    state = train.CheckpointState.restore_or_init(
        rdv,
        {"params": init_params(cfg, jax.random.PRNGKey(0)),
         "opt_state": train.ckpt_placeholder(), "step": 0},
        subdir=subdir)
    params = state.value["params"]
    if quantize and family != "moe":
        from trainingjob_operator_tpu.models.quant import quantize_weights

        params = quantize_weights(params)
        print("serving weight-only int8", flush=True)

    from trainingjob_operator_tpu.obs.telemetry import TelemetryEmitter

    service = DecodeService(params, cfg, slots=slots, max_len=max_len,
                            prefill_chunk=chunk, queue_cap=queue_cap,
                            family=family, emitter=TelemetryEmitter())
    print(f"serve: family={family} slots={slots} max_len={service.max_len} "
          f"chunk={chunk} queue_cap={queue_cap} rate={rate}", flush=True)

    if n_requests > 0:
        traffic = synthetic_traffic(n_requests, rate=rate,
                                    vocab=cfg.vocab_size)
        result = run_traffic(service, traffic)
        s = result["stats"]
        print(f"serve done: completed={s['completed_total']} "
              f"rejected={s['rejected_total']} "
              f"tokens/s={s['aggregate_tokens_per_sec']} "
              f"p50_ms={s['token_latency_ms_p50']} "
              f"p99_ms={s['token_latency_ms_p99']} "
              f"stale_kv_violations={s['stale_kv_violations']}", flush=True)
        return 0 if s["stale_kv_violations"] == 0 else 1

    # Serve forever: a persistent replica under the operator.  The
    # synthetic generator keeps feeding open-loop load (a real deployment
    # would splice a network frontend in here); SIGTERM from the drain
    # machinery ends the process like any workload.
    import itertools

    gen = iter(itertools.count())
    rng_seed = 0
    while True:
        batch_no = next(gen)
        traffic = synthetic_traffic(512, seed=rng_seed + batch_no,
                                    rate=rate, vocab=cfg.vocab_size)
        run_traffic(service, traffic)


if __name__ == "__main__":
    sys.exit(main())
