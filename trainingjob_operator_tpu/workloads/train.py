"""Shared training utilities: checkpoint/resume keyed on restart count.

The reference delegates checkpointing entirely to the workload, contributing
only the restart-count env and stable identity (SURVEY.md §5.4).  This module
is the workload half of that contract: orbax-backed save/restore under the
injected checkpoint dir, resumed whenever the operator restarts the pod.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous


class CheckpointState:
    """Tiny orbax wrapper: one pytree, latest-step retention."""

    def __init__(self, directory: str, value: Dict[str, Any], manager: Any):
        self.value = value
        self._dir = directory
        self._mngr = manager

    @classmethod
    def restore_or_init(cls, rdv: Rendezvous, init_value: Dict[str, Any],
                        subdir: Optional[str] = None) -> "CheckpointState":
        """Per-replica path by default; pass ``subdir`` for one path shared by
        every process of the job (elastic resume: the checkpoint must survive
        a world-size change, so it cannot be keyed on rank)."""
        directory = rdv.checkpoint_dir
        if not directory:
            return cls("", init_value, None)
        import orbax.checkpoint as ocp

        if subdir is not None:
            path = os.path.join(os.path.abspath(directory), subdir)
        else:
            path = os.path.join(os.path.abspath(directory),
                                rdv.replica_name or "worker",
                                str(rdv.replica_index))
        os.makedirs(path, exist_ok=True)
        manager = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(max_to_keep=2))
        latest = manager.latest_step()
        if latest is not None:
            import jax

            has_placeholders = any(
                leaf is None for leaf in jax.tree.leaves(
                    init_value, is_leaf=lambda x: x is None))
            if has_placeholders:
                # Elastic resume: the param tree is only known from the
                # checkpoint itself; restore the saved structure as-is.
                restored = manager.restore(latest)
            else:
                # Strict: a template/checkpoint mismatch (e.g. resumed with a
                # different model config) must fail loudly here, not deep in
                # a jitted step later.
                restored = manager.restore(
                    latest, args=ocp.args.StandardRestore(init_value))
            return cls(path, restored, manager)
        return cls(path, init_value, manager)

    def save(self, value: Dict[str, Any]) -> None:
        self.value = value
        if self._mngr is None:
            return
        import orbax.checkpoint as ocp

        step = int(value.get("step", 0))
        self._mngr.save(step, args=ocp.args.StandardSave(value))
        self._mngr.wait_until_finished()


def round_global_batch(global_batch: int, shards: int) -> int:
    """Largest multiple of ``shards`` <= global_batch (floor ``shards``)."""
    shards = max(shards, 1)
    return max(shards, global_batch // shards * shards)


def globalize_batch(sharding, local):
    """Per-process local batch shard -> global sharded array (identity when
    single-process)."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    import numpy as np

    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def host_replicated_copy(tree: Any, mesh) -> Any:
    """Numpy host copy of a (possibly cross-host sharded) pytree.

    ``jax.device_get`` alone raises on arrays with non-addressable shards
    (multi-host fsdp/tp): first all-gather to a fully-replicated layout via a
    jitted identity with replicated out_shardings, then fetch.  Used for
    rank-agnostic checkpoints that must survive an elastic width change.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None or jax.process_count() == 1:
        return jax.device_get(tree)
    replicated = NamedSharding(mesh, P())
    gather = jax.jit(lambda t: t, out_shardings=jax.tree.map(
        lambda _: replicated, tree))
    return jax.device_get(gather(tree))


def throughput_line(prefix: str, steps_done: int, units_per_step: int,
                    seconds: float, unit: str = "tokens") -> str:
    rate = steps_done * units_per_step / max(seconds, 1e-9)
    return f"{prefix} steps={steps_done} {unit}/s={rate:.0f}"


def reshard_restored(host_params: Any, host_opt: Any, rules, mesh,
                     opt_state_like: Any):
    """Re-shard host (numpy) checkpoint copies onto the CURRENT mesh.

    The elastic contract: checkpoints are rank- and width-agnostic host
    trees; after a resize the same checkpoint lands on a different mesh
    shape.  Params follow the model's sharding rules; the optimizer tree is
    rebuilt into the live (possibly NamedTuple) structure -- orbax round-trips
    containers as lists -- with scalar leaves going mesh-replicated.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from trainingjob_operator_tpu.parallel.sharding import sharding_pytree

    params = jax.device_put(host_params,
                            sharding_pytree(host_params, rules, mesh))
    host_opt = jax.tree.unflatten(jax.tree.structure(opt_state_like),
                                  jax.tree.leaves(host_opt))

    def put(host, like):
        sharding = like.sharding if isinstance(like.sharding, NamedSharding) \
            else NamedSharding(mesh, P())
        return jax.device_put(host, sharding)

    opt_state = jax.tree.map(put, host_opt, opt_state_like)
    return params, opt_state
