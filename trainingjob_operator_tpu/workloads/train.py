"""Shared training utilities: checkpoint/resume keyed on restart count.

The reference delegates checkpointing entirely to the workload, contributing
only the restart-count env and stable identity (SURVEY.md §5.4).  This module
is the workload half of that contract: orbax-backed save/restore under the
injected checkpoint dir, resumed whenever the operator restarts the pod.

Checkpointing is **sharded and asynchronous**: sharded ``jax.Array`` leaves
are saved distributed -- every host writes only its addressable shards to the
shared directory, nothing is ever gathered to one device or host (a fully
replicated gather of Llama-2-7B + AdamW state is ~78 GB and OOMs a 16 GB v5e
chip) -- and the save runs in the background so the step loop never blocks on
I/O; ``finalize()`` barriers before exit.  Restore reshards onto the
*current* mesh, whatever width the job came back at -- the storage format is
the global array, so elastic resume needs no gather/re-shard choreography.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.logs import configure_logging, get_logger
from trainingjob_operator_tpu.obs.telemetry import TelemetryEmitter
from trainingjob_operator_tpu.obs.trace import tracer_from_env
from trainingjob_operator_tpu.utils.metrics import METRICS
from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous


def _ensure_workload_logging() -> None:
    """Workloads run as bare subprocesses: without a handler, stdlib logging
    drops INFO records on the floor.  Install the structured handler once
    (JSON when the operator propagated --log-json via TRAININGJOB_LOG_JSON),
    so step records reach the pod log -- with trace/span ids attached."""
    root = logging.getLogger()
    if root.handlers:
        return
    configure_logging(
        json_output=os.environ.get(constants.LOG_JSON_ENV) == "1",
        level=logging.INFO)


#: Local stand-in for ``orbax.checkpoint.PLACEHOLDER`` on orbax versions
#: that do not export one (e.g. 0.7.x).  Identity-compared, never saved.
_PLACEHOLDER_FALLBACK = object()


def ckpt_placeholder() -> Any:
    """The 'skip this top-level item on restore' marker for
    ``CheckpointState.restore_or_init`` templates: orbax's own PLACEHOLDER
    when the installed version exports it, a local sentinel otherwise (the
    restore path degrades gracefully -- see ``restore_or_init``)."""
    import orbax.checkpoint as ocp

    return getattr(ocp, "PLACEHOLDER", _PLACEHOLDER_FALLBACK)


class CheckpointState:
    """Orbax wrapper: one pytree, async save, latest-step retention.

    Single-process jobs default to the **snapshot-donate** pipeline:
    ``save()`` copies the tree device->host at the step boundary (the only
    step-visible stall, O(device->host copy)) and a background writer thread
    runs the orbax write + commit off the step path entirely.  Orbax's async
    save already overlaps the *write* with compute, but its ``save()`` call
    still pays device sync + serialization setup in-step -- the snapshot
    path moves even that off the loop.  Multi-process jobs keep the direct
    handoff: sharded saves are COLLECTIVE (every host writes its shards
    inside one orbax save), and a per-host writer thread would need its own
    barrier choreography.  ``TRAININGJOB_CKPT_SNAPSHOT=0`` forces the
    direct handoff everywhere (the bench's A/B baseline).
    """

    #: Bounded re-check interval for writer handshakes (the condition loop
    #: re-checks its predicate; the timeout only bounds lost-wakeup latency).
    _WAIT_S = 0.2

    def __init__(self, directory: str, value: Dict[str, Any], manager: Any):
        self.value = value
        self._dir = directory
        self._mngr = manager
        # Snapshot-donate writer machinery.  All orbax manager access is
        # serialized by protocol: the writer thread touches it only between
        # _pending pickup and _busy clear, and direct callers (wait=True
        # save, finalize) drain the writer first.
        self._cv = threading.Condition()
        self._writer: Optional[threading.Thread] = None
        self._pending: Optional[Tuple] = None
        self._busy = False
        self._error: Optional[BaseException] = None
        #: Last step whose write COMMITTED (snapshot pipeline only) -- the
        #: recovery point a crash mid-write falls back to.
        self.committed_step: Optional[int] = None
        #: Step-visible wall time of the most recent ``save()`` call, ms.
        self.last_stall_ms = 0.0

    @classmethod
    def restore_or_init(cls, rdv: Rendezvous, init_value: Dict[str, Any],
                        subdir: Optional[str] = None,
                        mesh: Any = None) -> "CheckpointState":
        """Per-replica path by default; pass ``subdir`` for one path shared by
        every process of the job (elastic resume: the checkpoint must survive
        a world-size change, so it cannot be keyed on rank).

        ``jax.Array`` leaves in ``init_value`` act as the restore template:
        the checkpoint is restored *onto their shardings* (the current mesh),
        regardless of the mesh shape at save time.  ``None`` leaves mean the
        structure is only known from the checkpoint itself; an
        ``orbax.checkpoint.PLACEHOLDER`` leaf SKIPS that subtree entirely
        (e.g. a sampler restoring params but not optimizer moments,
        workloads/generate.py).
        """
        directory = rdv.checkpoint_dir
        if not directory:
            return cls("", init_value, None)
        import orbax.checkpoint as ocp

        if subdir is not None:
            path = os.path.join(os.path.abspath(directory), subdir)
        else:
            path = os.path.join(os.path.abspath(directory),
                                rdv.replica_name or "worker",
                                str(rdv.replica_index))
        os.makedirs(path, exist_ok=True)

        import jax

        placeholder = ckpt_placeholder()
        skip = [k for k, v in init_value.items() if v is placeholder]
        manager = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(max_to_keep=2),
            # Partial restore (PLACEHOLDER) needs the PyTree handler; the
            # on-disk format is the same as StandardSave's.
            item_handlers=ocp.PyTreeCheckpointHandler() if skip else None)
        latest = manager.latest_step()
        if latest is not None:
            has_placeholders = any(
                leaf is None for leaf in jax.tree.leaves(
                    init_value, is_leaf=lambda x: x is None))
            if has_placeholders:
                # The param tree is only known from the checkpoint itself;
                # restore the saved structure as-is.
                restored = manager.restore(latest)
            else:
                # Abstract template: sharded leaves restore distributed onto
                # their CURRENT sharding (elastic resume across widths); a
                # template/checkpoint structure mismatch (e.g. resumed with a
                # different model config) fails loudly here, not deep in a
                # jitted step later.
                from jax.sharding import NamedSharding, PartitionSpec

                def abstract(x):
                    if isinstance(x, jax.Array):
                        sharding = x.sharding
                        if (mesh is not None
                                and not isinstance(sharding, NamedSharding)):
                            # Leaves created off-mesh (e.g. optimizer step
                            # counters) restore mesh-replicated; a committed
                            # single-device leaf would poison the jitted
                            # step's device set.
                            sharding = NamedSharding(mesh, PartitionSpec())
                        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                    sharding=sharding)
                    return x

                if skip:
                    # Partial restore: PLACEHOLDER top-level items are not
                    # read at all (a sampler restoring params but not the
                    # ~2x-params optimizer moments, workloads/generate.py).
                    import inspect

                    template = jax.tree.map(
                        abstract, {k: v for k, v in init_value.items()
                                   if k not in skip})
                    if "partial_restore" in inspect.signature(
                            ocp.args.PyTreeRestore).parameters:
                        restored = manager.restore(
                            latest, args=ocp.args.PyTreeRestore(
                                template, partial_restore=True))
                        restored = dict(restored)
                    else:
                        # Older orbax (no partial_restore): read the full
                        # tree and drop the skipped items after the fact --
                        # costs the skipped items' I/O and host RAM, which
                        # is fine at test scale; newer orbax skips the read.
                        full = manager.restore(latest)
                        restored = {
                            k: jax.tree.map(
                                lambda t, x: (
                                    jax.device_put(x, t.sharding)
                                    if isinstance(t, jax.ShapeDtypeStruct)
                                    else x),
                                template[k], full[k])
                            for k in template}
                    for k in skip:
                        restored[k] = placeholder
                else:
                    template = jax.tree.map(abstract, init_value)
                    restored = _load_resume_image(path, latest, template)
                    if restored is None:
                        restored = _orbax_restore_with_fallback(
                            manager, latest, template)
            return cls(path, restored, manager)
        return cls(path, init_value, manager)

    def snapshot_mode(self) -> bool:
        """True when this save pipeline is snapshot-donate (see class doc)."""
        if os.environ.get(constants.CKPT_SNAPSHOT_ENV, "1") == "0":
            return False
        import jax

        return jax.process_count() == 1

    def save(self, value: Dict[str, Any], wait: bool = False,
             tracer: Any = None, trace_parent: Any = None) -> float:
        """Save ``value`` at its ``step``; returns the step-visible stall in
        ms (what the loop paid to call this, the
        ``trainingjob_checkpoint_stall_ms`` sample).

        Snapshot mode: device->host copy here (``ckpt.snapshot`` span),
        orbax write on the background writer (``ckpt.write`` span).  A new
        snapshot REPLACES an unstarted pending one (latest-wins coalescing;
        committed steps stay monotonic because the writer picks up at most
        one at a time, in arrival order).  A writer failure is stashed and
        re-raised from the next ``save()``/``finalize()`` -- a checkpoint
        that silently stops committing is worse than a crash.

        Direct mode (``wait=True``, multi-process, or
        TRAININGJOB_CKPT_SNAPSHOT=0): hand live arrays to orbax's async
        save; ``wait=True`` barriers immediately (pre-exit / preemption
        checkpoint).  All processes must call save -- sharded leaves are
        written collectively, each host its own shards."""
        t0 = time.perf_counter()
        self.value = value
        if self._mngr is None:
            return 0.0
        step = int(value.get("step", 0))
        if wait or not self.snapshot_mode():
            self._drain()
            import orbax.checkpoint as ocp

            self._mngr.save(step, args=ocp.args.StandardSave(value))
            if wait:
                self._mngr.wait_until_finished()
                with self._cv:
                    self.committed_step = step
                if self.snapshot_mode():
                    # Preemption checkpoints bypass the background writer
                    # but must stay fast-resumable: mirror them into the
                    # resume image too (post-commit, same as ``_write``).
                    _write_resume_image(self._dir, step,
                                        _snapshot_to_host(value))
        else:
            with _span(tracer, "ckpt.snapshot", parent=trace_parent,
                       step=step):
                host_value = _snapshot_to_host(value)
            with self._cv:
                self._surface_error_locked()
                self._pending = (step, host_value, tracer, trace_parent)
                if self._writer is None:
                    self._writer = threading.Thread(
                        target=self._writer_loop, daemon=True,
                        name="ckpt-writer")
                    self._writer.start()
                self._cv.notify_all()
        self.last_stall_ms = (time.perf_counter() - t0) * 1e3
        return self.last_stall_ms

    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while self._pending is None:
                    self._cv.wait(self._WAIT_S)
                step, host_value, tracer, parent = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write(step, host_value, tracer, parent)
                with self._cv:
                    self.committed_step = step
            # analyzer: allow[broad-except]: stashed and re-raised from the
            # next save()/finalize() on the step loop -- the writer thread
            # must neither die silently nor crash the process from here.
            except BaseException as exc:
                with self._cv:
                    self._error = exc
            finally:
                with self._cv:
                    # analyzer: allow[finally-state-restore] the restore IS
                    # in this finally; the flagged residual path is the cv
                    # acquire itself raising, which Condition.__enter__
                    # cannot do short of interpreter teardown.
                    self._busy = False
                    self._cv.notify_all()

    def _write(self, step: int, host_value: Dict[str, Any],
               tracer: Any, parent: Any) -> None:
        import orbax.checkpoint as ocp

        with _span(tracer, "ckpt.write", parent=parent, step=step):
            self._mngr.save(step, args=ocp.args.StandardSave(host_value))
            self._mngr.wait_until_finished()
        # The writer already holds the full host snapshot -- persist it as
        # the flat resume image too (the restore-side fast path).  AFTER the
        # orbax commit, so the image can never be newer than the durable
        # checkpoint it mirrors.
        _write_resume_image(self._dir, step, host_value)

    def _surface_error_locked(self) -> None:
        """Re-raise a stashed writer failure (caller holds ``self._cv``)."""
        if self._error is not None:
            exc, self._error = self._error, None
            raise RuntimeError(
                f"background checkpoint write failed; last committed step: "
                f"{self.committed_step}") from exc

    def _drain(self) -> None:
        """Block until the background writer is idle, then surface any
        stashed writer error.  If the writer is wedged (dead filesystem),
        this blocks -- under preemption the GracefulShutdown watchdog
        force-exits and recovery falls back to ``committed_step``."""
        with self._cv:
            while self._pending is not None or self._busy:
                self._cv.wait(self._WAIT_S)
            self._surface_error_locked()

    def finalize(self) -> None:
        """Barrier on any in-flight background save; call before exit."""
        if self._mngr is None:
            return
        self._drain()
        self._mngr.wait_until_finished()


def _span(tracer: Any, name: str, parent: Any = None, **attrs: Any):
    """``tracer.span`` when a tracer is wired, else a no-op context -- the
    checkpoint/resume helpers must work for callers that never built one.
    Spans opened on helper threads pass ``parent`` explicitly: the tracer's
    current-span contextvar is thread-local and empty there."""
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, parent=parent, **attrs)


def _snapshot_to_host(value: Any) -> Any:
    """Device->host snapshot of a checkpoint pytree.  Every device-to-host
    copy is STARTED before any is awaited, so the stall is one overlapped
    transfer, not a serial per-leaf walk.  Safe to hand off: the host
    copies are fully materialized before this returns, so even callers
    whose step functions DONATE their state buffers (mnist/bert/resnet)
    can dispatch the next step immediately -- nothing here reads a device
    buffer after the handoff."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(value)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            leaf.copy_to_host_async()
    host = [np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
            for leaf in leaves]
    return jax.tree.unflatten(treedef, host)


#: Flat host-snapshot mirror of the latest committed checkpoint, written
#: beside the orbax step dirs (single-process snapshot pipeline only).
_RESUME_IMAGE = "resume-image.bin"

#: Bytes of the sha256 footer appended to the resume-image pickle: read
#: verifies payload integrity BEFORE unpickling, so a torn or bit-rotted
#: image classifies as ``corrupt`` instead of surfacing as an arbitrary
#: unpickling exception (or, worse, silently wrong state).
_CKPT_SHA_LEN = 32

#: Structured reason of the most recent checkpoint fallback taken in this
#: process ("" = happy path).  Set by the integrity ladder
#: (``_load_resume_image`` / ``_orbax_restore_with_fallback``), consumed
#: and cleared by ``_push_resume_record`` so the resume telemetry record
#: carries the reason onto the incident bundle (obs/incident.py).
_LAST_RESUME_FALLBACK = ""


def _note_fallback_metric(metric: str, reason: str) -> None:
    """Record one classified checkpoint fallback: count it per reason and
    remember the reason for the next resume record."""
    global _LAST_RESUME_FALLBACK
    _LAST_RESUME_FALLBACK = reason
    METRICS.inc(metric, reason=reason)


def _write_resume_image(path: str, step: int, host_value: Any) -> None:
    """Persist the host snapshot as a flat **resume image** beside the orbax
    commit: ``(step, pytree-of-numpy)`` in one pickle, atomically replaced.
    Restore then costs a single sequential file read plus one ``device_put``
    pass, instead of driving orbax's chunked tensorstore reassembly -- which
    measures both slower and wildly variable (seconds to tens of seconds for
    identical state) on few-core hosts.  Strictly an optimization: the write
    is best-effort and the orbax checkpoint stays the durable, elastic-safe
    source of truth (any image problem falls back to it in
    ``_load_resume_image``)."""
    if not path:
        return
    import pickle

    target = os.path.join(path, _RESUME_IMAGE)
    tmp = f"{target}.tmp-{os.getpid()}"
    try:
        payload = pickle.dumps((step, host_value),
                               protocol=pickle.HIGHEST_PROTOCOL)
        with open(tmp, "wb") as f:
            # sha256 footer over the pickle payload: the read side verifies
            # it before unpickling (docs/RECOVERY.md integrity ladder).
            f.write(payload)
            f.write(hashlib.sha256(payload).digest())
        os.replace(tmp, target)  # readers see old-or-new, never torn
    # analyzer: allow[broad-except]: the durable orbax commit already
    # succeeded when this runs; a failed image write costs the next resume
    # its fast path, never correctness.
    except Exception as exc:
        print(f"ckpt: resume image write failed ({exc!r}); "
              f"next resume will use the orbax restore path")
        try:
            os.remove(tmp)
        except OSError:
            pass


def _load_resume_image(path: str, latest: int, template: Any) -> Any:
    """Resume fast path: rebuild state from the flat image written by
    ``_write_resume_image`` -- one sequential read, one ``device_put`` pass
    onto the template's CURRENT shardings.  Returns ``None`` (caller falls
    back to the orbax restore) with a CLASSIFIED reason -- ``missing``,
    ``corrupt`` (read error, truncation, sha256 footer mismatch, unpickle
    failure), ``stale`` (``step != latest``, e.g. a newer sync-mode save
    superseded it), or ``structure_mismatch`` (template/image tree shape
    drift) -- counted per reason in
    ``trainingjob_resume_image_fallbacks_total`` and stamped onto the next
    resume telemetry record.  ``TRAININGJOB_CKPT_FAULT=resume_image`` flips
    one byte of the image after the read, deterministically exercising the
    corrupt rung (docs/RECOVERY.md)."""
    if not resume_fastpath_enabled():
        return None
    import jax

    if jax.process_count() != 1:
        return None

    def fall(reason: str, detail: str = "") -> None:
        _note_fallback_metric("trainingjob_resume_image_fallbacks_total",
                              reason)
        suffix = f" ({detail})" if detail else ""
        print(f"resume: image fallback reason={reason}{suffix}; "
              f"using orbax restore")

    target = os.path.join(path, _RESUME_IMAGE)
    if not os.path.exists(target):
        fall("missing")
        return None
    import pickle

    try:
        with open(target, "rb") as f:
            raw = f.read()
    except OSError as exc:
        fall("corrupt", f"read failed: {exc!r}")
        return None
    if os.environ.get(constants.CKPT_FAULT_ENV, "") == "resume_image" and raw:
        # Deterministic corruption injection: flip one byte so the sha256
        # footer check below takes the corrupt rung.
        raw = bytes([raw[0] ^ 0xFF]) + raw[1:]
    if len(raw) <= _CKPT_SHA_LEN:
        fall("corrupt", f"truncated ({len(raw)} bytes)")
        return None
    body, footer = raw[:-_CKPT_SHA_LEN], raw[-_CKPT_SHA_LEN:]
    if hashlib.sha256(body).digest() != footer:
        fall("corrupt", "sha256 mismatch")
        return None
    try:
        step, host_value = pickle.loads(body)
    # analyzer: allow[broad-except]: unpickling a verified-but-wrong payload
    # can raise nearly anything; every failure is the corrupt rung.
    except Exception as exc:
        fall("corrupt", f"unpickle failed: {exc!r}")
        return None
    if step != latest:
        fall("stale", f"image step {step} != latest {latest}")
        return None
    try:
        restored = jax.tree.map(
            lambda t, x: (jax.device_put(x, t.sharding)
                          if isinstance(t, jax.ShapeDtypeStruct) else x),
            template, host_value)
    # analyzer: allow[broad-except]: a structure-mismatched image (resumed
    # with a different model config) must never fail the resume -- the orbax
    # checkpoint is the source of truth and restores the same state, slower.
    except Exception as exc:
        fall("structure_mismatch", f"{exc!r}")
        return None
    print(f"resume: step {step} restored from resume image")
    return restored


def _orbax_restore_with_fallback(manager: Any, latest: int,
                                 template: Any) -> Any:
    """Orbax restore with a committed-step fallback ladder: try ``latest``
    first, then walk earlier retained steps (``max_to_keep`` keeps the
    previous commit around) newest-first.  Each failed rung is counted in
    ``trainingjob_ckpt_restore_fallbacks_total`` with reason
    ``corrupt_latest`` (the newest step was unreadable) or
    ``corrupt_retained`` (an older rung also failed) and stamped onto the
    resume record.  ``TRAININGJOB_CKPT_FAULT=corrupt_latest`` fails the
    latest rung deterministically, proving the ladder reaches the previous
    committed step.  Exhausting every rung re-raises the first error --
    there is genuinely nothing to restore from."""
    import orbax.checkpoint as ocp

    steps = sorted({int(s) for s in manager.all_steps()}, reverse=True)
    if latest not in steps:
        steps.insert(0, latest)
    inject = os.environ.get(constants.CKPT_FAULT_ENV, "") == "corrupt_latest"
    first_err: Optional[BaseException] = None
    for step in steps:
        try:
            if inject and step == latest:
                raise ValueError(
                    "injected corrupt checkpoint (TRAININGJOB_CKPT_FAULT="
                    f"corrupt_latest, step {step})")
            restored = manager.restore(
                step, args=ocp.args.StandardRestore(template))
        # analyzer: allow[broad-except]: a corrupt rung can fail anywhere in
        # orbax/tensorstore; classify and try the next retained step.
        except Exception as exc:
            if first_err is None:
                first_err = exc
            reason = ("corrupt_latest" if step == latest
                      else "corrupt_retained")
            _note_fallback_metric("trainingjob_ckpt_restore_fallbacks_total",
                                  reason)
            print(f"resume: orbax restore of step {step} failed "
                  f"reason={reason} ({type(exc).__name__}: "
                  f"{str(exc)[:200]}); trying previous committed step")
            continue
        if step != latest:
            print(f"resume: restored previous committed step {step} "
                  f"(latest {latest} unreadable)")
        return restored
    raise first_err  # every retained step failed; nothing to fall back to


def overlapped_restore(restore_fn: Callable[[], Any],
                       compile_fn: Optional[Callable[[], Any]] = None,
                       tracer: Any = None, trace_parent: Any = None,
                       overlap: Optional[bool] = None):
    """Run the checkpoint restore and the (cache-warm) XLA compile as
    overlapped phases, so warm resume costs ~max(restore, compile) instead
    of their sum -- the two dominant serial terms in BENCH_r05's
    ``recovery_124m`` breakdown.

    ``restore_fn()`` -> restored state, on the calling thread (span
    ``resume.restore``).  ``compile_fn()`` -> the AOT-compiled step, e.g.
    ``step_fn.lower(*abstract_args).compile()``, on a helper thread (span
    ``resume.compile``); with the persistent compile cache warm this is
    trace + cache read, not a real XLA compile.  ``overlap=False`` (or
    ``TRAININGJOB_RESUME_OVERLAP=0``) runs the same two phases serially,
    still itemized -- the A/B baseline the ``time_to_resume_training``
    bench leg measures against.

    A failed compile never fails the resume: it is an optimization, so the
    error is printed and the compiled step comes back None (the first step
    falls back to trace+compile as before).

    Returns ``(restored, compiled, timings)``; timings keys ``restore_s``,
    ``compile_s``, ``wall_s``, ``overlap`` (whether the phases actually ran
    concurrently)."""
    if overlap is None:
        overlap = resume_fastpath_enabled()
    result: Dict[str, Any] = {}

    def run_compile() -> None:
        t0 = time.perf_counter()
        try:
            with _span(tracer, "resume.compile", parent=trace_parent):
                result["compiled"] = compile_fn()
        # analyzer: allow[broad-except]: the warm AOT compile is an
        # optimization -- any failure (cache miss, lowering quirk) must fall
        # back to compiling at the first step, never kill the resume.
        except Exception as exc:
            result["error"] = exc
        result["compile_s"] = time.perf_counter() - t0

    t_wall = time.perf_counter()
    thread: Optional[threading.Thread] = None
    if overlap and compile_fn is not None:
        thread = threading.Thread(target=run_compile, daemon=True,
                                  name="resume-compile")
        thread.start()
    t0 = time.perf_counter()
    with _span(tracer, "resume.restore", parent=trace_parent):
        restored = restore_fn()
    restore_s = time.perf_counter() - t0
    if thread is not None:
        thread.join()
    elif compile_fn is not None:
        run_compile()
    if "error" in result:
        err = result["error"]
        print(f"resume: warm compile failed ({type(err).__name__}: "
              f"{str(err)[:200]}); first step will compile", flush=True)
    timings = {
        "restore_s": restore_s,
        "compile_s": result.get("compile_s", 0.0),
        "wall_s": time.perf_counter() - t_wall,
        "overlap": thread is not None,
    }
    _push_resume_record(timings)
    return restored, result.get("compiled"), timings


def _push_resume_record(timings: Dict[str, Any]) -> None:
    """Best-effort push of the resume span durations to the controller's
    telemetry sink (short-lived emitter; no-op when the operator did not
    inject the address/identity env).  The incident flight recorder uses
    them to split the post-recovery downtime tail into
    rendezvous/restore/compile phases."""
    global _LAST_RESUME_FALLBACK
    fallback, _LAST_RESUME_FALLBACK = _LAST_RESUME_FALLBACK, ""
    timings["fallback"] = fallback
    emitter = TelemetryEmitter()
    if not emitter.enabled:
        return
    try:
        emitter.emit_resume(timings["restore_s"] * 1e3,
                            timings["compile_s"] * 1e3,
                            bool(timings["overlap"]),
                            fallback=fallback)
    finally:
        emitter.close()


def push_rendezvous_record(total_ms: float, rung: str, reason: str = "",
                           phase_ms: Optional[Dict[str, float]] = None
                           ) -> None:
    """Best-effort push of a live re-rendezvous outcome (rung taken +
    per-phase wall, docs/ELASTIC.md) to the controller's telemetry sink --
    the same short-lived-emitter shape as ``_push_resume_record``.  Called
    by the elastic resize ladder on rebootstrap success (rung=live) and
    again on degrade, so the incident bundle's ``rung`` always reflects the
    path that actually ran."""
    emitter = TelemetryEmitter()
    if not emitter.enabled:
        return
    try:
        emitter.emit_rendezvous(total_ms, rung, reason=reason,
                                phase_ms=phase_ms)
    finally:
        emitter.close()


def resume_fastpath_enabled() -> bool:
    """Whether the resume fast path (overlapped restore+compile AND the
    executable snapshot) is on.  ``TRAININGJOB_RESUME_OVERLAP=0`` turns the
    WHOLE fast path off, reproducing the legacy serial resume -- restore,
    then trace + compile through the HLO-level cache -- which is the A/B
    baseline the ``time_to_resume_training`` bench leg measures against."""
    return os.environ.get(constants.RESUME_OVERLAP_ENV, "1") != "0"


def load_executable_snapshot(path: str) -> Any:
    """Deserialize a compiled step executable stored by
    ``store_executable_snapshot``; returns the loaded executable or None
    (missing, corrupt, or incompatible -- the caller falls back to
    trace + compile).

    This is the second, coarser level of compile persistence: XLA's
    HLO-level cache still pays Python trace + lowering on every resume
    (seconds at 124M params, and pure CPU, so "overlapping" it with the
    restore buys nothing when both compete for the same cores).  The
    snapshot skips trace, lower, AND compile -- a warm resume's compile
    term becomes one file read, and genuinely hides under the restore
    even on a single-core host."""
    if not path or not os.path.exists(path):
        return None
    import pickle

    try:
        # The snapshot lives in the job's own compile-cache directory
        # (written by a prior incarnation of this same job), so unpickling
        # it is the same trust boundary as the checkpoint itself.
        with open(path, "rb") as f:
            ser, in_tree, out_tree = pickle.load(f)
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        return deserialize_and_load(ser, in_tree, out_tree)
    # analyzer: allow[broad-except]: the snapshot is an optimization; any
    # load failure (truncated file, jax/topology mismatch, pickle drift)
    # must fall back to the trace+compile path, never kill the resume.
    except Exception as exc:
        print(f"resume: executable snapshot unusable "
              f"({type(exc).__name__}); recompiling", flush=True)
        return None


def store_executable_snapshot(path: str, compiled: Any) -> None:
    """Best-effort serialize ``compiled`` (a ``jax.stages.Compiled``) to
    ``path`` for the next resume's ``load_executable_snapshot``.  Atomic
    via rename, so a crash mid-write leaves the previous snapshot (or
    nothing) -- same discipline as the orbax commit."""
    if not path or compiled is None:
        return
    import pickle

    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        from jax.experimental.serialize_executable import serialize

        payload = serialize(compiled)
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, path)
    # analyzer: allow[broad-except]: snapshot persistence is best-effort
    # (an unserializable executable, a read-only cache dir); the run must
    # proceed with the in-memory executable it already has.
    except Exception as exc:
        print(f"executable snapshot store failed ({type(exc).__name__}: "
              f"{str(exc)[:120]})", flush=True)
        try:
            os.remove(tmp)
        except OSError:
            pass


def aot_or_jit(compiled: Any, step_fn: Callable) -> Callable:
    """Prefer the AOT-compiled step from ``overlapped_restore``; on ANY call
    failure (signature drift: real batch dtype/sharding vs the abstract
    args) fall back PERMANENTLY to the jitted step.  The AOT step is an
    optimization -- it skips the first-step re-trace -- never a correctness
    dependency."""
    if compiled is None:
        return step_fn
    fell_back = [False]

    def run(params, opt_state, tokens):
        if not fell_back[0]:
            try:
                return compiled(params, opt_state, tokens)
            # analyzer: allow[broad-except]: XLA raises backend-specific
            # errors on signature mismatch; any failure here must re-route
            # to the jitted step, not kill training.
            except Exception as exc:
                fell_back[0] = True
                print(f"aot step fallback ({type(exc).__name__}: "
                      f"{str(exc)[:120]}); recompiling via jit", flush=True)
        return step_fn(params, opt_state, tokens)

    return run


class GracefulShutdown:
    """Preemption-aware step loop: SIGTERM sets a flag (a GKE spot reclaim
    gives ~30 s of notice; localproc's drain delivers the same signal) and
    the loop checkpoints at the *current* step and exits 143 -- so recovery
    replays zero steps instead of up to ``ckpt_every`` (VERDICT r3 Missing
    #4).  143 is in the default ``restarting_exit_code`` set, so the
    operator's restart machinery treats it as restart-worthy, not failure.

    The handler only flips a flag: calling orbax from signal context would
    race the background save thread.  The loop polls between steps.
    """

    EXIT_CODE = 143

    def __init__(self, stuck_grace: float = 3.0) -> None:
        self.requested = False
        self._surfaced = False
        self._save_done = False
        self._prev: Any = None
        #: After SIGTERM, how long the step loop gets to surface and
        #: checkpoint before the watchdog force-exits.  A worker whose peer
        #: was preempted is typically BLOCKED inside a collective (a C call
        #: no Python signal handler can interrupt) -- without the watchdog it
        #: burns the whole kubelet grace period doing nothing, then loses the
        #: graceful exit code too.  On force-exit the recovery point is the
        #: last async save.
        self._stuck_grace = stuck_grace

    def install(self) -> "GracefulShutdown":
        import os as _os
        import threading

        def _watchdog():
            time.sleep(self._stuck_grace)
            if self._surfaced:
                # Step loop surfaced and is checkpointing -- but the save is
                # COLLECTIVE, and if this SIGTERM was caused by a peer's
                # death it can block forever.  Give it a bounded window,
                # then force-exit 143 anyway: orbax's atomic tmp-dir commit
                # discards the incomplete save and recovery falls back to
                # the last periodic checkpoint.
                time.sleep(3 * self._stuck_grace)
                if self._save_done:
                    return
                print("shutdown watchdog: preemption checkpoint stuck; "
                      f"force-exiting {self.EXIT_CODE}", flush=True)
            else:
                print("shutdown watchdog: step loop stuck past "
                      f"{self._stuck_grace}s; force-exiting {self.EXIT_CODE}",
                      flush=True)
            _os._exit(self.EXIT_CODE)

        def _handler(signum, frame):
            self.requested = True
            threading.Thread(target=_watchdog, daemon=True).start()

        self._prev = signal.signal(signal.SIGTERM, _handler)
        return self

    def checkpoint_and_exit(self, save: Callable[[], None]) -> None:
        """Call from the step loop once ``requested`` is observed."""
        import os as _os

        self._surfaced = True
        save()
        self._save_done = True
        print("preemption checkpoint committed; exiting 143", flush=True)
        # os._exit, NOT SystemExit: normal interpreter teardown joins the
        # jax.distributed / orbax service threads, which can block forever
        # when a peer is already dead -- burning the kubelet grace and
        # downgrading the exit to SIGKILL.
        _os._exit(self.EXIT_CODE)


class StepProfiler:
    """Env-gated workload-side profiling + per-step telemetry (SURVEY.md §5.1).

    ``TRAININGJOB_PROFILE_DIR=/path`` + ``TRAININGJOB_PROFILE_STEPS=a:b``
    wraps steps [a, b) in ``jax.profiler.start_trace/stop_trace`` (view with
    tensorboard/xprof); ``TRAININGJOB_STEP_TIMES=1`` logs per-step wall time
    so a throughput regression is diagnosable from the log, not one scalar.
    When the operator injected ``TRAININGJOB_TELEMETRY_ADDR`` (pod.set_env),
    every completed step is additionally pushed to the controller-side
    aggregator (obs/telemetry.py) -- step index, wall ms, tokens, loss --
    feeding throughput/MFU/straggler/stall accounting.
    """

    def __init__(self, units_per_step: float = 0.0,
                 flops_per_step: float = 0.0, unit: str = "tokens") -> None:
        self.trace_dir = os.environ.get(constants.PROFILE_DIR_ENV, "")
        rng = os.environ.get(constants.PROFILE_STEPS_ENV, "2:5")
        try:
            a, b = rng.split(":")
            self.start_step, self.stop_step = int(a), int(b)
        except ValueError:
            self.start_step, self.stop_step = 2, 5
        self.step_times = os.environ.get(constants.STEP_TIMES_ENV) == "1"
        self.emitter = TelemetryEmitter(units_per_step=units_per_step,
                                        flops_per_step=flops_per_step,
                                        unit=unit)
        if self.step_times or self.emitter.enabled:
            _ensure_workload_logging()
        self._log = get_logger("trainingjob.workload.steps")
        self._tracing = False
        self._t0 = 0.0
        #: All step-visible checkpoint stalls this run (end-of-run summary).
        self.ckpt_stalls: List[float] = []
        self._ckpt_stall_ms: Optional[float] = None
        #: HBM sampler: every N steps, read device memory-in-use and ride it
        #: on the telemetry record as ``hbm_bytes`` -- an OOM-shaped incident
        #: then carries a memory timeline.  0 disables; sampling only when
        #: telemetry is on (the value has nowhere else to go).
        try:
            self.hbm_sample_steps = int(os.environ.get(
                constants.HBM_SAMPLE_STEPS_ENV, "32") or "0")
        except ValueError:
            self.hbm_sample_steps = 32

    def step_start(self, i: int) -> None:
        if self.trace_dir and not self._tracing and i == self.start_step:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
        self._t0 = time.perf_counter()

    def step_end(self, i: int, sync: Any = None,
                 loss: Optional[float] = None) -> None:
        """``sync``: a device value to fence on (its device-to-host read is
        the only reliable completion barrier -- ``block_until_ready`` can
        return early on the axon runtime; see
        tools/repro_block_until_ready.py)."""
        stopping = self._tracing and i + 1 >= self.stop_step
        if sync is not None and (self.step_times or stopping
                                 or self.emitter.enabled):
            import jax

            # analyzer: allow[host-sync-in-hot-loop] THE deliberate
            # completion fence: per-step wall time is the measurement, and
            # a device-to-host read is the only reliable barrier
            # (block_until_ready returns early on the axon runtime; see
            # tools/repro_block_until_ready.py).
            jax.device_get(sync)
        if stopping:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            print(f"profiler trace written to {self.trace_dir} "
                  f"(steps {self.start_step}:{self.stop_step})", flush=True)
        ms = (time.perf_counter() - self._t0) * 1e3
        if self.step_times:
            self._log.info("step_time step=%d ms=%.2f", i, ms)
        if self.emitter.enabled:
            hbm = None
            if (self.hbm_sample_steps > 0
                    and i % self.hbm_sample_steps == 0):
                hbm = _hbm_bytes_in_use()
            self.emitter.emit(i, ms, loss=_scalar(loss),
                              ckpt_ms=self._ckpt_stall_ms, hbm_bytes=hbm)
            self._ckpt_stall_ms = None

    def record_checkpoint_stall(self, ms: float) -> None:
        """Step-visible checkpoint stall (``CheckpointState.save``'s
        return).  Kept for the end-of-run summary and attached to the NEXT
        telemetry record -- the loop saves after ``step_end``'s emit, so
        the stall rides the following step's push."""
        self.ckpt_stalls.append(ms)
        self._ckpt_stall_ms = ms

    def log_throughput(self, prefix: str, steps_done: int,
                       units_per_step: float, seconds: float,
                       unit: str = "tokens") -> None:
        """Structured throughput summary (carries trace/span ids under
        --log-json), replacing the old bare ``print(throughput_line(...))``
        idiom."""
        _ensure_workload_logging()
        self._log.info("%s", throughput_line(prefix, steps_done,
                                             units_per_step, seconds, unit))

    def close(self) -> None:
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
        self.emitter.close()


def _hbm_bytes_in_use() -> Optional[float]:
    """Device memory in use (bytes): ``memory_stats()`` where the backend
    exposes it (TPU, GPU), else the sum of live array nbytes -- the CPU
    backend has no allocator stats, but live_arrays() still tracks what the
    program holds.  None when neither works; sampling must never fail a
    step."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_in_use" in stats:
            return float(stats["bytes_in_use"])
        return float(sum(getattr(a, "nbytes", 0)
                         for a in jax.live_arrays()))
    # analyzer: allow[broad-except]: backend-specific -- memory_stats is
    # unimplemented on some runtimes and live_arrays can race a deletion;
    # the HBM sample is observability, never worth a step.
    except Exception:
        return None


def _scalar(value: Any) -> Optional[float]:
    """Device scalar -> float, best-effort (telemetry must never crash a
    step on a weird dtype or an aborted transfer)."""
    if value is None:
        return None
    try:
        # analyzer: allow[host-sync-in-hot-loop] runs after step_end's
        # device_get fence, so the value is already on host; the float()
        # is a cheap local conversion for the telemetry record.
        return float(value)
    # analyzer: allow[broad-except]: jax raises backend-specific errors on
    # device-to-host transfer; a loss we cannot read is just omitted.
    except Exception:
        return None


#: Substrings identifying transport/collective failures caused by a LOST
#: PEER (Gloo/gRPC/coordination-service surfaces); a deterministic local bug
#: (shape error, checkpoint mismatch) matches none of these and must crash
#: normally so exit-code policy can mark the job Failed instead of
#: restart-looping it forever.
_PEER_LOSS_MARKERS = (
    "gloo", "grpc", "connection reset", "connection refused", "broken pipe",
    "socket closed", "unavailable", "deadline exceeded", "peer",
    "coordination service", "barrier", "heartbeat", "disconnect",
)


def looks_like_peer_loss(exc: BaseException) -> bool:
    """Match the exception and its EXPLICIT cause chain: orbax/asyncio wrap
    the underlying gRPC/Gloo error (``raise X from grpc_err``) and the
    marker often lives only on the cause.

    Implicit context (``__context__``) is followed only from a node that is
    itself transport-shaped (OSError/ConnectionError/TimeoutError): library
    code that re-raises inside an ``except`` block around a socket error
    chains implicitly (no ``from``), and skipping that hop would classify a
    genuine peer preemption as a local crash.  From any OTHER exception
    type the implicit context is deliberately NOT followed -- a
    deterministic local bug raised while HANDLING a transport error would
    inherit the transport marker and restart-loop forever instead of
    reaching the exit-code policy as a failure."""
    io_shaped = (OSError, ConnectionError, TimeoutError)
    seen = set()
    node: Optional[BaseException] = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        text = f"{type(node).__name__}: {node}".lower()
        if any(marker in text for marker in _PEER_LOSS_MARKERS):
            return True
        nxt = node.__cause__
        if (nxt is None and isinstance(node, io_shaped)
                and not node.__suppress_context__):
            # `raise X from None` sets __suppress_context__: the raiser
            # explicitly disclaimed the context -- honor that, or a
            # deterministic local bug would restart-loop as 143 again.
            nxt = node.__context__
        node = nxt
    return False


class peer_loss_guard:
    """Context manager around distributed workload code: a PEER-LOSS-shaped
    exception in a multi-process job exits 143 via ``os._exit``
    (restart-worthy, and no interpreter teardown to hang on dead-peer
    service threads).  Covers the collectives hiding outside the step
    function too -- orbax's sharded save/restore does its own allgathers and
    dies just as loudly when a peer is preempted mid-save.  Exceptions that
    do not look like transport failures propagate (a deterministic bug must
    reach the exit-code policy as a failure, not crash-loop as 143)."""

    def __init__(self, shutdown: Any = None) -> None:
        self._shutdown = shutdown

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None or exc_type in (KeyboardInterrupt, SystemExit):
            return False
        import os as _os

        import jax

        sigterm_seen = (self._shutdown is not None
                        and self._shutdown.requested)
        if sigterm_seen or (jax.process_count() > 1
                            and looks_like_peer_loss(exc)):
            print(f"distributed section failed ({exc_type.__name__}: "
                  f"{str(exc)[:300]}); exiting 143 for operator restart",
                  flush=True)
            _os._exit(GracefulShutdown.EXIT_CODE)
        return False


def run_elastic_loop(*, step_fn: Callable, batch_at: Callable,
                     state: "CheckpointState", params: Any, opt_state: Any,
                     steps: int, start_step: int, ckpt_every: int,
                     eval_fn: Optional[Callable] = None,
                     eval_every: int = 0,
                     units_per_step: float = 0.0,
                     flops_per_step: float = 0.0,
                     resize_watch: Optional[Any] = None,
                     tracer: Optional[Any] = None,
                     trace_parent: str = ""):
    """The shared elastic train loop (llama_elastic / moe_pretrain):
    checkpoint every ``ckpt_every`` steps, print the first post-resume step
    (the elastic-recovery endpoint the bench keys on), honor the SIGTERM
    preemption checkpoint, and run EVERYTHING -- including the orbax save
    collectives and the final ``finalize()`` commit barrier -- under
    ``peer_loss_guard`` so a peer preemption anywhere in the loop exits 143
    (restart-worthy), never a crash.

    ``resize_watch`` (a ``rendezvous.GenerationWatcher``) arms the in-place
    resize fast path: when the controller republishes a newer rendezvous
    generation, the loop exits cleanly at the next step boundary with
    ``resize_watch.pending`` set to the doc and ``resize_watch.resume_step``
    to the step the caller should continue from after resharding
    (docs/ELASTIC.md).  With ``TRAININGJOB_RESIZE_FASTPATH=0`` the signal
    instead takes the baseline path: checkpoint and exit 143, letting the
    operator restart the process at the new width.

    Returns ``(params, opt_state, loss, t_start)`` where ``t_start`` is the
    wall time after the first completed step (for throughput accounting).
    """
    import jax

    from trainingjob_operator_tpu.data.loader import Prefetcher

    shutdown = GracefulShutdown().install()
    profiler = StepProfiler(units_per_step=units_per_step,
                            flops_per_step=flops_per_step)
    # Workload half of the trace contract: enabled only when the operator
    # injected TRAININGJOB_TRACE_CONTEXT into the pod env (pod.set_env), so
    # the run span joins the trace of the reconcile that created this pod.
    # Callers that emit their own spans between loop invocations (the
    # in-place resize cycle) pass their tracer in, so one instance -- and
    # one exported trace file -- carries the whole lifetime.
    if tracer is None:
        tracer, trace_parent = tracer_from_env()
    loss = None
    t_start = None
    t_loop = time.time()
    # One-step-ahead prefetch: batch_at(i) runs on a background thread while
    # step i-1 executes on the chip (batch_at ends in an async device_put,
    # so the host->HBM DMA overlaps compute too).
    with tracer.span("train.run", parent=trace_parent,
                     start_step=start_step, steps=steps), \
            peer_loss_guard(shutdown=shutdown), \
            Prefetcher(batch_at, start_step, steps) as batches:
        for i, batch in batches:
            profiler.step_start(i)
            # The first step after a (re)start is trace+compile+step -- the
            # elastic-recovery component -- so it gets its own span name and
            # a real device fence; later steps dispatch async and the span
            # measures host-side dispatch only.
            with tracer.span("train.compile" if i == start_step
                             else "train.step", step=i):
                params, opt_state, loss = step_fn(params, opt_state, batch)
                if i == start_step:
                    # analyzer: allow[host-sync-in-hot-loop] first-step
                    # compile fence, gated to run once: splits
                    # trace+compile out of the recovery timing below.
                    jax.block_until_ready(loss)
            if i == start_step:
                t_start = time.time()
                # Trace + compile (compile-cache-sensitive) + one step:
                # the last recovery component after llama_elastic's
                # init/setup/restore breakdown.
                print(f"recovery_timing first_step_s="
                      f"{t_start - t_loop:.2f}", flush=True)
                if start_step > 0:
                    # analyzer: allow[host-sync-in-hot-loop] once, on the
                    # first post-resume step: the elastic-recovery
                    # endpoint the bench keys on.
                    print(f"step {i+1}/{steps} loss {float(loss):.4f} "
                          f"(first after resume)", flush=True)
            profiler.step_end(i, sync=loss, loss=loss)

            def save(step, wait=False):
                with tracer.span("train.checkpoint", step=step,
                                 wait=wait) as ckpt_span:
                    stall_ms = state.save(
                        {"params": params, "opt_state": opt_state,
                         "step": step}, wait=wait,
                        tracer=tracer, trace_parent=ckpt_span)
                profiler.record_checkpoint_stall(stall_ms)

            if shutdown.requested:
                shutdown.checkpoint_and_exit(lambda: save(i + 1, wait=True))
            if resize_watch is not None:
                doc = resize_watch.poll()
                if doc is not None:
                    # Drain the just-dispatched step before anchoring the
                    # resize: steps dispatch async, so without this fence
                    # the in-flight step's device time would be billed to
                    # the resize window ("last step before" would print
                    # before the last step finished).  Both paths below
                    # pay this drain identically.
                    # analyzer: allow[host-sync-in-hot-loop] resize-drain
                    # fence, runs once per resize, not per step.
                    jax.block_until_ready(loss)
                    if (os.environ.get(constants.RESIZE_FASTPATH_ENV, "")
                            == "0"):
                        # Fast path disabled: the old contract -- persist
                        # and exit 143 so the operator restarts us at the
                        # new width.  Printed BEFORE the save so both A/B
                        # arms of bench_elastic_resize anchor downtime at
                        # the same loop position (last step done, resize
                        # observed).
                        print(f"resize: generation {doc['generation']} "
                              f"observed at step {i+1}; fast path disabled, "
                              f"checkpointing for operator restart",
                              flush=True)
                        shutdown.checkpoint_and_exit(
                            lambda: save(i + 1, wait=True))
                    resize_watch.pending = doc
                    resize_watch.resume_step = i + 1
                    print(f"resize: generation {doc['generation']} "
                          f"(world {doc['world']}) observed at step {i+1}; "
                          f"leaving step loop for in-place reshard",
                          flush=True)
                    break
            if (i + 1) % ckpt_every == 0 or i == steps - 1:
                # analyzer: allow[host-sync-in-hot-loop] checkpoint-gated
                # log read, every ckpt_every steps; one scalar D2H.
                print(f"step {i+1}/{steps} loss {float(loss):.4f}",
                      flush=True)
                save(i + 1)
            if (eval_fn is not None and eval_every > 0
                    and (i + 1) % eval_every == 0):
                # Held-out loss on the params, not a training step.  The
                # eval set is FIXED (same batches every eval point), so the
                # series is comparable across checkpoints and elastic
                # widths.
                print(f"eval step {i+1} loss {eval_fn(params):.4f}",
                      flush=True)
        if profiler.ckpt_stalls:
            # The bench's save-side A/B keys on this line: snapshot-donate
            # vs direct-handoff step stall, measured at the same cadence.
            stalls = profiler.ckpt_stalls
            mode = "snapshot" if state.snapshot_mode() else "sync"
            print(f"ckpt_stall mode={mode} n={len(stalls)} "
                  f"avg_ms={sum(stalls) / len(stalls):.1f} "
                  f"max_ms={max(stalls):.1f}", flush=True)
        profiler.close()
        # analyzer: allow[host-sync-in-hot-loop] end-of-loop drain before
        # the finalize/commit barrier; runs once per loop exit.
        jax.block_until_ready(loss)
        if resize_watch is None or resize_watch.pending is None:
            # Commit any in-flight background save before exit.  NOT on
            # the in-place resize exit: the survivors keep their live
            # state, so the periodic save can finish committing in the
            # background while they reshard -- blocking here would put a
            # full checkpoint write inside the resize downtime window,
            # the exact round-trip the fast path exists to avoid.  (The
            # orbax fallback rung finalizes before it re-reads the dir.)
            state.finalize()
    if units_per_step and t_start is not None:
        profiler.log_throughput(
            "train_done", max(steps - start_step - 1, 1), units_per_step,
            max(time.time() - t_start, 1e-9))
    _maybe_export_trace(tracer)
    return params, opt_state, loss, t_start


def _maybe_export_trace(tracer) -> None:
    """Dump the workload trace (Chrome trace_event JSON, Perfetto-loadable)
    to ``$TRAININGJOB_TRACE_DIR/trace-<pid>.json`` when the dir is set.
    Best-effort: an unwritable dir must never fail a finished run."""
    trace_dir = os.environ.get(constants.TRACE_DIR_ENV, "")
    if not trace_dir or not tracer.enabled:
        return
    try:
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, f"trace-{os.getpid()}.json")
        with open(path, "w") as f:
            f.write(tracer.export_chrome())
        print(f"workload trace written to {path}", flush=True)
    except OSError as exc:
        print(f"trace export failed: {exc}", flush=True)


def accumulated_value_and_grad(loss_fn: Callable, params: Any, tokens,
                               accum: int):
    """``value_and_grad`` over ``accum`` microbatches via ``lax.scan``,
    averaging losses and gradients -- the standard HBM-for-throughput trade
    when the global batch exceeds one step's activation memory.  Exactly
    equals the full-batch gradient for mean-reduced losses (equal microbatch
    sizes); XLA keeps a single compiled microstep.

    ``loss_fn(params, tokens) -> scalar``; tokens' leading dim must divide
    by ``accum``."""
    import jax
    import jax.numpy as jnp

    if accum <= 1:
        return jax.value_and_grad(loss_fn)(params, tokens)
    B = tokens.shape[0]
    if B % accum != 0:
        raise ValueError(f"batch {B} not divisible by accum={accum}")
    # INTERLEAVED split (microbatch a = rows congruent to a mod accum), not
    # a contiguous reshape: with the batch dim sharded in contiguous blocks
    # over the data axes, a contiguous microbatch would live entirely on a
    # subset of shards whenever accum >= n_data shards (the elastic-shrink
    # case this feature targets), serializing the microsteps or forcing
    # per-microstep resharding.  Strided rows spread every microbatch
    # across all data shards; the gradient average is order-invariant.
    micro_batches = tokens.reshape(B // accum, accum,
                                   *tokens.shape[1:]).swapaxes(0, 1)

    def micro(carry, tb):
        acc_l, acc_g = carry
        l, g = jax.value_and_grad(loss_fn)(params, tb)
        return (acc_l + l, jax.tree.map(jnp.add, acc_g, g)), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (loss, grads), _ = jax.lax.scan(
        micro, (jnp.zeros((), jnp.float32), zeros), micro_batches)
    inv = 1.0 / accum
    return loss * inv, jax.tree.map(lambda x: x * inv, grads)


def round_global_batch(global_batch: int, shards: int,
                       accum: int = 1) -> "tuple[int, int]":
    """(batch, accum): largest multiple of ``shards * accum`` <= the request.

    Accumulation is the shedable factor: at a wider-than-planned elastic
    width it is clamped down first so the global batch never exceeds the
    request -- a silently INFLATED batch changes the loss trajectory and
    HBM footprint behind the user's back.  When even one row per data shard
    does not fit (batch < shards) the batch is inflated to exactly one row
    per shard, LOUDLY: an elastic scale-UP past the global batch must not
    turn a running job into a crash loop at the new width (the restart
    would re-derive the same width and die again).  Plan elastic max width
    <= global batch to avoid the inflation entirely.
    """
    shards = max(shards, 1)
    accum = max(accum, 1)
    if global_batch < shards:
        print(f"WARNING: global batch {global_batch} < {shards} data "
              f"shards; inflating to {shards} (one row per shard) -- the "
              f"loss trajectory changes at this width. Keep elastic max "
              f"width <= global batch to avoid this.", flush=True)
        return shards, 1
    # Pick the accum <= requested that yields the LARGEST rounded batch (on
    # ties, the largest accum -- smallest microbatch HBM).  Merely clamping
    # accum to fit would deflate the batch at widths where a smaller accum
    # tiles it exactly -- e.g. batch 12, shards 2, accum 4 rounds to 8,
    # while accum 2 keeps the requested 12 -- and a width-dependent batch
    # breaks the elastic contract that the loss trajectory is
    # width-independent.
    requested = accum
    best = None
    for a in range(min(accum, global_batch // shards), 0, -1):
        step = shards * a
        rounded = global_batch // step * step
        if best is None or rounded > best[0]:
            best = (rounded, a)
    rounded, accum = best
    if accum != requested:
        print(f"using gradient accumulation {accum} (requested {requested}) "
              f"for {shards} data shards at global batch {rounded}",
              flush=True)
    if rounded != global_batch:
        # A changed batch changes the loss trajectory; never do it silently
        # (the same rationale that forbids inflating it).
        print(f"rounded global batch {global_batch} -> {rounded} to tile "
              f"{shards} data shards x {accum} accumulation", flush=True)
    return rounded, accum


def build_batch_sources(*, prefix: str, vocab_size: int, global_batch: int,
                        local_batch: int, row0: int, seq: int,
                        batch_sharding, synthetic_key: int):
    """(batch_at, eval_batch_at | None, eval_every, eval_batches) from env.

    Shared data plumbing for the elastic workloads (llama_elastic,
    moe_pretrain).  Env, under the workload's ``prefix`` (e.g. ``LLAMA``):
    ``{P}_DATA`` (.tokens corpus; default synthetic), ``{P}_SEED``,
    ``{P}_EVAL_EVERY`` / ``{P}_EVAL_BATCHES`` / ``{P}_EVAL_FRACTION``.

    Both sources are stateless functions of (source, step) with NO
    process-layout input -- file windows or a global PRNG key -- so every
    elastic width sees the byte-identical global batch sequence; each
    process materializes only its contiguous row block.  When eval is on,
    the corpus TAIL is reserved for it (disjoint tokens, not a reseed:
    sampling the training tokens with a different seed would track
    memorization), and misconfigurations fail here at startup, not at the
    first eval step deep into paid TPU time.
    """
    import jax

    data_path = os.environ.get(f"{prefix}_DATA", "")
    seed = int(os.environ.get(f"{prefix}_SEED", str(synthetic_key)))
    eval_every = int(os.environ.get(f"{prefix}_EVAL_EVERY", "0"))
    eval_batches = int(os.environ.get(f"{prefix}_EVAL_BATCHES", "2"))
    eval_frac = float(os.environ.get(f"{prefix}_EVAL_FRACTION", "0.1"))
    if eval_every > 0:
        if eval_batches < 1:
            raise ValueError(
                f"{prefix}_EVAL_BATCHES={eval_batches} with eval enabled: "
                f"a zero-batch eval would print a bogus 0.0 loss")
        if not 0.0 < eval_frac < 1.0:
            raise ValueError(
                f"{prefix}_EVAL_FRACTION={eval_frac} must be in (0, 1)")
        if not data_path:
            # The held-out rationale only holds for a file corpus: with the
            # synthetic generator, "eval" is random tokens under a different
            # key and the printed loss series is pure noise.
            raise ValueError(
                f"{prefix}_EVAL_EVERY={eval_every} without {prefix}_DATA: "
                f"eval on the synthetic random-token stream measures "
                f"nothing; point {prefix}_DATA at a .tokens corpus or "
                f"disable eval")
    train_region = (0.0, 1.0 - eval_frac) if eval_every > 0 else (0.0, 1.0)

    ds = eval_ds = None
    if data_path:
        from trainingjob_operator_tpu.data import TokenDataset

        ds = TokenDataset(data_path, seed=seed, region=train_region)
        if ds.vocab_size > vocab_size:
            # XLA's gather clamps out-of-range ids: a mismatched corpus
            # would train on silently-corrupted tokens; refuse instead.
            raise ValueError(
                f"{data_path}: corpus vocab {ds.vocab_size} exceeds model "
                f"vocab {vocab_size}")
        ds.check_window(seq + 1)
        if eval_every > 0:
            eval_ds = TokenDataset(data_path, seed=seed,
                                   region=(1.0 - eval_frac, 1.0))
            eval_ds.check_window(seq + 1)  # tail must hold one window

    def make_batch_at(dataset, key_base):
        if dataset is not None:
            def fetch(i):
                local = dataset.batch(i, global_batch, seq,
                                      rows=slice(row0, row0 + local_batch))
                return globalize_batch(batch_sharding, local)
        else:
            def fetch(i):
                # Key = (base, step, ABSOLUTE row): content is a pure
                # function of the global row index, so every width agrees,
                # and each process generates only its own rows.
                k = jax.random.fold_in(jax.random.PRNGKey(key_base), i)
                keys = jax.vmap(lambda r: jax.random.fold_in(k, r))(
                    jax.numpy.arange(row0, row0 + local_batch))
                tokens = jax.vmap(lambda kk: jax.random.randint(
                    kk, (seq + 1,), 0, vocab_size))(keys)
                return globalize_batch(batch_sharding, tokens)
        return fetch

    batch_at = make_batch_at(ds, synthetic_key)
    eval_batch_at = (make_batch_at(eval_ds, synthetic_key ^ 0x5EED)
                     if eval_every > 0 else None)
    return batch_at, eval_batch_at, eval_every, eval_batches


def default_remat(n_layers: int) -> str:
    """Shared workload default: full-size configs cannot fit chip-saturating
    batches in 16 GB v5e HBM without remat, and "attn" (save the flash
    kernel's residuals) is the cheapest policy that does; tiny test configs
    skip remat entirely."""
    return "attn" if n_layers >= 32 else "none"


def mean_eval_fn(eval_loss, eval_batch_at, eval_batches: int):
    """Average a jitted ``eval_loss(params, tokens)`` over the FIXED
    held-out set (batches j = 0..N-1 every eval point -- comparable across
    checkpoints and elastic widths)."""
    def eval_fn(p):
        total = 0.0
        for j in range(eval_batches):
            total += float(eval_loss(p, eval_batch_at(j)))
        return total / eval_batches
    return eval_fn


def globalize_batch(sharding, local):
    """Per-process local batch shard -> global sharded array (identity when
    single-process)."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    import numpy as np

    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def throughput_line(prefix: str, steps_done: int, units_per_step: int,
                    seconds: float, unit: str = "tokens") -> str:
    rate = steps_done * units_per_step / max(seconds, 1e-9)
    return f"{prefix} steps={steps_done} {unit}/s={rate:.0f}"
