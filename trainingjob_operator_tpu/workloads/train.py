"""Shared training utilities: checkpoint/resume keyed on restart count.

The reference delegates checkpointing entirely to the workload, contributing
only the restart-count env and stable identity (SURVEY.md §5.4).  This module
is the workload half of that contract: orbax-backed save/restore under the
injected checkpoint dir, resumed whenever the operator restarts the pod.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous


class CheckpointState:
    """Tiny orbax wrapper: one pytree, latest-step retention."""

    def __init__(self, directory: str, value: Dict[str, Any], manager: Any):
        self.value = value
        self._dir = directory
        self._mngr = manager

    @classmethod
    def restore_or_init(cls, rdv: Rendezvous, init_value: Dict[str, Any],
                        subdir: Optional[str] = None) -> "CheckpointState":
        """Per-replica path by default; pass ``subdir`` for one path shared by
        every process of the job (elastic resume: the checkpoint must survive
        a world-size change, so it cannot be keyed on rank)."""
        directory = rdv.checkpoint_dir
        if not directory:
            return cls("", init_value, None)
        import orbax.checkpoint as ocp

        if subdir is not None:
            path = os.path.join(os.path.abspath(directory), subdir)
        else:
            path = os.path.join(os.path.abspath(directory),
                                rdv.replica_name or "worker",
                                str(rdv.replica_index))
        os.makedirs(path, exist_ok=True)
        manager = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(max_to_keep=2))
        latest = manager.latest_step()
        if latest is not None:
            try:
                restored = manager.restore(
                    latest, args=ocp.args.StandardRestore(init_value))
            except ValueError:
                # Template has placeholder (None) leaves -- e.g. elastic
                # resume where the param tree is only known from the
                # checkpoint itself: restore the saved structure as-is.
                restored = manager.restore(latest)
            return cls(path, restored, manager)
        return cls(path, init_value, manager)

    def save(self, value: Dict[str, Any]) -> None:
        self.value = value
        if self._mngr is None:
            return
        import orbax.checkpoint as ocp

        step = int(value.get("step", 0))
        self._mngr.save(step, args=ocp.args.StandardSave(value))
        self._mngr.wait_until_finished()
