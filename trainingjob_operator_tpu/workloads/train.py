"""Shared training utilities: checkpoint/resume keyed on restart count.

The reference delegates checkpointing entirely to the workload, contributing
only the restart-count env and stable identity (SURVEY.md §5.4).  This module
is the workload half of that contract: orbax-backed save/restore under the
injected checkpoint dir, resumed whenever the operator restarts the pod.

Checkpointing is **sharded and asynchronous**: sharded ``jax.Array`` leaves
are saved distributed -- every host writes only its addressable shards to the
shared directory, nothing is ever gathered to one device or host (a fully
replicated gather of Llama-2-7B + AdamW state is ~78 GB and OOMs a 16 GB v5e
chip) -- and the save runs in the background so the step loop never blocks on
I/O; ``finalize()`` barriers before exit.  Restore reshards onto the
*current* mesh, whatever width the job came back at -- the storage format is
the global array, so elastic resume needs no gather/re-shard choreography.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous


class CheckpointState:
    """Orbax wrapper: one pytree, async save, latest-step retention."""

    def __init__(self, directory: str, value: Dict[str, Any], manager: Any):
        self.value = value
        self._dir = directory
        self._mngr = manager

    @classmethod
    def restore_or_init(cls, rdv: Rendezvous, init_value: Dict[str, Any],
                        subdir: Optional[str] = None,
                        mesh: Any = None) -> "CheckpointState":
        """Per-replica path by default; pass ``subdir`` for one path shared by
        every process of the job (elastic resume: the checkpoint must survive
        a world-size change, so it cannot be keyed on rank).

        ``jax.Array`` leaves in ``init_value`` act as the restore template:
        the checkpoint is restored *onto their shardings* (the current mesh),
        regardless of the mesh shape at save time.  ``None`` leaves mean the
        structure is only known from the checkpoint itself.
        """
        directory = rdv.checkpoint_dir
        if not directory:
            return cls("", init_value, None)
        import orbax.checkpoint as ocp

        if subdir is not None:
            path = os.path.join(os.path.abspath(directory), subdir)
        else:
            path = os.path.join(os.path.abspath(directory),
                                rdv.replica_name or "worker",
                                str(rdv.replica_index))
        os.makedirs(path, exist_ok=True)
        manager = ocp.CheckpointManager(
            path, options=ocp.CheckpointManagerOptions(max_to_keep=2))
        latest = manager.latest_step()
        if latest is not None:
            import jax

            has_placeholders = any(
                leaf is None for leaf in jax.tree.leaves(
                    init_value, is_leaf=lambda x: x is None))
            if has_placeholders:
                # The param tree is only known from the checkpoint itself;
                # restore the saved structure as-is.
                restored = manager.restore(latest)
            else:
                # Abstract template: sharded leaves restore distributed onto
                # their CURRENT sharding (elastic resume across widths); a
                # template/checkpoint structure mismatch (e.g. resumed with a
                # different model config) fails loudly here, not deep in a
                # jitted step later.
                from jax.sharding import NamedSharding, PartitionSpec

                def abstract(x):
                    if isinstance(x, jax.Array):
                        sharding = x.sharding
                        if (mesh is not None
                                and not isinstance(sharding, NamedSharding)):
                            # Leaves created off-mesh (e.g. optimizer step
                            # counters) restore mesh-replicated; a committed
                            # single-device leaf would poison the jitted
                            # step's device set.
                            sharding = NamedSharding(mesh, PartitionSpec())
                        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                    sharding=sharding)
                    return x

                template = jax.tree.map(abstract, init_value)
                restored = manager.restore(
                    latest, args=ocp.args.StandardRestore(template))
            return cls(path, restored, manager)
        return cls(path, init_value, manager)

    def save(self, value: Dict[str, Any], wait: bool = False) -> None:
        """Background save (all processes must call it -- sharded leaves are
        written collectively, each host its own shards).  A new save waits for
        the previous one's commit; pass ``wait=True`` to barrier immediately
        (pre-exit / preemption checkpoint)."""
        self.value = value
        if self._mngr is None:
            return
        import orbax.checkpoint as ocp

        step = int(value.get("step", 0))
        self._mngr.save(step, args=ocp.args.StandardSave(value))
        if wait:
            self._mngr.wait_until_finished()

    def finalize(self) -> None:
        """Barrier on any in-flight background save; call before exit."""
        if self._mngr is not None:
            self._mngr.wait_until_finished()


def round_global_batch(global_batch: int, shards: int) -> int:
    """Largest multiple of ``shards`` <= global_batch (floor ``shards``)."""
    shards = max(shards, 1)
    return max(shards, global_batch // shards * shards)


def globalize_batch(sharding, local):
    """Per-process local batch shard -> global sharded array (identity when
    single-process)."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    import numpy as np

    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def throughput_line(prefix: str, steps_done: int, units_per_step: int,
                    seconds: float, unit: str = "tokens") -> str:
    rate = steps_done * units_per_step / max(seconds, 1e-9)
    return f"{prefix} steps={steps_done} {unit}/s={rate:.0f}"
