"""Elastic Llama-2 pretrain -- BASELINE config 5 (preemptible v5e-32).

The flagship elastic workload: width comes from the operator
(TRAININGJOB_ELASTIC_REPLICAS / JAX process env), so after a spot preemption
the SAME program restarts at whatever width survived, rebuilds a narrower
``dp x fsdp x tp (x sp)`` mesh over the remaining chips, restores the shared
checkpoint, and keeps training -- the workload half of the operator's elastic
resize (controller/pod.py _elastic_resize); recovery budget <90 s
(BASELINE.md).

Parallelism is the scaling-book layout: fsdp shards params/optimizer over the
data axis (per-layer all-gathers ride ICI), tp shards heads/ffn, sp enables
ring attention for long context (parallel/ringattention.py), dp carries
multislice DCN when present.  The global batch is kept constant across widths
(per-process share rescales), so the loss trajectory is width-independent.

Run: ``python -m trainingjob_operator_tpu.workloads.llama_elastic``.
Env: LLAMA_CONFIG=tiny|7b, LLAMA_TP, LLAMA_SP, LLAMA_PP (pipeline stages),
LLAMA_ACCUM (gradient-accumulation microbatches), LLAMA_STEPS, LLAMA_BATCH
(global), LLAMA_SEQ, LLAMA_LR, LLAMA_CKPT_EVERY, LLAMA_DATA (path to a
``.tokens`` corpus, data/tokens.py; default trains on synthetic tokens),
LLAMA_SEED, LLAMA_EVAL_EVERY (held-out eval cadence in steps; 0 = off),
LLAMA_EVAL_BATCHES, LLAMA_EVAL_FRACTION (corpus tail reserved for eval
when eval is on; default 0.1).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding

    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
    from trainingjob_operator_tpu.parallel.sharding import (
        batch_spec,
        shard_pytree,
    )

    cfg = (llama.LlamaConfig.llama2_7b()
           if os.environ.get("LLAMA_CONFIG", "tiny") == "7b"
           else llama.LlamaConfig.tiny())
    tp = int(os.environ.get("LLAMA_TP", "1"))
    sp = int(os.environ.get("LLAMA_SP", "1"))
    pp = int(os.environ.get("LLAMA_PP", "1"))
    steps = int(os.environ.get("LLAMA_STEPS", "20"))
    global_batch = int(os.environ.get("LLAMA_BATCH", "8"))
    seq = int(os.environ.get("LLAMA_SEQ", "128"))
    lr = float(os.environ.get("LLAMA_LR", "3e-4"))
    ckpt_every = int(os.environ.get("LLAMA_CKPT_EVERY", "10"))
    accum = int(os.environ.get("LLAMA_ACCUM", "1"))

    mesh = mesh_from_rendezvous(rdv, model_parallel=tp, sequence_parallel=sp,
                                pipeline_parallel=pp)
    use_sp = sp > 1
    print(f"elastic width {rdv.elastic_replicas}, mesh "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"{llama.num_params(cfg)/1e6:.1f}M params, restart "
          f"{rdv.restart_count}", flush=True)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    n_data = int(np.prod([mesh.shape[a] for a in data_axes])) or 1
    # The rounded batch must tile BOTH the data shards and the accumulation
    # microbatches, at every elastic width; the helper sheds accumulation
    # first so the global batch never exceeds the request.
    global_batch, accum = train.round_global_batch(global_batch, n_data,
                                                   accum=accum)

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, llama.sharding_rules(pipeline=pp > 1), mesh)
    tx = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = tx.init(params)
    batch_sharding = NamedSharding(mesh, batch_spec(mesh, sequence_axis=use_sp))

    @jax.jit
    def step_fn(p, o, tokens):
        def loss(pp, tb):
            return llama.loss_fn(pp, {"tokens": tb}, cfg, mesh=mesh,
                                 sequence_parallel=use_sp)

        l, grads = train.accumulated_value_and_grad(loss, p, tokens, accum)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, l

    local_batch = global_batch // max(jax.process_count(), 1)
    data_path = os.environ.get("LLAMA_DATA", "")
    eval_every = int(os.environ.get("LLAMA_EVAL_EVERY", "0"))
    eval_batches = int(os.environ.get("LLAMA_EVAL_BATCHES", "2"))
    # Held-out split: the corpus TAIL is reserved for eval (disjoint
    # tokens, not just a different sampling seed -- otherwise eval loss
    # would track memorization).  Training uses the full stream when eval
    # is off, so enabling eval is the only thing that changes the split.
    eval_frac = float(os.environ.get("LLAMA_EVAL_FRACTION", "0.1"))
    train_region = (0.0, 1.0 - eval_frac) if eval_every > 0 else (0.0, 1.0)

    row0 = rdv.process_id * local_batch

    if data_path:
        from trainingjob_operator_tpu.data import TokenDataset

        ds = TokenDataset(data_path, seed=int(os.environ.get("LLAMA_SEED",
                                                             "17")),
                          region=train_region)
        if ds.vocab_size > cfg.vocab_size:
            # XLA's gather clamps out-of-range ids, so a mismatched corpus
            # would train on silently-corrupted tokens; refuse instead.
            raise ValueError(
                f"{data_path}: corpus vocab {ds.vocab_size} exceeds model "
                f"vocab {cfg.vocab_size}")
    else:
        ds = None

    def make_batch_at(dataset, key_base):
        """Stateless (source, step) -> this process's contiguous row block
        of the GLOBAL batch.  Both sources derive content independent of
        the process layout (file windows / a global PRNG key), so every
        elastic width sees the byte-identical global batch sequence --
        train and eval alike."""
        if dataset is not None:
            def fetch(i):
                local = dataset.batch(i, global_batch, seq,
                                      rows=slice(row0, row0 + local_batch))
                return train.globalize_batch(batch_sharding, local)
        else:
            def fetch(i):
                k = jax.random.fold_in(jax.random.PRNGKey(key_base), i)
                tokens = jax.random.randint(k, (global_batch, seq + 1), 0,
                                            cfg.vocab_size)
                return train.globalize_batch(
                    batch_sharding, tokens[row0:row0 + local_batch])
        return fetch

    batch_at = make_batch_at(ds, 17)

    eval_fn = None
    if eval_every > 0:
        if eval_batches < 1:
            raise ValueError(
                f"LLAMA_EVAL_BATCHES={eval_batches} with eval enabled: a "
                f"zero-batch eval would print a bogus 0.0 loss")
        # FIXED held-out set (batches j = 0..N-1 every time): comparable
        # across checkpoints and widths.  File-backed eval reads the
        # reserved corpus tail; synthetic fallback uses a held-out key.
        if ds is None:
            eval_ds = None
        else:
            eval_ds = TokenDataset(data_path, seed=ds.seed,
                                   region=(1.0 - eval_frac, 1.0))
            # Fail at startup, not at the first eval step N*eval_every
            # deep into paid TPU time: the tail must hold one window.
            eval_ds._offsets(0, 1, seq + 1)

        @jax.jit
        def eval_loss(p, tokens):
            return llama.loss_fn(p, {"tokens": tokens}, cfg, mesh=mesh,
                                 sequence_parallel=use_sp)

        eval_batch_at = make_batch_at(eval_ds, 0x5EED)

        def eval_fn(p):
            total = 0.0
            for j in range(eval_batches):
                total += float(eval_loss(p, eval_batch_at(j)))
            return total / eval_batches

    # Elastic resume: ONE checkpoint path shared across widths and ranks.
    # Sharded orbax save/restore -- each host writes/reads only its own
    # shards, and restore reshards onto the CURRENT (possibly narrower) mesh;
    # nothing is ever gathered to one host (7B + AdamW replicated is ~78 GB,
    # far beyond one v5e chip's 16 GB HBM).
    state = train.CheckpointState.restore_or_init(
        rdv, {"params": params, "opt_state": opt_state, "step": 0},
        subdir="llama", mesh=mesh)
    start_step = int(state.value["step"])
    params = state.value["params"]
    opt_state = state.value["opt_state"]
    if start_step > 0:
        print(f"resumed at step {start_step} (width "
              f"{rdv.elastic_replicas})", flush=True)

    params, opt_state, loss, t_start = train.run_elastic_loop(
        step_fn=step_fn, batch_at=batch_at, state=state, params=params,
        opt_state=opt_state, steps=steps, start_step=start_step,
        ckpt_every=ckpt_every, eval_fn=eval_fn, eval_every=eval_every)
    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    print(f"done: steps={done} tokens/s={done * global_batch * seq / dt:.0f} "
          f"width={rdv.elastic_replicas} "
          f"final_loss={float(loss) if loss is not None else -1:.4f} "
          f"restart_count={rdv.restart_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
