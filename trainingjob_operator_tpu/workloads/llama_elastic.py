"""Elastic Llama-2 pretrain -- BASELINE config 5 (preemptible v5e-32).

The flagship elastic workload: width comes from the operator
(TRAININGJOB_ELASTIC_REPLICAS / JAX process env), so after a spot preemption
the SAME program restarts at whatever width survived, rebuilds a narrower
``dp x fsdp x tp (x sp)`` mesh over the remaining chips, restores the shared
checkpoint, and keeps training -- the workload half of the operator's elastic
resize (controller/pod.py _elastic_resize); recovery budget <90 s
(BASELINE.md).

On top of the restart path sits the IN-PLACE fast path (docs/ELASTIC.md):
under ``restartScope: Resize`` the survivors never exit.  The controller
republishes a bumped rendezvous generation (workloads/rendezvous.py
GenerationWatcher), the step loop returns at the next step boundary, and
this module re-forms the mesh at the new width, redistributes the LIVE
parameter/optimizer shards device-to-device (parallel/reshard.py -- no
checkpoint round-trip), rescales the batch geometry, and continues from the
very next step.  The orbax restore only runs as a fallback when the
survivors cannot cover a lost shard.

Parallelism is the scaling-book layout: fsdp shards params/optimizer over the
data axis (per-layer all-gathers ride ICI), tp shards heads/ffn, sp enables
ring attention for long context (parallel/ringattention.py), dp carries
multislice DCN when present.  The global batch is kept constant across widths
(per-process share rescales), so the loss trajectory is width-independent.

Run: ``python -m trainingjob_operator_tpu.workloads.llama_elastic``.
Env: LLAMA_CONFIG=tiny|124m|7b, LLAMA_TP, LLAMA_SP, LLAMA_PP (pipeline
stages), LLAMA_PP_MICROBATCH (GPipe microbatches; default targets an ~11%
bubble, models/llama.py choose_microbatches),
LLAMA_ACCUM (gradient-accumulation microbatches), LLAMA_STEPS, LLAMA_BATCH
(global), LLAMA_SEQ, LLAMA_LR, LLAMA_CKPT_EVERY, LLAMA_DATA (path to a
``.tokens`` corpus, data/tokens.py; default trains on synthetic tokens),
LLAMA_SEED, LLAMA_EVAL_EVERY (held-out eval cadence in steps; 0 = off),
LLAMA_EVAL_BATCHES, LLAMA_EVAL_FRACTION (corpus tail reserved for eval
when eval is on; default 0.1), LLAMA_REMAT (rematerialization policy
none/full/attn/dots; default attn for 7b, none for tiny), LLAMA_CE_CHUNK
(chunked cross-entropy; 0 = monolithic logits), LLAMA_WINDOW
(sliding-window attention span; 0 = full causal).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> int:
    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.workloads import rendezvous, train

    t_main = time.time()
    rdv = rendezvous.initialize_jax_distributed()
    t_init = time.time()

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.obs.trace import tracer_from_env
    from trainingjob_operator_tpu.parallel import reshard
    from trainingjob_operator_tpu.parallel.mesh import (
        MeshSpec,
        make_mesh,
        mesh_from_rendezvous,
    )
    from trainingjob_operator_tpu.parallel.sharding import (
        batch_spec,
        shard_pytree,
    )

    configs = {"7b": llama.LlamaConfig.llama2_7b,
               "124m": llama.LlamaConfig.base_124m,
               "tiny": llama.LlamaConfig.tiny}
    cfg_name = os.environ.get("LLAMA_CONFIG", "tiny")
    if cfg_name not in configs:
        # A loud startup error, not a KeyError restart loop.
        print(f"LLAMA_CONFIG={cfg_name!r} unknown; expected one of "
              f"{sorted(configs)}", flush=True)
        return 1
    cfg = configs[cfg_name]()
    tp = int(os.environ.get("LLAMA_TP", "1"))
    sp = int(os.environ.get("LLAMA_SP", "1"))
    pp = int(os.environ.get("LLAMA_PP", "1"))
    steps = int(os.environ.get("LLAMA_STEPS", "20"))
    batch_req = int(os.environ.get("LLAMA_BATCH", "8"))
    seq = int(os.environ.get("LLAMA_SEQ", "128"))
    lr = float(os.environ.get("LLAMA_LR", "3e-4"))
    ckpt_every = int(os.environ.get("LLAMA_CKPT_EVERY", "10"))
    accum_req = int(os.environ.get("LLAMA_ACCUM", "1"))
    # Remat defaults to "attn" for the 7B config (chip-saturating batches
    # do not fit 16 GB HBM without it; "attn" skips the quadratic
    # attention recompute at ~one [B, T, D] + lse per layer) and off for
    # tiny test runs.  LLAMA_CE_CHUNK>0 additionally keeps the [B, T,
    # vocab] logits from materializing (models/llama.py loss_fn).
    remat = os.environ.get("LLAMA_REMAT", train.default_remat(cfg.n_layers))
    ce_chunk = int(os.environ.get("LLAMA_CE_CHUNK", "0"))
    window = int(os.environ.get("LLAMA_WINDOW", "0"))
    if window:
        import dataclasses

        cfg = dataclasses.replace(cfg, sliding_window=window)

    mesh = mesh_from_rendezvous(rdv, model_parallel=tp, sequence_parallel=sp,
                                pipeline_parallel=pp)
    use_sp = sp > 1
    rules = llama.sharding_rules(pipeline=pp > 1)
    tx = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)
    print(f"elastic width {rdv.elastic_replicas}, mesh "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"{llama.num_params(cfg)/1e6:.1f}M params, restart "
          f"{rdv.restart_count}", flush=True)

    def width_build(mesh):
        """Everything the mesh width determines: batch geometry, the jitted
        step/eval functions, and the batch sources.  Called once at startup
        and again after every in-place resize."""
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        n_data = int(np.prod([mesh.shape[a] for a in data_axes])) or 1
        # The rounded batch must tile BOTH the data shards and the
        # accumulation microbatches, at every elastic width; the helper
        # sheds accumulation first so the global batch never exceeds the
        # request.
        global_batch, accum = train.round_global_batch(batch_req, n_data,
                                                       accum=accum_req)
        # Tokens are [B, seq+1] (targets shifted by one): the odd length
        # cannot shard over sp, so the raw int tokens stay batch-sharded
        # only -- GSPMD reshards the [B, T, D] activations onto sp at the
        # ring attention's shard_map boundary, where the sequence split
        # actually matters.
        batch_sharding = NamedSharding(mesh, batch_spec(mesh))

        @jax.jit
        def step_fn(p, o, tokens):
            def loss(p_, tb):
                return llama.loss_fn(p_, {"tokens": tb}, cfg, mesh=mesh,
                                     sequence_parallel=use_sp, remat=remat,
                                     ce_chunk=ce_chunk)

            l, grads = train.accumulated_value_and_grad(loss, p, tokens,
                                                        accum)
            updates, o = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, l

        local_batch = global_batch // max(jax.process_count(), 1)
        batch_at, eval_batch_at, eval_every, eval_batches = (
            train.build_batch_sources(
                prefix="LLAMA", vocab_size=cfg.vocab_size,
                global_batch=global_batch, local_batch=local_batch,
                row0=rdv.process_id * local_batch, seq=seq,
                batch_sharding=batch_sharding, synthetic_key=17))

        eval_fn = None
        if eval_batch_at is not None:
            @jax.jit
            def eval_loss(p, tokens):
                # Same remat/ce_chunk as the train step: eval must fit
                # exactly where training fits (a monolithic-logits eval
                # would OOM at the first eval point of the config ce_chunk
                # exists for).
                return llama.loss_fn(p, {"tokens": tokens}, cfg, mesh=mesh,
                                     sequence_parallel=use_sp, remat=remat,
                                     ce_chunk=ce_chunk)

            eval_fn = train.mean_eval_fn(eval_loss, eval_batch_at,
                                         eval_batches)
        return (global_batch, accum, batch_sharding, step_fn, batch_at,
                eval_fn, eval_every)

    (global_batch, accum, batch_sharding, step_fn, batch_at,
     eval_fn, eval_every) = width_build(mesh)

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, rules, mesh)
    opt_state = tx.init(params)
    # Optimizer leaves created off-mesh (adamw's step counter) sit committed
    # on one device; replicate them on the mesh so the step signature is
    # IDENTICAL on cold start and warm resume (restore_or_init maps the same
    # leaves mesh-replicated) -- one persistent-cache entry, and the warm
    # AOT compile below hits it.
    replicated = NamedSharding(mesh, PartitionSpec())
    opt_state = jax.tree.map(
        lambda x: (jax.device_put(x, replicated)
                   if isinstance(x, jax.Array)
                   and not isinstance(x.sharding, NamedSharding) else x),
        opt_state)

    # Elastic resume: ONE checkpoint path shared across widths and ranks.
    # Sharded orbax save/restore -- each host writes/reads only its own
    # shards, and restore reshards onto the CURRENT (possibly narrower) mesh;
    # nothing is ever gathered to one host (7B + AdamW replicated is ~78 GB,
    # far beyond one v5e chip's 16 GB HBM).
    t_setup = time.time()

    def restore_fn():
        return train.CheckpointState.restore_or_init(
            rdv, {"params": params, "opt_state": opt_state, "step": 0},
            subdir="llama", mesh=mesh)

    def abstract_like(tree):
        return jax.tree.map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
                       if isinstance(x, jax.Array) else x), tree)

    # The warm compile needs only ABSTRACT args (shapes/dtypes/shardings),
    # so overlapped_restore runs it concurrently with the orbax read: warm
    # resume pays ~max(restore, compile) instead of their sum.  The compiled
    # step also skips the first-step re-trace (aot_or_jit below).
    p_abs, o_abs = abstract_like(params), abstract_like(opt_state)
    tok_abs = jax.ShapeDtypeStruct((global_batch, seq + 1), jax.numpy.int32,
                                   sharding=batch_sharding)

    # Beyond the HLO-level persistent cache, the resume fast path keeps an
    # EXECUTABLE snapshot next to it: the cold run serializes the compiled
    # step, and a warm resume deserializes it -- skipping trace + lower +
    # compile wholesale.  That is what actually empties the compile term on
    # a small host, where an overlapped trace still competes with the
    # restore for the same cores.  Keyed on everything that shapes the
    # jaxpr/topology; any mismatch is a miss and we recompile.
    def snap_path(mesh, global_batch, accum):
        """Snapshot file for a given topology + batch geometry ("" when the
        fast path or cache dir is off).  Shared by the startup resume and
        the post-resize re-AOT: a width this cache filer has compiled
        before -- an earlier resize, or a prior job on equivalent topology
        -- loads the serialized executable instead of recompiling."""
        if not train.resume_fastpath_enabled():
            return ""
        cache_dir = rendezvous.compile_cache_dir(rdv)
        if not cache_dir:
            return ""
        import dataclasses
        import hashlib

        # Field-wise, sorted config rendering: repr(cfg) happens to be
        # stable for a frozen dataclass, but a default object repr embeds
        # the process address -- render the fields so the cache key can
        # never pick one up (TJA025 digest-stability).
        cfg_desc = str(sorted(dataclasses.asdict(cfg).items()))
        desc = "|".join((jax.__version__, jax.default_backend(),
                         str(jax.device_count()),
                         str(tuple(mesh.devices.shape)),
                         str(mesh.axis_names), cfg_desc, remat,
                         str((global_batch, seq, accum, ce_chunk, lr))))
        key = hashlib.sha256(desc.encode()).hexdigest()[:16]
        os.makedirs(cache_dir, exist_ok=True)
        return os.path.join(cache_dir, f"exec-{key}.jexec")

    exec_snap = snap_path(mesh, global_batch, accum)

    def compile_fn():
        loaded = train.load_executable_snapshot(exec_snap)
        if loaded is not None:
            return loaded
        compiled = step_fn.lower(p_abs, o_abs, tok_abs).compile()
        train.store_executable_snapshot(exec_snap, compiled)
        return compiled

    state, compiled, rtimes = train.overlapped_restore(restore_fn, compile_fn)
    start_step = int(state.value["step"])
    params = state.value["params"]
    opt_state = state.value["opt_state"]
    if start_step > 0:
        print(f"resumed at step {start_step} (width "
              f"{rdv.elastic_replicas})", flush=True)
    # Recovery-phase breakdown (consumed by bench.py's recovery legs and
    # tools/recovery_smoke.py): init = JAX/distributed bring-up, setup =
    # model init + sharding, restore = orbax read + reshard, compile = warm
    # AOT compile (compile-cache-sensitive), resume_phases_wall = the
    # restore||compile region's wall clock (~max of the two when
    # resume_overlap=1, ~their sum when TRAININGJOB_RESUME_OVERLAP=0).  The
    # remaining component -- first step -- is printed by run_elastic_loop.
    print(f"recovery_timing init_s={t_init - t_main:.2f} "
          f"setup_s={t_setup - t_init:.2f} "
          f"restore_s={rtimes['restore_s']:.2f} "
          f"compile_s={rtimes['compile_s']:.2f} "
          f"resume_phases_wall_s={rtimes['wall_s']:.2f} "
          f"resume_overlap={int(rtimes['overlap'])}", flush=True)

    # Telemetry accounting: tokens per optimizer step, and the standard
    # dense-transformer estimate of 6 * params * tokens FLOPs per step
    # (fwd 2x + bwd 4x) -- feeds the controller-side MFU gauge.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    # In-place resize machinery: the generation watcher (armed only when the
    # operator injected the resize channel), the survivor world as a list of
    # replica indices, and the device share each replica contributes to the
    # sim's flat device pool.
    watcher = (rendezvous.GenerationWatcher(rdv) if rdv.resize_dir else None)
    tracer, trace_parent = tracer_from_env()
    world = list(range(max(rdv.elastic_replicas, 1)))
    per_replica_dev = max(len(jax.devices()) // max(len(world), 1), 1)
    inner = tp * sp * pp
    loop_step = train.aot_or_jit(compiled, step_fn)

    def persist_and_exit(step: int) -> int:
        state.save({"params": params, "opt_state": opt_state, "step": step},
                   wait=True)
        state.finalize()
        return train.GracefulShutdown.EXIT_CODE

    while True:
        tokens_per_step = global_batch * seq
        params, opt_state, loss, t_start = train.run_elastic_loop(
            step_fn=loop_step, batch_at=batch_at, state=state, params=params,
            opt_state=opt_state, steps=steps, start_step=start_step,
            ckpt_every=ckpt_every, eval_fn=eval_fn, eval_every=eval_every,
            units_per_step=tokens_per_step,
            flops_per_step=6.0 * n_params * tokens_per_step,
            resize_watch=watcher, tracer=tracer, trace_parent=trace_parent)
        if watcher is None or watcher.pending is None:
            break
        doc = watcher.pending
        watcher.pending = None
        generation = int(doc.get("generation", 0))
        t_r0 = time.time()
        was_multi = rdv.num_processes > 1
        ladder_phase = "shutdown"
        try:
            if (jax.process_count() > 1
                    and os.environ.get(constants.RESIZE_LIVE_ENV, "1")
                    == "0"):
                # The bench A/B baseline arm: measure the old
                # checkpoint+restart path against the live ladder.
                raise rendezvous.RebootstrapError(
                    "shutdown", f"{constants.RESIZE_LIVE_ENV}=0 forces the "
                                "checkpoint rung")
            # Live rung: tear down only the distributed client, barrier on
            # the bumped-generation coordinator the controller published,
            # re-init at the new rank (docs/ELASTIC.md).  Single-process
            # runtimes pass through (fault injection still fires).  The
            # process -- and with it the executable-snapshot/compile
            # caches -- stays up either way.
            with tracer.span("resize.rendezvous", parent=trace_parent,
                             generation=generation,
                             processes=rdv.num_processes):
                rdv, rdv_times = rendezvous.rebootstrap_jax_distributed(
                    rdv, doc, old_world=world)
            t_rdv = time.time()
            new_world = [int(r) for r in doc["world"]]
            lost_ranks = [i for i, r in enumerate(world)
                          if r not in set(new_world)]
            n_dev = int(doc.get("devices")
                        or per_replica_dev * len(new_world))
            if n_dev <= 0 or n_dev % inner != 0:
                raise rendezvous.RebootstrapError(
                    "reshard", f"{n_dev} devices not divisible by "
                               f"tp*sp*pp={inner}")
            ladder_phase = "reshard"
            rendezvous.check_fault("reshard", generation)
            # Report the rung as soon as the rendezvous lands: the record's
            # timestamp is where the incident bundle splits rendezvous from
            # reshard, and a later degrade re-reports with the rung fallen
            # to (latest record wins).
            train.push_rendezvous_record(
                sum(rdv_times.values()), rendezvous.RUNG_LIVE,
                phase_ms=rdv_times)
            # Host-level shard-exchange plan: the traffic estimate for the
            # log line, and the fast-path gate -- a lost rank whose shards
            # have no surviving copy forces the checkpoint fallback.  In
            # the single-process sim every leaf is fully addressable, so
            # the live arrays themselves cover everything the plan marks
            # missing.
            shapes = {jax.tree_util.keystr(kp): tuple(x.shape)
                      for kp, x in jax.tree_util.tree_leaves_with_path(
                          params)
                      if hasattr(x, "shape") and x.shape}
            agg = reshard.plan_pytree_exchange(
                shapes, len(world), len(new_world), lost=lost_ranks)
            addressable = all(getattr(x, "is_fully_addressable", True)
                              for x in jax.tree_util.tree_leaves(params)
                              if isinstance(x, jax.Array))
            with tracer.span("resize.requod", parent=trace_parent,
                             generation=generation,
                             world=len(new_world), devices=n_dev):
                data = n_dev // inner
                dp = max(rdv.num_slices, 1)
                if data % dp != 0:
                    dp = 1
                new_mesh = make_mesh(
                    MeshSpec.of(dp=dp, pp=pp, fsdp=data // dp, tp=tp,
                                sp=sp),
                    devices=jax.devices()[:n_dev])
            t_r1 = time.time()
            fellback = 0
            # A true multi-process rebootstrap cleared the old backend, so
            # the live arrays are gone with it: those survivors always
            # re-materialize from the last checkpoint (the orbax rung) --
            # still no process restart, and the compile caches stay warm.
            if not was_multi and (agg["covered"] or addressable):
                with tracer.span("resize.reshard", parent=trace_parent,
                                 moved_bytes=agg["moved_bytes"]):
                    params = reshard.redistribute(params, new_mesh)
                    opt_state = reshard.redistribute(opt_state, new_mesh)
                    # analyzer: allow[host-sync-in-hot-loop] reshard-commit
                    # drain: the exchange must land before the resized loop
                    # restarts; runs once per resize, not per step.
                    jax.block_until_ready((params, opt_state))
                start_step = watcher.resume_step
            else:
                # Survivors cannot cover a lost shard: orbax fallback --
                # restore the last checkpoint onto the new mesh (still no
                # process restart, but the downtime win shrinks to restore
                # time).
                fellback = 1
                with tracer.span("resize.reshard", parent=trace_parent,
                                 fallback=True):
                    # The loop skipped its exit finalize on the resize
                    # path; this rung re-reads the checkpoint dir, so
                    # commit any in-flight save first (restoring mid-write
                    # would hand back the previous committed step under
                    # orbax's feet).
                    state.finalize()
                    params = shard_pytree(
                        llama.init_params(cfg, jax.random.PRNGKey(0)),
                        rules, new_mesh)
                    opt_state = tx.init(params)
                    rep = NamedSharding(new_mesh, PartitionSpec())
                    opt_state = jax.tree.map(
                        lambda x: (jax.device_put(x, rep)
                                   if isinstance(x, jax.Array)
                                   and not isinstance(x.sharding,
                                                      NamedSharding)
                                   else x),
                        opt_state)
                    state = train.CheckpointState.restore_or_init(
                        rdv, {"params": params, "opt_state": opt_state,
                              "step": watcher.resume_step},
                        subdir="llama", mesh=new_mesh)
                    params = state.value["params"]
                    opt_state = state.value["opt_state"]
                    start_step = int(state.value["step"])
            t_r2 = time.time()
            mesh = new_mesh
            world = new_world
            (global_batch, accum, batch_sharding, step_fn, batch_at,
             eval_fn, eval_every) = width_build(mesh)
            # Re-AOT at the new width through the same executable-snapshot
            # machinery as the startup resume: a topology this cache has
            # seen (an earlier resize cycle, or a prior job on the shared
            # filer) deserializes the compiled step and skips
            # trace+lower+compile; a first-seen width pays the compile once
            # and seeds the snapshot for the next resize.
            with tracer.span("resize.compile", parent=trace_parent,
                             devices=n_dev):
                snap = snap_path(mesh, global_batch, accum)
                loaded = train.load_executable_snapshot(snap)
                if loaded is None:
                    tok_abs2 = jax.ShapeDtypeStruct(
                        (global_batch, seq + 1), jax.numpy.int32,
                        sharding=batch_sharding)
                    loaded = step_fn.lower(abstract_like(params),
                                           abstract_like(opt_state),
                                           tok_abs2).compile()
                    train.store_executable_snapshot(snap, loaded)
                loop_step = train.aot_or_jit(loaded, step_fn)
            t_r3 = time.time()
        # analyzer: allow[broad-except]: the ladder guard.  Any failure in
        # the guarded region -- injected, a jax/distributed error, or a
        # plain bug mid-reshard -- must degrade one rung, never wedge a
        # survivor holding devices.
        except Exception as exc:
            # The ladder degrades exactly one rung per failure:
            # live -> checkpoint (park state, operator restarts at the new
            # width) -> restart-all (exit without a fresh checkpoint; the
            # operator's restart recovers from the last committed step).
            phase = getattr(exc, "phase", ladder_phase)
            injected = bool(getattr(exc, "injected", False))
            print(f"resize_rung generation={generation} "
                  f"rung={rendezvous.RUNG_CHECKPOINT} phase={phase} "
                  f"injected={int(injected)}", flush=True)
            print(f"resize: live rebootstrap degraded at phase "
                  f"{phase} ({type(exc).__name__}: {exc}); checkpointing "
                  "and exiting 143 for operator restart", flush=True)
            train.push_rendezvous_record(
                (time.time() - t_r0) * 1e3, rendezvous.RUNG_CHECKPOINT,
                reason=f"{phase}: {exc}")
            try:
                rendezvous.check_fault("persist", generation)
                return persist_and_exit(watcher.resume_step)
            # analyzer: allow[broad-except]: the checkpoint rung must
            # degrade to restart-all on ANY persist failure (orbax I/O,
            # injected fault, a collective on the torn-down client) --
            # wedging a survivor here is the exact failure mode the
            # ladder exists to prevent.
            except Exception as exc2:
                print(f"resize_rung generation={generation} "
                      f"rung={rendezvous.RUNG_RESTART_ALL} phase=persist "
                      f"injected="
                      f"{int(getattr(exc2, 'injected', False))}",
                      flush=True)
                print(f"resize: checkpoint rung failed "
                      f"({type(exc2).__name__}: {exc2}); exiting 143 "
                      "without a fresh checkpoint -- restart-all recovers "
                      "from the last committed step", flush=True)
                train.push_rendezvous_record(
                    (time.time() - t_r0) * 1e3,
                    rendezvous.RUNG_RESTART_ALL,
                    reason=f"persist: {exc2}")
                return train.GracefulShutdown.EXIT_CODE
        watcher.reenter(generation)
        print(f"resize_rung generation={generation} "
              f"rung={rendezvous.RUNG_LIVE} phase=-", flush=True)
        # The resize counterpart of recovery_timing, parsed by
        # bench_elastic_resize and tools/elastic_smoke.py.
        print(f"resize_timing generation={generation} "
              f"width={len(new_world)} "
              f"rendezvous_s={t_rdv - t_r0:.2f} "
              f"requod_s={t_r1 - t_rdv:.2f} "
              f"reshard_s={t_r2 - t_r1:.2f} "
              f"moved_mb={agg['moved_bytes'] / 2**20:.1f} "
              f"fallback={fellback} compile_s={t_r3 - t_r2:.2f}",
              flush=True)
        print(f"resized in place: mesh "
              f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, "
              f"resuming at step {start_step}", flush=True)

    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    print(f"done: steps={done} tokens/s={done * global_batch * seq / dt:.0f} "
          f"width={len(world)} "
          f"final_loss={float(loss) if loss is not None else -1:.4f} "
          f"restart_count={rdv.restart_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
