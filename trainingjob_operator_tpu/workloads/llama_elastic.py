"""Elastic Llama-2 pretrain -- BASELINE config 5 (preemptible v5e-32).

The flagship elastic workload: width comes from the operator
(TRAININGJOB_ELASTIC_REPLICAS / JAX process env), so after a spot preemption
the SAME program restarts at whatever width survived, rebuilds a narrower
``dp x fsdp x tp (x sp)`` mesh over the remaining chips, restores the shared
checkpoint, and keeps training -- the workload half of the operator's elastic
resize (controller/pod.py _elastic_resize); recovery budget <90 s
(BASELINE.md).

Parallelism is the scaling-book layout: fsdp shards params/optimizer over the
data axis (per-layer all-gathers ride ICI), tp shards heads/ffn, sp enables
ring attention for long context (parallel/ringattention.py), dp carries
multislice DCN when present.  The global batch is kept constant across widths
(per-process share rescales), so the loss trajectory is width-independent.

Run: ``python -m trainingjob_operator_tpu.workloads.llama_elastic``.
Env: LLAMA_CONFIG=tiny|124m|7b, LLAMA_TP, LLAMA_SP, LLAMA_PP (pipeline
stages), LLAMA_PP_MICROBATCH (GPipe microbatches; default targets an ~11%
bubble, models/llama.py choose_microbatches),
LLAMA_ACCUM (gradient-accumulation microbatches), LLAMA_STEPS, LLAMA_BATCH
(global), LLAMA_SEQ, LLAMA_LR, LLAMA_CKPT_EVERY, LLAMA_DATA (path to a
``.tokens`` corpus, data/tokens.py; default trains on synthetic tokens),
LLAMA_SEED, LLAMA_EVAL_EVERY (held-out eval cadence in steps; 0 = off),
LLAMA_EVAL_BATCHES, LLAMA_EVAL_FRACTION (corpus tail reserved for eval
when eval is on; default 0.1), LLAMA_REMAT (rematerialization policy
none/full/attn/dots; default attn for 7b, none for tiny), LLAMA_CE_CHUNK
(chunked cross-entropy; 0 = monolithic logits), LLAMA_WINDOW
(sliding-window attention span; 0 = full causal).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    t_main = time.time()
    rdv = rendezvous.initialize_jax_distributed()
    t_init = time.time()

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding

    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
    from trainingjob_operator_tpu.parallel.sharding import (
        batch_spec,
        shard_pytree,
    )

    configs = {"7b": llama.LlamaConfig.llama2_7b,
               "124m": llama.LlamaConfig.base_124m,
               "tiny": llama.LlamaConfig.tiny}
    cfg_name = os.environ.get("LLAMA_CONFIG", "tiny")
    if cfg_name not in configs:
        # A loud startup error, not a KeyError restart loop.
        print(f"LLAMA_CONFIG={cfg_name!r} unknown; expected one of "
              f"{sorted(configs)}", flush=True)
        return 1
    cfg = configs[cfg_name]()
    tp = int(os.environ.get("LLAMA_TP", "1"))
    sp = int(os.environ.get("LLAMA_SP", "1"))
    pp = int(os.environ.get("LLAMA_PP", "1"))
    steps = int(os.environ.get("LLAMA_STEPS", "20"))
    global_batch = int(os.environ.get("LLAMA_BATCH", "8"))
    seq = int(os.environ.get("LLAMA_SEQ", "128"))
    lr = float(os.environ.get("LLAMA_LR", "3e-4"))
    ckpt_every = int(os.environ.get("LLAMA_CKPT_EVERY", "10"))
    accum = int(os.environ.get("LLAMA_ACCUM", "1"))
    # Remat defaults to "attn" for the 7B config (chip-saturating batches
    # do not fit 16 GB HBM without it; "attn" skips the quadratic
    # attention recompute at ~one [B, T, D] + lse per layer) and off for
    # tiny test runs.  LLAMA_CE_CHUNK>0 additionally keeps the [B, T,
    # vocab] logits from materializing (models/llama.py loss_fn).
    remat = os.environ.get("LLAMA_REMAT", train.default_remat(cfg.n_layers))
    ce_chunk = int(os.environ.get("LLAMA_CE_CHUNK", "0"))
    window = int(os.environ.get("LLAMA_WINDOW", "0"))
    if window:
        import dataclasses

        cfg = dataclasses.replace(cfg, sliding_window=window)

    mesh = mesh_from_rendezvous(rdv, model_parallel=tp, sequence_parallel=sp,
                                pipeline_parallel=pp)
    use_sp = sp > 1
    print(f"elastic width {rdv.elastic_replicas}, mesh "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"{llama.num_params(cfg)/1e6:.1f}M params, restart "
          f"{rdv.restart_count}", flush=True)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    n_data = int(np.prod([mesh.shape[a] for a in data_axes])) or 1
    # The rounded batch must tile BOTH the data shards and the accumulation
    # microbatches, at every elastic width; the helper sheds accumulation
    # first so the global batch never exceeds the request.
    global_batch, accum = train.round_global_batch(global_batch, n_data,
                                                   accum=accum)

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, llama.sharding_rules(pipeline=pp > 1), mesh)
    tx = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = tx.init(params)
    # Optimizer leaves created off-mesh (adamw's step counter) sit committed
    # on one device; replicate them on the mesh so the step signature is
    # IDENTICAL on cold start and warm resume (restore_or_init maps the same
    # leaves mesh-replicated) -- one persistent-cache entry, and the warm
    # AOT compile below hits it.
    from jax.sharding import PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())
    opt_state = jax.tree.map(
        lambda x: (jax.device_put(x, replicated)
                   if isinstance(x, jax.Array)
                   and not isinstance(x.sharding, NamedSharding) else x),
        opt_state)
    # Tokens are [B, seq+1] (targets shifted by one): the odd length cannot
    # shard over sp, so the raw int tokens stay batch-sharded only -- GSPMD
    # reshards the [B, T, D] activations onto sp at the ring attention's
    # shard_map boundary, where the sequence split actually matters.
    batch_sharding = NamedSharding(mesh, batch_spec(mesh))

    @jax.jit
    def step_fn(p, o, tokens):
        def loss(pp, tb):
            return llama.loss_fn(pp, {"tokens": tb}, cfg, mesh=mesh,
                                 sequence_parallel=use_sp, remat=remat,
                                 ce_chunk=ce_chunk)

        l, grads = train.accumulated_value_and_grad(loss, p, tokens, accum)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, l

    local_batch = global_batch // max(jax.process_count(), 1)
    batch_at, eval_batch_at, eval_every, eval_batches = (
        train.build_batch_sources(
            prefix="LLAMA", vocab_size=cfg.vocab_size,
            global_batch=global_batch, local_batch=local_batch,
            row0=rdv.process_id * local_batch, seq=seq,
            batch_sharding=batch_sharding, synthetic_key=17))

    eval_fn = None
    if eval_batch_at is not None:
        @jax.jit
        def eval_loss(p, tokens):
            # Same remat/ce_chunk as the train step: eval must fit exactly
            # where training fits (a monolithic-logits eval would OOM at
            # the first eval point of the config ce_chunk exists for).
            return llama.loss_fn(p, {"tokens": tokens}, cfg, mesh=mesh,
                                 sequence_parallel=use_sp, remat=remat,
                                 ce_chunk=ce_chunk)

        eval_fn = train.mean_eval_fn(eval_loss, eval_batch_at, eval_batches)

    # Elastic resume: ONE checkpoint path shared across widths and ranks.
    # Sharded orbax save/restore -- each host writes/reads only its own
    # shards, and restore reshards onto the CURRENT (possibly narrower) mesh;
    # nothing is ever gathered to one host (7B + AdamW replicated is ~78 GB,
    # far beyond one v5e chip's 16 GB HBM).
    t_setup = time.time()

    def restore_fn():
        return train.CheckpointState.restore_or_init(
            rdv, {"params": params, "opt_state": opt_state, "step": 0},
            subdir="llama", mesh=mesh)

    def abstract_like(tree):
        return jax.tree.map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
                       if isinstance(x, jax.Array) else x), tree)

    # The warm compile needs only ABSTRACT args (shapes/dtypes/shardings),
    # so overlapped_restore runs it concurrently with the orbax read: warm
    # resume pays ~max(restore, compile) instead of their sum.  The compiled
    # step also skips the first-step re-trace (aot_or_jit below).
    p_abs, o_abs = abstract_like(params), abstract_like(opt_state)
    tok_abs = jax.ShapeDtypeStruct((global_batch, seq + 1), jax.numpy.int32,
                                   sharding=batch_sharding)

    # Beyond the HLO-level persistent cache, the resume fast path keeps an
    # EXECUTABLE snapshot next to it: the cold run serializes the compiled
    # step, and a warm resume deserializes it -- skipping trace + lower +
    # compile wholesale.  That is what actually empties the compile term on
    # a small host, where an overlapped trace still competes with the
    # restore for the same cores.  Keyed on everything that shapes the
    # jaxpr/topology; any mismatch is a miss and we recompile.
    exec_snap = ""
    if train.resume_fastpath_enabled():
        cache_dir = rendezvous.compile_cache_dir(rdv)
        if cache_dir:
            import hashlib

            desc = "|".join((jax.__version__, jax.default_backend(),
                             str(jax.device_count()),
                             str(tuple(mesh.devices.shape)),
                             str(mesh.axis_names), repr(cfg), remat,
                             str((global_batch, seq, accum, ce_chunk, lr))))
            key = hashlib.sha256(desc.encode()).hexdigest()[:16]
            os.makedirs(cache_dir, exist_ok=True)
            exec_snap = os.path.join(cache_dir, f"exec-{key}.jexec")

    def compile_fn():
        loaded = train.load_executable_snapshot(exec_snap)
        if loaded is not None:
            return loaded
        compiled = step_fn.lower(p_abs, o_abs, tok_abs).compile()
        train.store_executable_snapshot(exec_snap, compiled)
        return compiled

    state, compiled, rtimes = train.overlapped_restore(restore_fn, compile_fn)
    start_step = int(state.value["step"])
    params = state.value["params"]
    opt_state = state.value["opt_state"]
    if start_step > 0:
        print(f"resumed at step {start_step} (width "
              f"{rdv.elastic_replicas})", flush=True)
    # Recovery-phase breakdown (consumed by bench.py's recovery legs and
    # tools/recovery_smoke.py): init = JAX/distributed bring-up, setup =
    # model init + sharding, restore = orbax read + reshard, compile = warm
    # AOT compile (compile-cache-sensitive), resume_phases_wall = the
    # restore||compile region's wall clock (~max of the two when
    # resume_overlap=1, ~their sum when TRAININGJOB_RESUME_OVERLAP=0).  The
    # remaining component -- first step -- is printed by run_elastic_loop.
    print(f"recovery_timing init_s={t_init - t_main:.2f} "
          f"setup_s={t_setup - t_init:.2f} "
          f"restore_s={rtimes['restore_s']:.2f} "
          f"compile_s={rtimes['compile_s']:.2f} "
          f"resume_phases_wall_s={rtimes['wall_s']:.2f} "
          f"resume_overlap={int(rtimes['overlap'])}", flush=True)

    # Telemetry accounting: tokens per optimizer step, and the standard
    # dense-transformer estimate of 6 * params * tokens FLOPs per step
    # (fwd 2x + bwd 4x) -- feeds the controller-side MFU gauge.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    tokens_per_step = global_batch * seq
    params, opt_state, loss, t_start = train.run_elastic_loop(
        step_fn=train.aot_or_jit(compiled, step_fn),
        batch_at=batch_at, state=state, params=params,
        opt_state=opt_state, steps=steps, start_step=start_step,
        ckpt_every=ckpt_every, eval_fn=eval_fn, eval_every=eval_every,
        units_per_step=tokens_per_step,
        flops_per_step=6.0 * n_params * tokens_per_step)
    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    print(f"done: steps={done} tokens/s={done * global_batch * seq / dt:.0f} "
          f"width={rdv.elastic_replicas} "
          f"final_loss={float(loss) if loss is not None else -1:.4f} "
          f"restart_count={rdv.restart_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
