"""Workload-side rendezvous: read the operator's injected env and assemble the
distributed topology.

This is the consumer of the env contract from controller/pod.py set_env
(reference: pod.go:548-652 + the TPU mapping of SURVEY.md §3.5): identity vars
(TRAININGJOB_*), per-group host lists ({RT}_INSTANCES/_PORTS/_HOSTS), and the
JAX bootstrap set (coordinator address, process count/id, TPU topology).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants

#: Rebootstrap ladder phases, in execution order (docs/ELASTIC.md "Live
#: re-rendezvous").  ``shutdown``/``barrier``/``reinit`` live here;
#: ``reshard`` and ``persist`` are guarded at the workload's ladder driver
#: (llama_elastic) but share the same fault-injection knob.
REBOOTSTRAP_PHASES = ("shutdown", "barrier", "reinit", "reshard", "persist")

#: Fallback ladder rungs, best first.  ``live``: the survivors re-formed
#: the distributed world in place.  ``checkpoint``: a phase failed, the
#: survivors committed a checkpoint at the interrupted step and exited 143
#: for the operator to restart at the published width.  ``restart_all``:
#: even the checkpoint failed -- exit anyway and let recovery replay from
#: the last committed step.
RUNG_LIVE = "live"
RUNG_CHECKPOINT = "checkpoint"
RUNG_RESTART_ALL = "restart_all"
REBOOTSTRAP_RUNGS = (RUNG_LIVE, RUNG_CHECKPOINT, RUNG_RESTART_ALL)


@dataclass
class Rendezvous:
    """Everything a worker needs to find its peers and its place."""

    job_name: str = ""
    namespace: str = ""
    replica_name: str = ""
    replica_index: int = 0
    restart_count: int = 0
    num_processes: int = 1
    process_id: int = 0
    coordinator_address: str = ""
    service: str = ""
    checkpoint_dir: str = ""
    elastic_replicas: int = 1
    tpu_accelerator: str = ""
    tpu_topology: str = ""
    slice_id: int = 0
    num_slices: int = 1
    is_reservation: bool = False
    resize_dir: str = ""
    rendezvous_generation: int = 0
    group_instances: Dict[str, List[str]] = field(default_factory=dict)
    group_hosts: Dict[str, List[str]] = field(default_factory=dict)

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    def hold_reservation_if_needed(self) -> None:
        """Re-expand reservation pods (capacity canaries) idle here instead of
        joining a rendezvous they are not part of; the operator restarts them
        with a real rank once the resize commits.  Call first in every
        workload main.

        The hold is bounded: past the injected TTL the canary exits 143
        (-> pod Failed -> the controller's probe-failed path cancels the
        probe on resync), so a probe orphaned by a dead controller frees its
        TPU host without any external GC (VERDICT r3 Weak #7)."""
        if not self.is_reservation:
            return
        import sys as _sys
        import time as _time

        ttl = float(os.environ.get(constants.RESERVATION_TTL_ENV, "0") or 0)
        deadline = _time.time() + ttl if ttl > 0 else None
        while deadline is None or _time.time() < deadline:
            _time.sleep(min(5.0, max(deadline - _time.time(), 0.01))
                        if deadline is not None else 3600)
        _sys.exit(143)

    def hosts(self, group: str) -> List[str]:
        """host:port list of a replica group (after any localproc rewrite)."""
        return self.group_hosts.get(group.upper(), [])

    @property
    def generation_path(self) -> str:
        """Where the controller republishes the rendezvous generation
        (controller/pod.py publish_generation); "" when resize is not wired
        for this job."""
        return (os.path.join(self.resize_dir, "generation.json")
                if self.resize_dir else "")


def read_generation(path: str) -> Optional[Dict[str, Any]]:
    """Parse a published generation doc; None on absence or garble.

    The writer is atomic (tmp + os.replace) so a partial read means an
    out-of-band scribble, not a torn write -- either way the contract is the
    same: ignore anything that is not a well-formed doc and keep training at
    the current generation."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if (isinstance(doc, dict)
                and isinstance(doc.get("generation"), int)
                and isinstance(doc.get("world"), list)
                and doc["generation"] > 0):
            return doc
    except (OSError, ValueError):
        pass
    return None


class GenerationWatcher:
    """Cheap per-step poll of the controller's generation channel.

    Survivors call ``poll()`` at every step boundary; it is rate-limited to
    ``TRAININGJOB_RESIZE_POLL_S`` (default 0.5 s) and stat-gated (a read only
    happens when the file's mtime moved), so the steady-state cost is one
    ``os.stat`` every poll interval.  A doc is surfaced once, and only when
    its generation is beyond both the process's birth epoch (the injected
    ``TRAININGJOB_RENDEZVOUS_GENERATION``) and the last surfaced doc --
    a freshly (re)started pod never reacts to the generation it was born
    into.
    """

    def __init__(self, rdv: Optional[Rendezvous] = None,
                 path: Optional[str] = None,
                 birth: Optional[int] = None,
                 interval: Optional[float] = None) -> None:
        if rdv is None and (path is None or birth is None):
            rdv = from_env()
        self.path = path if path is not None else rdv.generation_path
        self.seen = birth if birth is not None else rdv.rendezvous_generation
        if interval is None:
            try:
                interval = float(
                    os.environ.get(constants.RESIZE_POLL_ENV, "") or 0.5)
            except ValueError:
                interval = 0.5
        self.interval = max(interval, 0.0)
        self._next_check = 0.0
        self._mtime: Optional[float] = None
        #: Set by train.run_elastic_loop when a poll fires mid-run: the doc
        #: that interrupted the step loop, and the step to resume at after
        #: the in-place reshard.
        self.pending: Optional[Dict[str, Any]] = None
        self.resume_step: int = 0

    def poll(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """The freshest unseen generation doc, or None."""
        if not self.path:
            return None
        now = time.monotonic() if now is None else now
        if now < self._next_check:
            return None
        self._next_check = now + self.interval
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return None
        if mtime == self._mtime:
            return None
        self._mtime = mtime
        doc = read_generation(self.path)
        if doc is not None and doc["generation"] > self.seen:
            self.seen = doc["generation"]
            return doc
        return None

    def reenter(self, generation: int) -> None:
        """Mark a completed rebootstrap at ``generation``: the watcher keeps
        polling for LATER bumps in the same process lifetime, but docs at or
        below this epoch are now stale -- a slow NFS replay of the doc that
        triggered the rendezvous must not trigger it twice."""
        self.seen = max(self.seen, generation)
        self.pending = None


def from_env(env: Optional[Dict[str, str]] = None) -> Rendezvous:
    e = dict(os.environ if env is None else env)
    rdv = Rendezvous(
        job_name=e.get(constants.JOB_NAME_ENV, ""),
        namespace=e.get(constants.JOB_NAMESPACE_ENV, "default"),
        replica_name=e.get(constants.REPLICA_NAME_ENV, ""),
        replica_index=int(e.get(constants.REPLICA_INDEX_ENV, "0") or 0),
        restart_count=int(e.get(constants.REPLICA_RESTART_COUNT_ENV, "0") or 0),
        num_processes=int(e.get(constants.NUM_PROCESSES_ENV, "1") or 1),
        process_id=int(e.get(constants.PROCESS_ID_ENV, "0") or 0),
        coordinator_address=e.get(constants.COORDINATOR_ADDRESS_ENV, ""),
        service=e.get(constants.SERVICE_ENV, ""),
        checkpoint_dir=e.get(constants.CHECKPOINT_DIR_ENV, ""),
        elastic_replicas=int(e.get(constants.ELASTIC_REPLICAS_ENV, "1") or 1),
        tpu_accelerator=e.get(constants.TPU_ACCELERATOR_ENV, ""),
        tpu_topology=e.get(constants.TPU_TOPOLOGY_ENV, ""),
        slice_id=int(e.get(constants.SLICE_ID_ENV, "0") or 0),
        num_slices=int(e.get(constants.NUM_SLICES_ENV, "1") or 1),
        is_reservation=e.get(constants.RESERVATION_ENV, "") == "1",
        resize_dir=e.get(constants.RESIZE_DIR_ENV, ""),
        rendezvous_generation=int(
            e.get(constants.RENDEZVOUS_GENERATION_ENV, "0") or 0),
    )
    for key, value in e.items():
        if key.endswith("_INSTANCES") and not key.endswith("_NUM"):
            rdv.group_instances[key[:-len("_INSTANCES")]] = (
                value.split(",") if value else [])
        elif key.endswith("_HOSTS") and not key.endswith("_NUM"):
            rdv.group_hosts[key[:-len("_HOSTS")]] = (
                value.split(",") if value else [])
    return rdv


def initialize_jax_distributed(rdv: Optional[Rendezvous] = None) -> Rendezvous:
    """Call jax.distributed.initialize from the injected env when the job is
    multi-process; no-op for single-process jobs.

    This is the TPU-native replacement for the reference's "framework inside
    the pod self-assembles from env" contract (SURVEY.md §2.7): intra-slice
    collectives ride ICI compiled by XLA; this call only wires the control
    plane (coordinator + process ids).
    """
    rdv = rdv or from_env()
    rdv.hold_reservation_if_needed()  # capacity canaries never join
    apply_platform_override()
    enable_compile_cache(rdv)
    if rdv.num_processes > 1 and rdv.coordinator_address:
        import jax

        jax.distributed.initialize(
            coordinator_address=rdv.coordinator_address,
            num_processes=rdv.num_processes,
            process_id=rdv.process_id,
        )
    return rdv


# -- live re-rendezvous: coordinator rebootstrap (docs/ELASTIC.md) -----------

class RebootstrapError(RuntimeError):
    """A guarded rebootstrap phase failed.  Carries the phase name for
    incident attribution and whether the failure was injected
    (``TRAININGJOB_RESIZE_FAULT``) -- the ladder driver degrades one rung
    either way; tests tell the two apart."""

    def __init__(self, phase: str, message: str,
                 injected: bool = False) -> None:
        super().__init__(message)
        self.phase = phase
        self.injected = injected


def resize_faults(env: Optional[Dict[str, str]] = None
                  ) -> Dict[str, Optional[int]]:
    """Parse ``TRAININGJOB_RESIZE_FAULT`` into {phase: generation-or-None}.

    The knob is a comma-separated list of ladder phase names, each
    optionally pinned to a single generation as ``phase@N`` (unpinned
    phases fire at every generation).  Unknown phase names and garbled
    pins are ignored -- a typo'd injection knob must never change what a
    production resize does."""
    e = os.environ if env is None else env
    spec: Dict[str, Optional[int]] = {}
    for token in (e.get(constants.RESIZE_FAULT_ENV, "") or "").split(","):
        token = token.strip()
        if not token:
            continue
        phase, _, pin = token.partition("@")
        if phase not in REBOOTSTRAP_PHASES:
            continue
        if pin:
            try:
                spec[phase] = int(pin)
            except ValueError:
                continue
        else:
            spec[phase] = None
    return spec


def check_fault(phase: str, generation: int,
                faults: Optional[Dict[str, Optional[int]]] = None) -> None:
    """Raise the injected fault when the knob arms ``phase`` (for this
    generation, or unpinned).  Deterministic: same env + same generation
    always fails at the same point -- the property ``make resize-smoke``
    and the rung tests rely on."""
    faults = resize_faults() if faults is None else faults
    if phase in faults and faults[phase] in (None, generation):
        raise RebootstrapError(
            phase, f"injected fault ({constants.RESIZE_FAULT_ENV}) at "
                   f"phase {phase}, generation {generation}", injected=True)


def shutdown_jax_distributed() -> bool:
    """Tear down only the distributed client -- the process, its host
    state, and the compile/executable caches stay warm.  Version-probed:
    returns True when a live client was shut down, False when this jax has
    no ``distributed.shutdown`` or no client was initialized."""
    import jax

    shutdown = getattr(getattr(jax, "distributed", None), "shutdown", None)
    if shutdown is None:
        return False
    try:
        shutdown()
    except RuntimeError:
        return False  # not initialized: nothing to tear down
    return True


def _clear_jax_backends() -> bool:
    """Drop the cached XLA backends so the next jax use re-initializes
    against the re-formed world -- ``jax.distributed.initialize`` only
    takes effect for backends created after it.  Version-probed across the
    locations jax has kept this; False when none exists (the rebootstrap
    then degrades a rung rather than continuing on a stale topology)."""
    import jax

    for probe in (
            lambda: getattr(getattr(jax, "extend", None), "backend", None),
            lambda: jax,
            lambda: getattr(jax, "_src", None) and jax._src.api):
        try:
            mod = probe()
        # analyzer: allow[broad-except]: version probing across jax
        # releases; any import/attr surprise just means "try the next".
        except Exception:
            continue
        clear = getattr(mod, "clear_backends", None) if mod else None
        if clear is None:
            continue
        try:
            clear()
            return True
        # analyzer: allow[broad-except]: a failed clear leaves the old
        # backend live; the caller treats that as "cannot rebootstrap".
        except Exception:
            return False
    return False


def barrier_timeout_s(env: Optional[Dict[str, str]] = None) -> float:
    """The coordinator-barrier budget (``TRAININGJOB_RESIZE_BARRIER_S``,
    default 30 s; floored at 0.1 s so a typo cannot spin-fail)."""
    e = os.environ if env is None else env
    try:
        return max(float(e.get(constants.RESIZE_BARRIER_ENV, "") or 30.0),
                   0.1)
    except ValueError:
        return 30.0


def _await_coordinator(address: str, timeout: float,
                       sleep: Callable[[float], None] = time.sleep) -> None:
    """Block until ``address`` accepts a TCP connection, with exponential
    backoff inside ``timeout`` seconds.  The bumped-generation coordinator
    (new rank 0) restarts its service inside ``jax.distributed.initialize``;
    the other survivors probe here first so their own initialize does not
    burn its whole internal timeout against a coordinator that is still
    tearing down."""
    host, _, port_s = address.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        raise RebootstrapError(
            "barrier", f"unparseable coordinator address {address!r}")
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            probe_budget = max(min(1.0, deadline - time.monotonic()), 0.05)
            socket.create_connection((host or "127.0.0.1", port),
                                     timeout=probe_budget).close()
            return
        except OSError:
            if time.monotonic() + delay >= deadline:
                raise RebootstrapError(
                    "barrier", f"coordinator {address} unreachable after "
                               f"{timeout:.1f}s")
            sleep(delay)
            delay = min(delay * 2.0, 1.0)


def rebootstrap_jax_distributed(
        rdv: Rendezvous, doc: Dict[str, Any],
        old_world: Optional[List[int]] = None,
        sleep: Callable[[float], None] = time.sleep,
) -> Tuple[Rendezvous, Dict[str, float]]:
    """Re-enter the distributed runtime at a published generation, live.

    The re-entrant counterpart of ``initialize_jax_distributed``: survivors
    tear down only the distributed client (``shutdown`` phase), wait for
    the bumped-generation coordinator the controller published
    (``barrier``, with timeout + backoff), and re-init at their new rank in
    the published world (``reinit``).  Single-process runtimes pass through
    with every phase a no-op -- except fault injection, which fires
    everywhere so every rung is drivable on one process.

    ``old_world`` is the replica-index list of the PREVIOUS generation
    (llama_elastic's ``world``); a multi-process survivor's stable identity
    is its entry there, and its new process id is that entry's position in
    the published world.  Raises ``RebootstrapError`` with the failing
    phase; returns the updated Rendezvous plus per-phase wall timings (ms).
    """
    generation = int(doc.get("generation", 0))
    world = [int(r) for r in (doc.get("world") or [])]
    faults = resize_faults()
    timings: Dict[str, float] = {}
    multi = rdv.num_processes > 1

    t0 = time.perf_counter()
    check_fault("shutdown", generation, faults)
    if multi:
        torn_down = shutdown_jax_distributed()
        if torn_down and not _clear_jax_backends():
            # The old topology would silently survive re-init: that is a
            # wedge waiting for the first collective, not a fast path.
            raise RebootstrapError(
                "shutdown", "distributed client shut down but this jax "
                            "cannot clear cached backends; cannot re-form "
                            "the world live")
    timings["shutdown_ms"] = (time.perf_counter() - t0) * 1e3

    if multi:
        ident = (old_world[rdv.process_id]
                 if old_world and 0 <= rdv.process_id < len(old_world)
                 else rdv.process_id)
        if ident not in world:
            # This survivor is not part of the published world: the
            # controller meant to drain it and the delete is in flight.
            # Degrading to the checkpoint rung parks its shards safely
            # instead of wedging the barrier for everyone else.
            raise RebootstrapError(
                "reinit", f"replica {ident} absent from published world "
                          f"{world} (generation {generation})")
        new_pid = world.index(ident)
        new_num = int(doc.get("num_processes") or len(world) or 1)
        coordinator = (str(doc.get("coordinator") or "")
                       or rdv.coordinator_address)
    else:
        # Single-process runtime: the published world is logical (the
        # sim's flat device pool); there is no client to re-form.
        new_pid, new_num, coordinator = 0, 1, rdv.coordinator_address

    t1 = time.perf_counter()
    check_fault("barrier", generation, faults)
    if multi and new_num > 1 and coordinator and new_pid != 0:
        _await_coordinator(coordinator, barrier_timeout_s(), sleep=sleep)
    timings["barrier_ms"] = (time.perf_counter() - t1) * 1e3

    t2 = time.perf_counter()
    check_fault("reinit", generation, faults)
    if multi and new_num > 1:
        if not coordinator:
            raise RebootstrapError(
                "reinit", f"generation {generation} doc published no "
                          "coordinator address")
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=new_num,
                process_id=new_pid,
            )
        # analyzer: allow[broad-except]: jax raises RuntimeError,
        # ValueError, or backend-specific errors here depending on
        # version; every one of them means "degrade a rung".
        except Exception as exc:
            raise RebootstrapError(
                "reinit", f"jax.distributed.initialize at generation "
                          f"{generation} failed: {exc}")
    timings["reinit_ms"] = (time.perf_counter() - t2) * 1e3

    new_rdv = dataclasses.replace(
        rdv,
        num_processes=new_num,
        process_id=new_pid,
        coordinator_address=coordinator,
        rendezvous_generation=max(generation, rdv.rendezvous_generation),
        elastic_replicas=len(world) or rdv.elastic_replicas,
    )
    return new_rdv, timings


def compile_cache_dir(rdv: Rendezvous) -> str:
    """Resolve the persistent compile-cache directory ("" when disabled).

    Shared by ``enable_compile_cache`` (points XLA's HLO-level cache here)
    and the workloads' executable snapshots
    (``train.store_executable_snapshot``), which live beside the HLO cache
    so both survive exactly as long as each other.
    """
    path = (os.environ.get(constants.COMPILE_CACHE_DIR_ENV, "")
            or os.environ.get(constants.COMPILE_CACHE_ENV, ""))
    if not path and rdv.checkpoint_dir:
        path = os.path.join(rdv.checkpoint_dir, ".jax_compile_cache")
    return "" if (not path or path == "off") else path


def enable_compile_cache(rdv: Rendezvous) -> None:
    """Point XLA's persistent compilation cache at a job-stable directory.

    A restarted elastic worker re-traces the same step function; with the
    cache warm, compilation -- the dominant term in the <90 s recovery budget
    (BASELINE.md) -- is a disk read instead of a rebuild.
    ``TRAININGJOB_COMPILE_CACHE_DIR`` names a JOB-SURVIVABLE location
    (cluster NFS, a persistent volume): a rescheduled job with a brand-new
    checkpoint dir still warm-starts its compile, and
    workloads/train.py's ``overlapped_restore`` runs the warm compile
    concurrently with the orbax restore.  Falls back to the legacy
    ``TRAININGJOB_COMPILE_CACHE``, then to
    ``<checkpoint_dir>/.jax_compile_cache`` (survives restarts exactly as
    long as the checkpoint does); ``off`` in either var disables.
    """
    path = compile_cache_dir(rdv)
    if not path:
        return
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: elastic workloads are restart-dominated, so even
    # sub-second compiles are worth persisting.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def apply_platform_override(var: str = constants.JAX_PLATFORM_ENV) -> None:
    """Honor a platform request from env (e.g. "cpu" for CPU replica groups).

    A config update after import wins even where a site hook pins the
    platform at interpreter start (needed so multi-worker CPU jobs on one
    machine don't all claim the single TPU, and so the driver's
    JAX_PLATFORMS=cpu virtual-mesh dry run actually gets CPU devices).
    The single implementation for every caller: workloads use the manifest
    env var, tests and the graft entry pass ``var="JAX_PLATFORMS"``.
    """
    plat = os.environ.get(var)
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    configure_partitioner()


def configure_partitioner() -> None:
    """Select the SPMD partitioner (TRAININGJOB_SHARDY=1 opts back in to
    Shardy; default is the classic GSPMD partitioner).

    Measured on the 2-slice virtual multislice mesh (6 axes, this jax/XLA
    build): Shardy emits "Involuntary full rematerialization"
    (spmd_partitioner.cc:652) for a per-layer tensor at the backward scan
    boundary -- a replicate-then-repartition on every step -- and the
    rmsnorm cotangent pin (models/llama.py ``pin_act``) does not silence
    it (it ADDS two more around the embedding gather).  The classic
    partitioner with the same pin compiles the full train step with ZERO
    involuntary remats, and the partial-manual shard_map pipeline path
    passes its parity suite under it.  Flip the default once XLA's
    b/433785288 (per the warning text) ships.
    """
    shardy = os.environ.get(constants.SHARDY_ENV, "")
    if shardy not in ("1", "true"):
        import jax

        try:
            jax.config.update("jax_use_shardy_partitioner", False)
        except AttributeError:  # config knob gone (future jax): keep default
            pass
