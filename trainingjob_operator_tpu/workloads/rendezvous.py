"""Workload-side rendezvous: read the operator's injected env and assemble the
distributed topology.

This is the consumer of the env contract from controller/pod.py set_env
(reference: pod.go:548-652 + the TPU mapping of SURVEY.md §3.5): identity vars
(TRAININGJOB_*), per-group host lists ({RT}_INSTANCES/_PORTS/_HOSTS), and the
JAX bootstrap set (coordinator address, process count/id, TPU topology).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from trainingjob_operator_tpu.api import constants


@dataclass
class Rendezvous:
    """Everything a worker needs to find its peers and its place."""

    job_name: str = ""
    namespace: str = ""
    replica_name: str = ""
    replica_index: int = 0
    restart_count: int = 0
    num_processes: int = 1
    process_id: int = 0
    coordinator_address: str = ""
    service: str = ""
    checkpoint_dir: str = ""
    elastic_replicas: int = 1
    tpu_accelerator: str = ""
    tpu_topology: str = ""
    slice_id: int = 0
    num_slices: int = 1
    is_reservation: bool = False
    resize_dir: str = ""
    rendezvous_generation: int = 0
    group_instances: Dict[str, List[str]] = field(default_factory=dict)
    group_hosts: Dict[str, List[str]] = field(default_factory=dict)

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    def hold_reservation_if_needed(self) -> None:
        """Re-expand reservation pods (capacity canaries) idle here instead of
        joining a rendezvous they are not part of; the operator restarts them
        with a real rank once the resize commits.  Call first in every
        workload main.

        The hold is bounded: past the injected TTL the canary exits 143
        (-> pod Failed -> the controller's probe-failed path cancels the
        probe on resync), so a probe orphaned by a dead controller frees its
        TPU host without any external GC (VERDICT r3 Weak #7)."""
        if not self.is_reservation:
            return
        import sys as _sys
        import time as _time

        ttl = float(os.environ.get(constants.RESERVATION_TTL_ENV, "0") or 0)
        deadline = _time.time() + ttl if ttl > 0 else None
        while deadline is None or _time.time() < deadline:
            _time.sleep(min(5.0, max(deadline - _time.time(), 0.01))
                        if deadline is not None else 3600)
        _sys.exit(143)

    def hosts(self, group: str) -> List[str]:
        """host:port list of a replica group (after any localproc rewrite)."""
        return self.group_hosts.get(group.upper(), [])

    @property
    def generation_path(self) -> str:
        """Where the controller republishes the rendezvous generation
        (controller/pod.py publish_generation); "" when resize is not wired
        for this job."""
        return (os.path.join(self.resize_dir, "generation.json")
                if self.resize_dir else "")


def read_generation(path: str) -> Optional[Dict[str, Any]]:
    """Parse a published generation doc; None on absence or garble.

    The writer is atomic (tmp + os.replace) so a partial read means an
    out-of-band scribble, not a torn write -- either way the contract is the
    same: ignore anything that is not a well-formed doc and keep training at
    the current generation."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if (isinstance(doc, dict)
                and isinstance(doc.get("generation"), int)
                and isinstance(doc.get("world"), list)
                and doc["generation"] > 0):
            return doc
    except (OSError, ValueError):
        pass
    return None


class GenerationWatcher:
    """Cheap per-step poll of the controller's generation channel.

    Survivors call ``poll()`` at every step boundary; it is rate-limited to
    ``TRAININGJOB_RESIZE_POLL_S`` (default 0.5 s) and stat-gated (a read only
    happens when the file's mtime moved), so the steady-state cost is one
    ``os.stat`` every poll interval.  A doc is surfaced once, and only when
    its generation is beyond both the process's birth epoch (the injected
    ``TRAININGJOB_RENDEZVOUS_GENERATION``) and the last surfaced doc --
    a freshly (re)started pod never reacts to the generation it was born
    into.
    """

    def __init__(self, rdv: Optional[Rendezvous] = None,
                 path: Optional[str] = None,
                 birth: Optional[int] = None,
                 interval: Optional[float] = None) -> None:
        if rdv is None and (path is None or birth is None):
            rdv = from_env()
        self.path = path if path is not None else rdv.generation_path
        self.seen = birth if birth is not None else rdv.rendezvous_generation
        if interval is None:
            try:
                interval = float(
                    os.environ.get(constants.RESIZE_POLL_ENV, "") or 0.5)
            except ValueError:
                interval = 0.5
        self.interval = max(interval, 0.0)
        self._next_check = 0.0
        self._mtime: Optional[float] = None
        #: Set by train.run_elastic_loop when a poll fires mid-run: the doc
        #: that interrupted the step loop, and the step to resume at after
        #: the in-place reshard.
        self.pending: Optional[Dict[str, Any]] = None
        self.resume_step: int = 0

    def poll(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """The freshest unseen generation doc, or None."""
        if not self.path:
            return None
        now = time.monotonic() if now is None else now
        if now < self._next_check:
            return None
        self._next_check = now + self.interval
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return None
        if mtime == self._mtime:
            return None
        self._mtime = mtime
        doc = read_generation(self.path)
        if doc is not None and doc["generation"] > self.seen:
            self.seen = doc["generation"]
            return doc
        return None


def from_env(env: Optional[Dict[str, str]] = None) -> Rendezvous:
    e = dict(os.environ if env is None else env)
    rdv = Rendezvous(
        job_name=e.get(constants.JOB_NAME_ENV, ""),
        namespace=e.get(constants.JOB_NAMESPACE_ENV, "default"),
        replica_name=e.get(constants.REPLICA_NAME_ENV, ""),
        replica_index=int(e.get(constants.REPLICA_INDEX_ENV, "0") or 0),
        restart_count=int(e.get(constants.REPLICA_RESTART_COUNT_ENV, "0") or 0),
        num_processes=int(e.get(constants.NUM_PROCESSES_ENV, "1") or 1),
        process_id=int(e.get(constants.PROCESS_ID_ENV, "0") or 0),
        coordinator_address=e.get(constants.COORDINATOR_ADDRESS_ENV, ""),
        service=e.get(constants.SERVICE_ENV, ""),
        checkpoint_dir=e.get(constants.CHECKPOINT_DIR_ENV, ""),
        elastic_replicas=int(e.get(constants.ELASTIC_REPLICAS_ENV, "1") or 1),
        tpu_accelerator=e.get(constants.TPU_ACCELERATOR_ENV, ""),
        tpu_topology=e.get(constants.TPU_TOPOLOGY_ENV, ""),
        slice_id=int(e.get(constants.SLICE_ID_ENV, "0") or 0),
        num_slices=int(e.get(constants.NUM_SLICES_ENV, "1") or 1),
        is_reservation=e.get(constants.RESERVATION_ENV, "") == "1",
        resize_dir=e.get(constants.RESIZE_DIR_ENV, ""),
        rendezvous_generation=int(
            e.get(constants.RENDEZVOUS_GENERATION_ENV, "0") or 0),
    )
    for key, value in e.items():
        if key.endswith("_INSTANCES") and not key.endswith("_NUM"):
            rdv.group_instances[key[:-len("_INSTANCES")]] = (
                value.split(",") if value else [])
        elif key.endswith("_HOSTS") and not key.endswith("_NUM"):
            rdv.group_hosts[key[:-len("_HOSTS")]] = (
                value.split(",") if value else [])
    return rdv


def initialize_jax_distributed(rdv: Optional[Rendezvous] = None) -> Rendezvous:
    """Call jax.distributed.initialize from the injected env when the job is
    multi-process; no-op for single-process jobs.

    This is the TPU-native replacement for the reference's "framework inside
    the pod self-assembles from env" contract (SURVEY.md §2.7): intra-slice
    collectives ride ICI compiled by XLA; this call only wires the control
    plane (coordinator + process ids).
    """
    rdv = rdv or from_env()
    rdv.hold_reservation_if_needed()  # capacity canaries never join
    apply_platform_override()
    enable_compile_cache(rdv)
    if rdv.num_processes > 1 and rdv.coordinator_address:
        import jax

        jax.distributed.initialize(
            coordinator_address=rdv.coordinator_address,
            num_processes=rdv.num_processes,
            process_id=rdv.process_id,
        )
    return rdv


def compile_cache_dir(rdv: Rendezvous) -> str:
    """Resolve the persistent compile-cache directory ("" when disabled).

    Shared by ``enable_compile_cache`` (points XLA's HLO-level cache here)
    and the workloads' executable snapshots
    (``train.store_executable_snapshot``), which live beside the HLO cache
    so both survive exactly as long as each other.
    """
    path = (os.environ.get(constants.COMPILE_CACHE_DIR_ENV, "")
            or os.environ.get(constants.COMPILE_CACHE_ENV, ""))
    if not path and rdv.checkpoint_dir:
        path = os.path.join(rdv.checkpoint_dir, ".jax_compile_cache")
    return "" if (not path or path == "off") else path


def enable_compile_cache(rdv: Rendezvous) -> None:
    """Point XLA's persistent compilation cache at a job-stable directory.

    A restarted elastic worker re-traces the same step function; with the
    cache warm, compilation -- the dominant term in the <90 s recovery budget
    (BASELINE.md) -- is a disk read instead of a rebuild.
    ``TRAININGJOB_COMPILE_CACHE_DIR`` names a JOB-SURVIVABLE location
    (cluster NFS, a persistent volume): a rescheduled job with a brand-new
    checkpoint dir still warm-starts its compile, and
    workloads/train.py's ``overlapped_restore`` runs the warm compile
    concurrently with the orbax restore.  Falls back to the legacy
    ``TRAININGJOB_COMPILE_CACHE``, then to
    ``<checkpoint_dir>/.jax_compile_cache`` (survives restarts exactly as
    long as the checkpoint does); ``off`` in either var disables.
    """
    path = compile_cache_dir(rdv)
    if not path:
        return
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: elastic workloads are restart-dominated, so even
    # sub-second compiles are worth persisting.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def apply_platform_override(var: str = constants.JAX_PLATFORM_ENV) -> None:
    """Honor a platform request from env (e.g. "cpu" for CPU replica groups).

    A config update after import wins even where a site hook pins the
    platform at interpreter start (needed so multi-worker CPU jobs on one
    machine don't all claim the single TPU, and so the driver's
    JAX_PLATFORMS=cpu virtual-mesh dry run actually gets CPU devices).
    The single implementation for every caller: workloads use the manifest
    env var, tests and the graft entry pass ``var="JAX_PLATFORMS"``.
    """
    plat = os.environ.get(var)
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    configure_partitioner()


def configure_partitioner() -> None:
    """Select the SPMD partitioner (TRAININGJOB_SHARDY=1 opts back in to
    Shardy; default is the classic GSPMD partitioner).

    Measured on the 2-slice virtual multislice mesh (6 axes, this jax/XLA
    build): Shardy emits "Involuntary full rematerialization"
    (spmd_partitioner.cc:652) for a per-layer tensor at the backward scan
    boundary -- a replicate-then-repartition on every step -- and the
    rmsnorm cotangent pin (models/llama.py ``pin_act``) does not silence
    it (it ADDS two more around the embedding gather).  The classic
    partitioner with the same pin compiles the full train step with ZERO
    involuntary remats, and the partial-manual shard_map pipeline path
    passes its parity suite under it.  Flip the default once XLA's
    b/433785288 (per the warning text) ships.
    """
    shardy = os.environ.get(constants.SHARDY_ENV, "")
    if shardy not in ("1", "true"):
        import jax

        try:
            jax.config.update("jax_use_shardy_partitioner", False)
        except AttributeError:  # config knob gone (future jax): keep default
            pass
