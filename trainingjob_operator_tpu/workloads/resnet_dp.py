"""ResNet-50 data-parallel trainer -- BASELINE config 3 (v5e-8 single host).

TPU-first data parallelism: one process, all local chips in a 1-axis ``dp``
mesh; the global batch is sharded over it with ``NamedSharding`` and the
gradient all-reduce is inserted by XLA from the sharded mean -- no
hand-written collectives (scaling-book recipe).  Conv/matmul FLOPs land on
the MXU in bfloat16 via the model's compute dtype; batch-norm statistics ride
the same XLA fusions.

Checkpoint/resume keyed on TRAININGJOB_REPLICA_RESTARTCOUNT (reference
contract, pod.go:610-613).

Data is SYNTHETIC (random images) by design: this workload proves
config/operator parity for the reference's single-host DP shape, not
training quality -- the real-input path lives in llama_elastic/moe_pretrain
(``{P}_DATA`` + data/tokens.py).  Wire an image loader here only if you
need accuracy numbers.

Run: ``python -m trainingjob_operator_tpu.workloads.resnet_dp``.
Env: RESNET_CONFIG=tiny|resnet50, RESNET_STEPS, RESNET_BATCH (global),
RESNET_LR.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from trainingjob_operator_tpu.models import resnet

    cfg = (resnet.ResNetConfig.resnet50()
           if os.environ.get("RESNET_CONFIG", "tiny") == "resnet50"
           else resnet.ResNetConfig.tiny())
    steps = int(os.environ.get("RESNET_STEPS", "20"))
    global_batch = int(os.environ.get("RESNET_BATCH", "32"))
    lr = float(os.environ.get("RESNET_LR", "0.1"))
    size = int(os.environ.get("RESNET_IMAGE", "64"))

    import numpy as np

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("dp",))
    batch_sharding = NamedSharding(mesh, P("dp"))
    replicated = NamedSharding(mesh, P())
    global_batch, _ = train.round_global_batch(global_batch, len(devices))

    key = jax.random.PRNGKey(0)
    params, stats = resnet.init_params(cfg, key)
    params = jax.device_put(params, replicated)
    stats = jax.device_put(stats, replicated)
    tx = optax.sgd(lr, momentum=0.9)
    opt_state = tx.init(params)

    # Donated state (TJA022): params/stats/opt_state round-trip through
    # every step and the loop rebinds all three, so XLA reuses the input
    # buffers for the outputs instead of double-buffering the full state.
    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step_fn(p, s, o, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            resnet.loss_fn, has_aux=True)(p, s, {"images": images,
                                                 "labels": labels}, cfg)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), new_stats, o, loss

    def batch_at(i):
        k = jax.random.fold_in(jax.random.PRNGKey(7), i)
        ki, kl = jax.random.split(k)
        images = jax.random.normal(
            ki, (global_batch, size, size, 3), jnp.float32)
        labels = jax.random.randint(kl, (global_batch,), 0, cfg.num_classes)
        return (jax.device_put(images, batch_sharding),
                jax.device_put(labels, batch_sharding))

    # Full training state: params, batch-norm statistics, and optimizer
    # momentum all resume, so the post-restart trajectory matches an
    # uninterrupted run.
    state = train.CheckpointState.restore_or_init(
        rdv, {"params": jax.device_get(params),
              "stats": jax.device_get(stats),
              "opt_state": jax.device_get(opt_state), "step": 0})
    start_step = int(state.value["step"])
    if start_step > 0:
        params = jax.device_put(state.value["params"], replicated)
        stats = jax.device_put(state.value["stats"], replicated)
        host_opt = jax.tree.unflatten(jax.tree.structure(opt_state),
                                      jax.tree.leaves(state.value["opt_state"]))
        opt_state = jax.tree.map(
            lambda host, _: jax.device_put(host, replicated),
            host_opt, opt_state)

    loss = None
    t_start = None
    for i in range(start_step, steps):
        images, labels = batch_at(i)
        params, stats, opt_state, loss = step_fn(params, stats, opt_state,
                                                 images, labels)
        if i == start_step:
            # analyzer: allow[host-sync-in-hot-loop] first-step compile
            # fence, gated to run once: excludes trace+compile from the
            # throughput window.
            jax.block_until_ready(loss)
            t_start = time.time()
        if (i + 1) % 10 == 0 or i == steps - 1:
            # analyzer: allow[host-sync-in-hot-loop] periodic log read,
            # gated to every 10th step; one bounded scalar D2H.
            print(f"step {i+1}/{steps} loss {float(loss):.4f}", flush=True)
            # Live device arrays: CheckpointState.save snapshots to host
            # with async copies (the snapshot-donate path).  The previous
            # jax.device_get per tree here was TJA021's canonical finding:
            # three synchronous full-state D2H copies stalling the step
            # loop, duplicating the copy save() does anyway.
            state.save({"params": params, "stats": stats,
                        "opt_state": opt_state, "step": i + 1})
    jax.block_until_ready(loss)
    state.finalize()  # commit any in-flight background save before exit
    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    print(f"done: steps={done} imgs/s={done * global_batch / dt:.1f} "
          f"devices={len(devices)} batch={global_batch} "
          f"final_loss={float(loss) if loss is not None else -1:.4f} "
          f"restart_count={rdv.restart_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
