"""MNIST MLP trainer -- BASELINE config 1 (the reference's paddle-mnist
example, example/paddle-mnist.yaml, as a JAX workload).

Single- or multi-process data-parallel: with N processes the global batch is
sharded N ways and gradients are psum'd across the `jax.distributed` mesh.
Data is a deterministic synthetic MNIST stand-in (no network egress), with the
same shapes (28x28 grayscale, 10 classes) so the compute path is authentic.

Checkpoint/resume: keyed on TRAININGJOB_REPLICA_RESTARTCOUNT (the reference's
restart-detection contract, pod.go:610-613) -- on restart > 0 the trainer
reloads step/params from the injected checkpoint dir and continues.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial


def synthetic_mnist(key, n: int, batch: int):
    """Deterministic synthetic dataset: class-conditional Gaussian digits."""
    import jax
    import jax.numpy as jnp

    kimg, klab = jax.random.split(key)
    labels = jax.random.randint(klab, (n,), 0, 10)
    centers = jax.random.normal(kimg, (10, 784)) * 0.5
    noise = jax.random.normal(jax.random.fold_in(kimg, 1), (n, 784)) * 0.3
    images = centers[labels] + noise
    steps = n // batch
    return images.reshape(steps, batch, 784), labels.reshape(steps, batch)


def _make_globalizer():
    """Identity on one process; on many, assemble per-process shards into a
    global batch-sharded array."""
    import jax

    if jax.process_count() == 1:
        return lambda x: x
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("batch",))
    sharding = NamedSharding(mesh, P("batch"))
    return lambda x: jax.make_array_from_process_local_data(
        sharding, np.asarray(x))


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import jax.numpy as jnp
    import optax

    num_steps = int(os.environ.get("MNIST_STEPS", "60"))
    batch = int(os.environ.get("MNIST_BATCH", "128"))
    hidden = int(os.environ.get("MNIST_HIDDEN", "256"))
    lr = float(os.environ.get("MNIST_LR", "1e-3"))

    key = jax.random.PRNGKey(0)
    k1, k2, kdata = jax.random.split(key, 3)
    params = {
        "w1": jax.random.normal(k1, (784, hidden)) * 0.05,
        "b1": jnp.zeros((hidden,)),
        "w2": jax.random.normal(k2, (hidden, 10)) * 0.05,
        "b2": jnp.zeros((10,)),
    }
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    # Each process sees its shard of the global batch (data parallel).  With
    # multiple processes the per-step shards are assembled into one GLOBAL
    # array sharded over all devices; the loss is a mean over the global
    # batch, so XLA inserts the cross-process gradient all-reduce itself --
    # no hand-written collective (scaling-book recipe).
    shard_key = jax.random.fold_in(kdata, rdv.process_id)
    images, labels = synthetic_mnist(shard_key, num_steps * batch, batch)

    globalize = _make_globalizer()

    def loss_fn(p, x, y):
        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    # Donated state (TJA022): the loop rebinds params/opt_state every
    # step, so XLA aliases the inputs to the outputs in place.
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(p, o, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    # Multi-process: wrap in pmap-style mean via device mesh.  On one process
    # with one device, plain jit suffices; cross-process sync happens through
    # jax.distributed (all processes run identical programs).
    state = train.CheckpointState.restore_or_init(
        rdv, {"params": params, "opt_state": opt_state, "step": 0})
    params, opt_state = state.value["params"], state.value["opt_state"]
    start_step = int(state.value["step"])

    t0 = time.time()
    loss = None
    for i in range(start_step, num_steps):
        params, opt_state, loss = step(params, opt_state,
                                       globalize(images[i]),
                                       globalize(labels[i]))
        if (i + 1) % 20 == 0 or i == num_steps - 1:
            # analyzer: allow[host-sync-in-hot-loop] periodic log read,
            # gated to every 20th step; one bounded scalar D2H.
            print(f"step {i+1}/{num_steps} loss {float(loss):.4f}", flush=True)
            state.save({"params": params, "opt_state": opt_state, "step": i + 1})
    state.finalize()  # commit any in-flight background save before exit
    dt = time.time() - t0

    # Final train accuracy on the last shard.
    h = jax.nn.relu(images[-1] @ params["w1"] + params["b1"])
    acc = float((jnp.argmax(h @ params["w2"] + params["b2"], -1)
                 == labels[-1]).mean())
    steps_done = num_steps - start_step
    print(f"done: steps={steps_done} time={dt:.2f}s "
          f"steps/s={steps_done / max(dt, 1e-9):.1f} "
          f"final_loss={float(loss) if loss is not None else -1:.4f} acc={acc:.3f} "
          f"restart_count={rdv.restart_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
