"""BERT-base masked-LM pretrain -- BASELINE config 4 (v5e-16, 4 hosts).

Multi-host TPU path: every pod calls ``jax.distributed.initialize`` from the
operator-injected coordinator env (SURVEY.md §5.8), then builds ONE global
``dp x tp`` mesh over all chips of the slice.  Parameters are sharded by the
model's rules (tp on the head/ffn axes), the batch by dp; each process feeds
its local shard of the global batch via
``make_array_from_process_local_data`` and XLA inserts every collective --
the multi-host program is byte-identical on every worker.

Run: ``python -m trainingjob_operator_tpu.workloads.bert_pretrain``.
Env: BERT_CONFIG=tiny|base, BERT_TP (model-parallel width, default 1),
BERT_STEPS, BERT_BATCH (global), BERT_SEQ, BERT_LR.

Data is SYNTHETIC (random MLM batches) by design: this workload proves the
multi-host operator contract, not training quality; the real-corpus path is
llama_elastic/moe_pretrain (``{P}_DATA``).
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial


def synthetic_mlm_batch(key, batch: int, seq: int, vocab: int,
                        mask_token: int = 0, rate: float = 0.15):
    """Random tokens; 15% positions masked out and to be predicted."""
    import jax
    import jax.numpy as jnp

    kt, km = jax.random.split(key)
    targets = jax.random.randint(kt, (batch, seq), 1, vocab)
    mask = jax.random.bernoulli(km, rate, (batch, seq))
    tokens = jnp.where(mask, mask_token, targets)
    return {"tokens": tokens, "targets": targets,
            "mask": mask.astype(jnp.int32)}


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from trainingjob_operator_tpu.models import bert
    from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
    from trainingjob_operator_tpu.parallel.sharding import shard_pytree

    cfg = (bert.BertConfig.base()
           if os.environ.get("BERT_CONFIG", "tiny") == "base"
           else bert.BertConfig.tiny())
    tp = int(os.environ.get("BERT_TP", "1"))
    steps = int(os.environ.get("BERT_STEPS", "20"))
    global_batch = int(os.environ.get("BERT_BATCH", "32"))
    seq = int(os.environ.get("BERT_SEQ", "128"))
    lr = float(os.environ.get("BERT_LR", "1e-4"))

    mesh = mesh_from_rendezvous(rdv, model_parallel=tp)
    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch_sharding = NamedSharding(mesh, P(data_axes))
    n_data = int(np.prod([mesh.shape[a] for a in data_axes])) or 1
    global_batch, _ = train.round_global_batch(global_batch, n_data)

    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    params = shard_pytree(params, bert.SHARDING_RULES, mesh)
    tx = optax.adamw(lr, weight_decay=0.01)
    opt_state = tx.init(params)

    # Donated state (TJA022): the loop rebinds params/opt_state every
    # step, so XLA aliases the inputs to the outputs instead of holding
    # two copies of the full state in HBM.
    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(p, o, b):
        loss, grads = jax.value_and_grad(bert.loss_fn)(p, b, cfg)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    local_batch = global_batch // max(jax.process_count(), 1)

    def batch_at(i):
        k = jax.random.fold_in(jax.random.PRNGKey(11 + rdv.process_id), i)
        local = synthetic_mlm_batch(k, local_batch, seq, cfg.vocab_size)
        return {name: train.globalize_batch(batch_sharding, v)
                for name, v in local.items()}

    # Shared rank-agnostic checkpoint: sharded orbax save/restore -- each
    # host writes/reads only its shards; restore reshards onto the current
    # mesh (the live params/opt_state act as the sharding template).
    state = train.CheckpointState.restore_or_init(
        rdv, {"params": params, "opt_state": opt_state, "step": 0},
        subdir="bert", mesh=mesh)
    start_step = int(state.value["step"])
    params = state.value["params"]
    opt_state = state.value["opt_state"]
    if start_step > 0:
        print(f"resumed at step {start_step}", flush=True)

    loss = None
    t_start = None
    for i in range(start_step, steps):
        params, opt_state, loss = step_fn(params, opt_state, batch_at(i))
        if i == start_step:
            # analyzer: allow[host-sync-in-hot-loop] first-step compile
            # fence, gated to run once: excludes trace+compile from the
            # throughput window.
            jax.block_until_ready(loss)
            t_start = time.time()
        if (i + 1) % 10 == 0 or i == steps - 1:
            # analyzer: allow[host-sync-in-hot-loop] periodic log read,
            # gated to every 10th step; one bounded scalar D2H.
            print(f"step {i+1}/{steps} loss {float(loss):.4f}", flush=True)
            # Collective sharded background save: all processes call it.
            state.save({"params": params, "opt_state": opt_state,
                        "step": i + 1})
    jax.block_until_ready(loss)
    state.finalize()
    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    tokens_s = done * global_batch * seq / dt
    print(f"done: steps={done} tokens/s={tokens_s:.0f} "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"processes={jax.process_count()} "
          f"final_loss={float(loss) if loss is not None else -1:.4f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
