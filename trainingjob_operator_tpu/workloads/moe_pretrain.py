"""Elastic MoE (Mixtral-style) pretrain over a dp x fsdp x tp x ep mesh.

Same operator contract as workloads/llama_elastic.py (width from
TRAININGJOB_* env, shared sharded checkpoint, graceful-preemption SIGTERM
handler, profiler hooks), with the MoE model family exercising expert
parallelism: expert weights shard on ``ep`` and the token->expert dispatch
einsum carries the all-to-all on ICI (models/moe.py).

Run: ``python -m trainingjob_operator_tpu.workloads.moe_pretrain``.
Env: MOE_CONFIG=tiny|8x7b, MOE_TP, MOE_EP, MOE_STEPS, MOE_BATCH (global),
MOE_CE_CHUNK (chunked cross-entropy),
MOE_WINDOW (sliding-window attention span),
MOE_SEQ, MOE_LR, MOE_CKPT_EVERY, plus the shared data/eval set
(MOE_DATA, MOE_SEED, MOE_EVAL_EVERY/_BATCHES/_FRACTION --
workloads/train.py build_batch_sources).
"""

from __future__ import annotations

import os
import sys
import time


def main() -> int:
    from trainingjob_operator_tpu.workloads import rendezvous, train

    rdv = rendezvous.initialize_jax_distributed()

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding

    from trainingjob_operator_tpu.models import moe
    from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
    from trainingjob_operator_tpu.parallel.sharding import (
        batch_spec,
        shard_pytree,
    )

    cfg = (moe.MoEConfig.mixtral_8x7b()
           if os.environ.get("MOE_CONFIG", "tiny") == "8x7b"
           else moe.MoEConfig.tiny())
    tp = int(os.environ.get("MOE_TP", "1"))
    ep = int(os.environ.get("MOE_EP", "1"))
    steps = int(os.environ.get("MOE_STEPS", "20"))
    global_batch = int(os.environ.get("MOE_BATCH", "8"))
    seq = int(os.environ.get("MOE_SEQ", "128"))
    lr = float(os.environ.get("MOE_LR", "3e-4"))
    ckpt_every = int(os.environ.get("MOE_CKPT_EVERY", "10"))
    remat = os.environ.get("MOE_REMAT", train.default_remat(cfg.n_layers))
    ce_chunk = int(os.environ.get("MOE_CE_CHUNK", "0"))
    window = int(os.environ.get("MOE_WINDOW", "0"))
    if window:
        import dataclasses

        cfg = dataclasses.replace(cfg, sliding_window=window)

    mesh = mesh_from_rendezvous(rdv, model_parallel=tp, expert_parallel=ep)
    print(f"elastic width {rdv.elastic_replicas}, mesh "
          f"{dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"{moe.num_params(cfg)/1e6:.1f}M params "
          f"({moe.active_params(cfg)/1e6:.1f}M active), restart "
          f"{rdv.restart_count}", flush=True)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    n_data = int(np.prod([mesh.shape[a] for a in data_axes])) or 1
    global_batch, _ = train.round_global_batch(global_batch, n_data)

    params = shard_pytree(moe.init_params(cfg, jax.random.PRNGKey(0)),
                          moe.SHARDING_RULES, mesh)
    tx = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.1)
    opt_state = tx.init(params)
    batch_sharding = NamedSharding(mesh, batch_spec(mesh))

    @jax.jit
    def step_fn(p, o, tokens):
        def loss(pp):
            return moe.loss_fn(pp, {"tokens": tokens}, cfg, mesh=mesh,
                               remat=remat, ce_chunk=ce_chunk)

        l, grads = jax.value_and_grad(loss)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, l

    local_batch = global_batch // max(jax.process_count(), 1)
    batch_at, eval_batch_at, eval_every, eval_batches = (
        train.build_batch_sources(
            prefix="MOE", vocab_size=cfg.vocab_size,
            global_batch=global_batch, local_batch=local_batch,
            row0=rdv.process_id * local_batch, seq=seq,
            batch_sharding=batch_sharding, synthetic_key=23))

    eval_fn = None
    if eval_batch_at is not None:
        @jax.jit
        def eval_loss(p, tokens):
            # Same ce_chunk as training: eval must fit where training fits.
            return moe.loss_fn(p, {"tokens": tokens}, cfg, mesh=mesh,
                               ce_chunk=ce_chunk)

        eval_fn = train.mean_eval_fn(eval_loss, eval_batch_at, eval_batches)

    state = train.CheckpointState.restore_or_init(
        rdv, {"params": params, "opt_state": opt_state, "step": 0},
        subdir="moe", mesh=mesh)
    start_step = int(state.value["step"])
    params = state.value["params"]
    opt_state = state.value["opt_state"]
    if start_step > 0:
        print(f"resumed at step {start_step} (width "
              f"{rdv.elastic_replicas})", flush=True)

    # Telemetry accounting.  The 6 * params * tokens FLOPs estimate is an
    # upper bound for an MoE (only top-k experts are active per token);
    # templates wanting an exact figure override via
    # TRAININGJOB_MODEL_FLOPS_PER_STEP.
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    tokens_per_step = global_batch * seq
    params, opt_state, loss, t_start = train.run_elastic_loop(
        step_fn=step_fn, batch_at=batch_at, state=state, params=params,
        opt_state=opt_state, steps=steps, start_step=start_step,
        ckpt_every=ckpt_every, eval_fn=eval_fn, eval_every=eval_every,
        units_per_step=tokens_per_step,
        flops_per_step=6.0 * n_params * tokens_per_step)
    dt = max(time.time() - (t_start or time.time()), 1e-9)
    done = max(steps - start_step - 1, 1)
    print(f"done: steps={done} tokens/s={done * global_batch * seq / dt:.0f} "
          f"width={rdv.elastic_replicas} "
          f"final_loss={float(loss) if loss is not None else -1:.4f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
