"""JAX/XLA training workloads exercised by the operator end-to-end.

The reference runs *other people's* frameworks inside its pods (Paddle, TF --
README.md:2); this package is the equivalent in-repo workload layer for the
BASELINE.json configs: MNIST MLP (CPU), PS/worker, ResNet-50 DP, BERT
multi-host, elastic Llama-2 pretrain.  Every entrypoint bootstraps from the
operator's injected env (workloads.rendezvous) and runs under
``python -m trainingjob_operator_tpu.workloads.<name>``.

JAX is imported lazily inside the workload modules so the operator control
plane never pays the import cost.
"""
