"""Memory-mapped token datasets with stateless deterministic sampling.

File format (``.tokens``): a 16-byte header -- magic ``b"AITJTOK1"``, then
uint32 dtype code (2 = uint16, 4 = uint32) and uint32 vocab size -- followed
by the flat token stream.  The vocab travels WITH the corpus so a consumer
can refuse a model/corpus mismatch (an out-of-range id would otherwise be
silently clamped by XLA's gather into a plausible-looking wrong token).
Written by ``write_tokens`` (tokenize once, train many); memory-mapped on
load so a TPU-VM host never pages the whole corpus into RAM (reference has
no equivalent; the in-container framework owns data, SURVEY.md §2.7).

Sampling is STATELESS: ``batch(step)`` derives every row's window offset from
``(seed, step, row)`` via a tiny splitmix-style hash -- random access, no
shuffle buffer, no iterator state.  Restart/elastic contracts fall out:
resuming at step N at ANY data-parallel width replays the byte-identical
global batch sequence, because a width-w shard just takes its ``rows / w``
slice of the same global batch (workloads/train.py ``globalize_batch``).
"""

from __future__ import annotations

import os
from typing import Optional

MAGIC = b"AITJTOK1"
_DTYPES = {2: "uint16", 4: "uint32"}
_CODES = {v: k for k, v in _DTYPES.items()}
HEADER_BYTES = 16


def write_tokens(path: str, tokens, vocab_size: Optional[int] = None) -> int:
    """Serialize a 1-D int array to the ``.tokens`` format; returns count.

    Picks uint16 when the ids fit (vocab <= 65536: half the disk and HBM-DMA
    bytes of int32 -- bandwidth is the input pipeline's budget).
    """
    import numpy as np

    arr = np.asarray(tokens)
    if arr.ndim != 1:
        raise ValueError(f"tokens must be 1-D, got shape {arr.shape}")
    top = int(arr.max()) if arr.size else 0
    if arr.size and int(arr.min()) < 0:
        raise ValueError(f"negative token id {int(arr.min())}")
    hi = int(vocab_size) if vocab_size else top + 1
    if top >= hi:
        # A narrower dtype would WRAP the stray id into a plausible-looking
        # wrong token -- corrupting the corpus at write time, silently.
        raise ValueError(f"token id {top} >= vocab_size {hi}")
    dtype = "uint16" if hi <= 65536 else "uint32"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        import struct

        f.write(MAGIC + struct.pack("<II", _CODES[dtype], hi))
        f.write(arr.astype(dtype).tobytes())
    os.replace(tmp, path)  # atomic: a reader never sees a half-written file
    return int(arr.size)


class TokenDataset:
    """Random-access window sampler over a memory-mapped token file.

    ``region=(lo, hi)`` restricts sampling to that fraction of the stream --
    a REAL train/eval split (train on ``(0, 0.9)``, eval on ``(0.9, 1.0)``):
    held-out data must be disjoint TOKENS, not merely a different sampling
    seed over the same tokens, or eval loss tracks memorization.
    """

    def __init__(self, path: str, seed: int = 0,
                 region: "tuple[float, float]" = (0.0, 1.0)):
        import struct

        import numpy as np

        with open(path, "rb") as f:
            head = f.read(HEADER_BYTES)
        if len(head) != HEADER_BYTES or head[:8] != MAGIC:
            raise ValueError(f"{path}: not a {MAGIC.decode()} token file")
        code, vocab = struct.unpack("<II", head[8:])
        if code not in _DTYPES:
            raise ValueError(f"{path}: unknown dtype code {code}")
        lo, hi = region
        if not (0.0 <= lo < hi <= 1.0):
            raise ValueError(f"bad region {region}")
        self.path = path
        #: ids are < vocab_size (0 on files from before the field existed).
        self.vocab_size = int(vocab)
        self.seed = int(seed)
        self.region = (float(lo), float(hi))
        self._tokens = np.memmap(path, dtype=_DTYPES[code], mode="r",
                                 offset=HEADER_BYTES)
        if self._tokens.size == 0:
            raise ValueError(f"{path}: empty token stream")

    def __len__(self) -> int:
        return int(self._tokens.size)

    def check_window(self, window: int) -> None:
        """Raise unless the region holds at least one ``window``-token
        sample -- the startup-time misconfiguration check (a too-small eval
        tail must fail before training burns steps toward the first eval
        point, not at it)."""
        self._offsets(0, 1, window)

    def _offsets(self, step: int, rows: int, window: int):
        """Window start offsets for every row of global step ``step``.

        splitmix64-style avalanche of (seed, step, row): uncorrelated,
        O(1)-random-access, and identical on every host -- determinism
        across widths needs no coordination.
        """
        import numpy as np

        lo = int(len(self) * self.region[0])
        hi = int(len(self) * self.region[1])
        span = (hi - lo) - window
        if span < 0:
            raise ValueError(
                f"{self.path}: region {self.region} holds {hi - lo} "
                f"tokens < window {window}")
        with np.errstate(over="ignore"):  # uint64 wraparound is the hash
            x = (np.uint64(self.seed) * np.uint64(0x9E3779B97F4A7C15)
                 + np.uint64(step) * np.uint64(0xBF58476D1CE4E5B9)
                 + np.arange(rows, dtype=np.uint64)
                 * np.uint64(0x94D049BB133111EB))
            x ^= x >> np.uint64(30)
            x *= np.uint64(0xBF58476D1CE4E5B9)
            x ^= x >> np.uint64(27)
            x *= np.uint64(0x94D049BB133111EB)
            x ^= x >> np.uint64(31)
        return (np.uint64(lo) + x % np.uint64(span + 1)).astype(np.int64)

    def batch(self, step: int, batch: int, seq: int, *,
              rows: Optional[slice] = None):
        """[rows, seq + 1] int32 windows for global step ``step``.

        ``seq + 1`` tokens per row (input + next-token target, the shape
        workloads/train.py losses expect).  ``rows`` selects this process's
        slice of the global batch (multi-host: each host materializes only
        its own rows and ``globalize_batch`` assembles the sharded global
        array); default is every row.
        """
        import numpy as np

        offs = self._offsets(step, batch, seq + 1)
        if rows is not None:
            offs = offs[rows]
        out = np.empty((len(offs), seq + 1), np.int32)
        for i, o in enumerate(offs):
            out[i] = self._tokens[o:o + seq + 1]
        return out
