"""Host->device prefetch: overlap next-batch assembly with the current step.

TPU-first rationale: a training step is MXU-bound; the host is idle while the
chip computes.  ``Prefetcher`` uses that idle time to (a) gather the next
batch's windows from the memory-mapped dataset and (b) start its DMA to HBM
(``jax.device_put`` is async), so step N+1's data is resident when step N's
``step_fn`` returns.  One background thread + a bounded handoff queue -- the
sampling is stateless (data/tokens.py), so the thread holds no state worth
checkpointing and a crashed prefetcher is rebuilt from the step number alone.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Callable

from trainingjob_operator_tpu.api import constants


_DONE = object()


def _stall_timeout() -> float:
    """Seconds of consumer wait per warning cycle before declaring the
    producer dead (TRAININGJOB_PREFETCH_STALL_S, default 300; floored at
    0.1 s -- a zero/negative value would busy-spin the consumer or crash
    queue.get)."""
    try:
        v = float(os.environ.get(constants.PREFETCH_STALL_ENV, "300")
                  or 300)
    except ValueError:
        v = 300.0
    return max(v, 0.1)


class Prefetcher:
    """Iterates ``fetch(step)`` for step = start..stop-1, one step ahead.

    ``fetch`` returns a device array (or pytree); it runs on the background
    thread, so it should end in an async ``jax.device_put``/
    ``globalize_batch`` -- NOT a blocking transfer.  Exceptions propagate to
    the consumer at the matching ``next()``.
    """

    def __init__(self, fetch: Callable[[int], Any], start: int, stop: int,
                 depth: int = 1):
        self._fetch = fetch
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(start, stop), name="prefetcher",
            daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._shutdown.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, start: int, stop: int) -> None:
        for step in range(start, stop):
            if self._shutdown.is_set():
                return
            try:
                item = (step, self._fetch(step), None)
            # analyzer: allow[broad-except]: forwarded through
            # the queue and re-raised in __next__ on the consumer.
            except BaseException as exc:  # surfaced at next()
                self._put((step, None, exc))
                return
            if not self._put(item):
                return
        self._put(_DONE)

    def __iter__(self):
        return self

    def __next__(self):
        """(step, batch) in order; raises the producer's exception, or
        StopIteration after the final step."""
        if self._shutdown.is_set():
            raise StopIteration
        # A slow-but-alive producer (cold GCS-fuse/NFS page-in of an mmap
        # window) only WARNS each cycle; the hard error is reserved for a
        # dead producer thread -- aborting un-checkpointed training over one
        # slow fetch is worse than waiting it out.
        stall = _stall_timeout()
        waited = 0.0
        while True:
            try:
                item = self._q.get(timeout=stall)
                break
            except queue.Empty:
                waited += stall
                if not self._thread.is_alive():
                    # The producer may have enqueued its final item (or
                    # _DONE) and exited between our timeout and this check:
                    # drain once before declaring it dead.
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    raise RuntimeError(
                        f"prefetcher thread died after {waited:.0f} s wait "
                        f"(dataset IO crashed?)")
                if self._shutdown.is_set():
                    raise StopIteration
                print(f"WARNING: prefetcher stalled {waited:.0f} s; producer "
                      f"thread alive, still waiting (slow dataset IO? tune "
                      f"TRAININGJOB_PREFETCH_STALL_S)", flush=True)
        if item is _DONE:
            self._thread.join(timeout=5.0)
            raise StopIteration
        step, batch, exc = item
        if exc is not None:
            self.close()
            raise exc
        return step, batch

    def close(self) -> None:
        """Stop the producer (used on preemption-triggered early exit)."""
        self._shutdown.set()
        # Drain so a blocked put() observes the shutdown flag.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
