"""Input pipeline: deterministic, elastic-friendly token loading.

The reference operator has no data layer -- feeding the model is the
in-container framework's job (SURVEY.md §0, §2.7).  The TPU build owns the
workload layer, so it owns input too, designed around the same elastic
contract as the rest of the framework:

- **Stateless sampling** (`TokenDataset.batch`): the global batch for step N
  is a pure function of (seed, step, batch, seq) -- no iterator state to
  checkpoint, and a job resumed at a different elastic width replays the
  byte-identical global batch sequence (each data shard just takes its rows
  of it).  Orbax only ever has to persist the step number.
- **Host-side prefetch** (`Prefetcher`): a background thread assembles the
  next batch and lands it on device while the current step runs, hiding
  host->HBM transfer behind MXU time (single-core TPU-VM hosts still
  overlap DMA with compute).
"""

from trainingjob_operator_tpu.data.tokens import TokenDataset, write_tokens
from trainingjob_operator_tpu.data.loader import Prefetcher

__all__ = ["TokenDataset", "write_tokens", "Prefetcher"]
