"""API group constants, labels, env-var names, and event reasons.

Reference: pkg/apis/aitrainingjob/v1/constants.go and register.go.  Group and
kind names are TPU-native; the label/env contract keeps the reference's shape
(TRAININGJOB_* identity env, {RT}_INSTANCES/PORTS/HOSTS rendezvous env) and
adds the TPU/JAX bootstrap set.
"""

# --- group/version/kind (reference: v1/register.go:27-33) -------------------
GROUP_NAME = "tpu.trainingjob.dev"
GROUP_VERSION = "v1"
KIND = "TPUTrainingJob"
KIND_PLURAL = "tputrainingjobs"
SHORT_NAME = "tpujob"
API_VERSION = f"{GROUP_NAME}/{GROUP_VERSION}"

CONTROLLER_NAME = "TPUTrainingJobOperator"

# --- labels (reference: constants.go:3-11) ----------------------------------
REPLICA_NAME_LABEL = "TrainingJobReplicaName"
REPLICA_INDEX_LABEL = "TrainingJobReplicaIndex"
JOB_NAME_LABEL = "TrainingJobName"
FRAMEWORK_LABEL = "FrameworkType"
GROUP_NAME_LABEL = "GroupName"
PRIORITY_LABEL = "priority"
RESTART_COUNT_LABEL = "RestartCount"
POD_ROLE_LABEL = "PodRole"
# TPU extensions
SLICE_ID_LABEL = "TPUSliceID"
GANG_LABEL = "TPUGang"
# Node-side failure-domain topology label (sim/fleet nodes): every node of
# one physical slice carries the same value, so a domain-correlated fault
# (fleet/chaos.py node_faults kind=domain_down) downs them together --
# pods of a gang share fate with their interconnect, not just their host.
NODE_SLICE_LABEL = "tpu.trainingjob.dev/slice"
# Declared member count of the gang: schedulers must not place a gang they
# have only partially observed (pods of one slice are created over several
# API calls; placing the visible subset first-come steals its capacity).
GANG_SIZE_LABEL = "TPUGangSize"

# Name of the informer secondary index mapping a pod/service to its owning
# job ("ns/jobname" from the GroupName+TrainingJobName label pair --
# controller.job_index_key).  An indexed lookup is O(job's objects); the
# lister list it replaces deepcopied the whole store per reconcile.
JOB_INDEX = "by-job"

# Informer secondary index mapping a pod to the node it is placed on.  Node
# readiness transitions use it to reconcile exactly the affected jobs
# (O(pods-on-node)) instead of waiting out a resync period.
NODE_INDEX = "by-node"

# --- identity env vars injected into every container
# (reference: constants.go:13-21, pkg/controller/pod.go:600-628) -------------
REPLICA_NAME_ENV = "TRAININGJOB_REPLICA_NAME"
REPLICA_INDEX_ENV = "TRAININGJOB_REPLICA_INDEX"
REPLICA_RESTART_COUNT_ENV = "TRAININGJOB_REPLICA_RESTARTCOUNT"
JOB_NAME_ENV = "TRAININGJOB_NAME"
JOB_NAMESPACE_ENV = "TRAININGJOB_NAMESPACE"
SERVICE_ENV = "TRAININGJOB_SERVICE"
PORTS_ENV = "TRAININGJOB_PORTS"
# TPU/JAX bootstrap env (new; the TPU-native "communication backend" contract:
# SURVEY.md §5.8 -- worker identity + coordinator address for
# jax.distributed.initialize, slice topology for mesh construction)
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
TPU_ACCELERATOR_ENV = "TRAININGJOB_TPU_ACCELERATOR"
TPU_TOPOLOGY_ENV = "TRAININGJOB_TPU_TOPOLOGY"
COORDINATOR_ADDRESS_ENV = "TRAININGJOB_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV = "TRAININGJOB_NUM_PROCESSES"
PROCESS_ID_ENV = "TRAININGJOB_PROCESS_ID"
SLICE_ID_ENV = "MEGASCALE_SLICE_ID"
NUM_SLICES_ENV = "MEGASCALE_NUM_SLICES"
MEGASCALE_COORDINATOR_ENV = "MEGASCALE_COORDINATOR_ADDRESS"
CHECKPOINT_DIR_ENV = "TRAININGJOB_CHECKPOINT_DIR"
ELASTIC_REPLICAS_ENV = "TRAININGJOB_ELASTIC_REPLICAS"
# Set to "1" on re-expand reservation pods: the workload must idle (capacity
# canary), not join the (full) rendezvous -- it is restarted with a real rank
# once the resize commits.
RESERVATION_ENV = "TRAININGJOB_RESERVATION"
# Seconds before an orphaned reservation canary self-expires (exit 143) so a
# dead controller's probe cannot pin a TPU host forever.
RESERVATION_TTL_ENV = "TRAININGJOB_RESERVATION_TTL"
# Persistent XLA compilation cache dir ("off" disables).  Defaults to a
# subdir of the checkpoint dir so a restarted worker skips recompilation --
# the dominant term in elastic-recovery latency.
COMPILE_CACHE_ENV = "TRAININGJOB_COMPILE_CACHE"
# Job-survivable compile-cache location (takes precedence over
# TRAININGJOB_COMPILE_CACHE): point it at storage that outlives the
# checkpoint dir (e.g. a per-cluster NFS path) so a RESCHEDULED job -- new
# checkpoint dir and all -- still warm-starts its XLA compile.  "off"
# disables, like the legacy knob.
COMPILE_CACHE_DIR_ENV = "TRAININGJOB_COMPILE_CACHE_DIR"
# "0" disables the overlapped resume path (workloads/train.py
# overlapped_restore): restore and the warm XLA compile then run serially,
# each still timed -- the A/B leg bench.py's time_to_resume_training keys on.
RESUME_OVERLAP_ENV = "TRAININGJOB_RESUME_OVERLAP"
# "0" disables snapshot-donate checkpointing (workloads/train.py
# CheckpointState.save): the step loop then hands live jax.Arrays straight
# to orbax (the legacy synchronous handoff), paying device-sync +
# serialization setup in the step instead of one device->host copy.
CKPT_SNAPSHOT_ENV = "TRAININGJOB_CKPT_SNAPSHOT"
# Workload-side profiler (SURVEY.md §5.1): directory to write a
# jax.profiler trace into, and the "start:stop" step range to trace.
PROFILE_DIR_ENV = "TRAININGJOB_PROFILE_DIR"
PROFILE_STEPS_ENV = "TRAININGJOB_PROFILE_STEPS"
# "1" -> log per-step wall time (diagnosable throughput, not one scalar).
STEP_TIMES_ENV = "TRAININGJOB_STEP_TIMES"
# Which runtime launched the workload process ("localproc", "kube", "sim");
# injected so a workload can adapt (e.g. skip node-local tmpfs on sim).
RUNTIME_ENV = "TRAININGJOB_RUNTIME"
# Per-replica-group JAX platform override (e.g. "cpu" so CPU groups on a TPU
# host don't claim the chip); read by workloads/rendezvous.py.
JAX_PLATFORM_ENV = "TRAININGJOB_JAX_PLATFORM"
# Trace context handed to workloads rendezvous-style ("trace_id:span_id"):
# the workload's root span adopts it so one trace id spans controller,
# runtime, and train loop (obs/trace.py).  Absent -> workload tracing is a
# no-op fast path.
TRACE_CONTEXT_ENV = "TRAININGJOB_TRACE_CONTEXT"
# Telemetry sink address ("host:port"), injected rendezvous-style like the
# trace context: when set, the workload's StepProfiler pushes one JSON line
# per completed step (obs/telemetry.py wire protocol) back to the runtime's
# controller-side aggregator.  Absent -> per-step telemetry is a no-op.
TELEMETRY_ADDR_ENV = "TRAININGJOB_TELEMETRY_ADDR"
# MFU accounting overrides (obs/telemetry.py): model FLOPs per optimizer
# step, and the aggregate peak FLOP/s of the chips the replica drives.  Both
# are normally computed (workload config / spec.tpu topology) -- the env
# vars exist so a template can pin the numbers for odd models.
MODEL_FLOPS_ENV = "TRAININGJOB_MODEL_FLOPS_PER_STEP"
PEAK_FLOPS_ENV = "TRAININGJOB_PEAK_FLOPS"
# "1" -> workload processes emit structured JSON log lines (obs/logs.py),
# mirroring the operator's --log-json; step records then carry trace ids.
LOG_JSON_ENV = "TRAININGJOB_LOG_JSON"
# Directory the workload writes its finished trace into on shutdown
# (Chrome trace_event JSON, one file per process); unset -> no export.
TRACE_DIR_ENV = "TRAININGJOB_TRACE_DIR"
# "1"/"true" opts back in to the Shardy partitioner (default: classic GSPMD;
# rationale in workloads/rendezvous.py configure_partitioner).
SHARDY_ENV = "TRAININGJOB_SHARDY"
# Virtual multislice geometry for platforms without a slice notion (CPU test
# meshes): device.id // k becomes the slice id, letting the DCN-aware paths
# run end-to-end on a forced-host-device mesh.
VIRTUAL_DEVICES_PER_SLICE_ENV = "TRAININGJOB_VIRTUAL_DEVICES_PER_SLICE"
# Pallas kernel selection for ops/ ("auto"/"force"/"off"/"interpret"; see
# ops.use_pallas) and flash-attention block-size overrides for odd shapes.
# Fleet churn-harness defaults (fleet/harness.py CLI, `make fleet-smoke`):
# the seed feeding the deterministic churn generator and the number of jobs
# driven.  User-set, never injected into containers.
FLEET_SEED_ENV = "TRAININGJOB_FLEET_SEED"
FLEET_JOBS_ENV = "TRAININGJOB_FLEET_JOBS"
# Sim kubelet kernel (runtime/sim.py): "event" (default; discrete-event
# timer queue, O(events)) or "scan" (the original fixed-cadence pod walk,
# kept as the A/B baseline and escape hatch).  User-set, never injected.
SIM_KERNEL_ENV = "TRAININGJOB_SIM_KERNEL"
# Control-plane chaos plane (fleet/chaos.py + client/chaos.py): the seed
# feeding the deterministic fault-schedule generator for `--chaos` harness
# runs and `make chaos-smoke`.  User-set, never injected.
CHAOS_SEED_ENV = "TRAININGJOB_CHAOS_SEED"
# Bounded-retry budget for controller API writes (client/retry.py
# default_policy; attempts, clamped to [1, 16]; 1 disables retry).
API_RETRIES_ENV = "TRAININGJOB_API_RETRIES"
# Sync-loop failure quarantine (cmd/options.py -> workqueue): consecutive
# failed syncs before a key is parked (0 disables), and how long it parks.
QUARANTINE_AFTER_ENV = "TRAININGJOB_QUARANTINE_AFTER"
QUARANTINE_DELAY_ENV = "TRAININGJOB_QUARANTINE_S"
# Node-flap damping (controller/pod.py get_node_status): seconds a node must
# stay NotReady before the controller treats it as failed.  Inside the grace
# the node still counts as ready, so NODE_FAIL teardown, elastic shrink and
# resize keepalive are all uniformly debounced -- a flap storm costs one
# grace window, not a restart storm.  0 (default) disables damping.
NODE_FLAP_GRACE_ENV = "TRAININGJOB_NODE_FLAP_GRACE_S"
# Crash-loop quarantine (controller/pod.py _restart_pods): a replica group
# whose restarts keep failing within CRASHLOOP_WINDOW_S of each other is
# parked after CRASHLOOP_AFTER consecutive fast failures, retrying at a
# flat CRASHLOOP_DELAY_S cadence (one CrashLoopQuarantined event per
# episode) until a run survives past the window.  AFTER=0 (default)
# disables quarantine.
CRASHLOOP_AFTER_ENV = "TRAININGJOB_CRASHLOOP_AFTER"
CRASHLOOP_WINDOW_ENV = "TRAININGJOB_CRASHLOOP_WINDOW_S"
CRASHLOOP_DELAY_ENV = "TRAININGJOB_CRASHLOOP_DELAY_S"
# Deterministic checkpoint-fault injection (workloads/train.py):
# "resume_image" corrupts the flat resume image's bytes at read (the sha256
# footer must catch it and classify the fallback as corrupt);
# "corrupt_latest" makes the latest-step orbax restore raise, driving the
# fallback ladder down to the previous committed step (max_to_keep=2
# retains it).  Unset (default) injects nothing.
CKPT_FAULT_ENV = "TRAININGJOB_CKPT_FAULT"
PALLAS_ENV = "TRAININGJOB_PALLAS"
FA_BLOCK_Q_ENV = "TRAININGJOB_FA_BLOCK_Q"
FA_BLOCK_K_ENV = "TRAININGJOB_FA_BLOCK_K"
# Seconds without a produced batch before the prefetching loader declares the
# producer dead (data/loader.py watchdog).
PREFETCH_STALL_ENV = "TRAININGJOB_PREFETCH_STALL_S"
# Incident flight recorder (obs/incident.py): per-job timeline ring length
# (events and step records each) and how many assembled incident bundles are
# retained per job.  Both bound memory -- a crash-looping job keeps its last
# K incidents, never an unbounded history.
INCIDENT_RING_ENV = "TRAININGJOB_INCIDENT_RING"
INCIDENT_BUNDLES_ENV = "TRAININGJOB_INCIDENT_BUNDLES"
# Workload-side HBM sampler (workloads/train.py StepProfiler): sample device
# memory every N steps and ride it on the telemetry record as ``hbm_bytes``
# (OOM-shaped incidents then carry a memory timeline).  "0" disables.
HBM_SAMPLE_STEPS_ENV = "TRAININGJOB_HBM_SAMPLE_STEPS"
# Elastic-resize fast path (docs/ELASTIC.md).  RESIZE_DIR_ENV is the
# generation channel: a directory (shared volume / NFS in a real cluster,
# a host path under the sim/localproc runtimes) into which the controller
# atomically publishes ``generation.json`` -- the bumped rendezvous
# generation, new world size, and surviving host list -- when a
# scope=Resize drain completes.  Surviving workload processes watch the
# file from the step loop and re-form the mesh in place.
RESIZE_DIR_ENV = "TRAININGJOB_RESIZE_DIR"
# The rendezvous generation a pod was created under; the workload reacts
# only to published generations strictly greater than its birth epoch.
RENDEZVOUS_GENERATION_ENV = "TRAININGJOB_RENDEZVOUS_GENERATION"
# Seconds between generation-file polls in the workload step loop.
RESIZE_POLL_ENV = "TRAININGJOB_RESIZE_POLL_S"
# "0" disables the in-process reshard fast path: a resize signal then
# checkpoints and exits 143 (the restart-the-world A/B baseline that
# bench.py's elastic_resize leg measures against).
RESIZE_FASTPATH_ENV = "TRAININGJOB_RESIZE_FASTPATH"
# Live multi-host re-rendezvous (docs/ELASTIC.md "Live re-rendezvous").
# "0" disables the coordinator-rebootstrap path for multi-process jobs: a
# resize signal then degrades straight to the checkpoint rung -- the
# live-vs-checkpoint A/B baseline bench.py's elastic_resize leg measures.
RESIZE_LIVE_ENV = "TRAININGJOB_RESIZE_LIVE"
# Seconds a survivor waits for the bumped-generation coordinator to accept
# connections before the barrier phase times out and the rebootstrap
# ladder degrades one rung (checkpoint+restart).  Probes back off
# exponentially inside this budget.
RESIZE_BARRIER_ENV = "TRAININGJOB_RESIZE_BARRIER_S"
# Deterministic fault injection for the rebootstrap ladder
# (workloads/rendezvous.py): a comma-separated list of phase names
# (shutdown|barrier|reinit|reshard|persist), each optionally pinned to one
# generation as ``phase@N``.  A listed phase raises an injected fault at
# that point, forcing the documented fallback rung -- tests and
# ``make resize-smoke`` drive every rung this way.
RESIZE_FAULT_ENV = "TRAININGJOB_RESIZE_FAULT"
# Serving plane (workloads/serve.py, docs/SERVING.md).  Decode-batch slot
# count (the continuous-batching batch axis), cache length override, prompt
# prefill chunk size, bounded admission-queue capacity (QueueFull past it),
# open-loop synthetic arrival rate (mean requests per scheduler tick),
# total synthetic requests (0 = serve forever), and "1" for weight-only
# int8 decode.
SERVE_SLOTS_ENV = "TRAININGJOB_SERVE_SLOTS"
SERVE_MAX_LEN_ENV = "TRAININGJOB_SERVE_MAX_LEN"
SERVE_PREFILL_CHUNK_ENV = "TRAININGJOB_SERVE_PREFILL_CHUNK"
SERVE_QUEUE_CAP_ENV = "TRAININGJOB_SERVE_QUEUE_CAP"
SERVE_RATE_ENV = "TRAININGJOB_SERVE_RATE"
SERVE_REQUESTS_ENV = "TRAININGJOB_SERVE_REQUESTS"
SERVE_QUANT_ENV = "TRAININGJOB_SERVE_QUANT"
# Traffic-aware serve scale policy (controller/pod.py _maybe_scale_serve):
# queue depth that triggers scale-out, the depth below which an idle serve
# replica scales back in, and the per-job cooldown seconds between scaling
# actions (damps flapping on bursty arrivals).
SERVE_SCALE_UP_QUEUE_ENV = "TRAININGJOB_SERVE_SCALE_UP_QUEUE"
SERVE_SCALE_DOWN_QUEUE_ENV = "TRAININGJOB_SERVE_SCALE_DOWN_QUEUE"
SERVE_SCALE_COOLDOWN_ENV = "TRAININGJOB_SERVE_SCALE_COOLDOWN_S"

# --- Fleet SLO plane (obs/tsdb.py, obs/slo.py, obs/profiler.py) -------------
# In-process time-series store: snapshot cadence (seconds), ring length
# (points retained per series), and the series-cardinality cap past which
# new label sets are rejected -- counted via
# trainingjob_tsdb_series_dropped_total, never silently.
TSDB_INTERVAL_ENV = "TRAININGJOB_TSDB_INTERVAL_S"
TSDB_POINTS_ENV = "TRAININGJOB_TSDB_POINTS"
TSDB_MAX_SERIES_ENV = "TRAININGJOB_TSDB_MAX_SERIES"
# Burn-rate engine (docs/SLO.md): evaluation cadence, the "short:long"
# alerting-window pair (seconds, multi-window multi-burn-rate style), the
# burn-rate threshold both windows must exceed before a breach fires, and
# per-objective thresholds for the built-in SLO inventory.
SLO_EVAL_ENV = "TRAININGJOB_SLO_EVAL_S"
SLO_WINDOWS_ENV = "TRAININGJOB_SLO_WINDOWS"
SLO_BURN_ENV = "TRAININGJOB_SLO_BURN"
SLO_EVENT_P99_MS_ENV = "TRAININGJOB_SLO_EVENT_P99_MS"
SLO_RESTART_P99_S_ENV = "TRAININGJOB_SLO_RESTART_P99_S"
SLO_GOODPUT_FLOOR_ENV = "TRAININGJOB_SLO_GOODPUT_FLOOR"
SLO_SERVE_P99_MS_ENV = "TRAININGJOB_SLO_SERVE_P99_MS"
SLO_TTFT_P99_MS_ENV = "TRAININGJOB_SLO_TTFT_P99_MS"
# Sampling stack profiler: base sampling interval (milliseconds; each
# actual gap is jittered off a seeded random.Random so samples don't alias
# the controller's periodic loops) and the jitter seed.  Distinct names
# from TRAININGJOB_PROFILE_DIR/STEPS above -- those drive the *workload*
# jax.profiler; these drive the in-operator span profiler.
PROFILE_INTERVAL_MS_ENV = "TRAININGJOB_PROFILE_INTERVAL_MS"
PROFILE_SEED_ENV = "TRAININGJOB_PROFILE_SEED"

# --- Request-lifecycle plane (obs/reqtrace.py, docs/SERVING.md) -------------
# Tail-sampling retention: full spans kept per job (the slowest-k ring --
# the rest drop with trainingjob_reqtrace_sampled_dropped_total, never
# silently) and the bounded recent window feeding incident overlap
# queries and TTFT/TPOT percentiles.
REQTRACE_RING_ENV = "TRAININGJOB_REQTRACE_RING"
REQTRACE_WINDOW_ENV = "TRAININGJOB_REQTRACE_WINDOW"

#: Env vars that are part of the contract but *user-set* (pod template or
#: operator environment), never injected by the controller: workload tuning
#: knobs.  TJA011 env-contract treats membership here as the injection
#: evidence -- a contract var in neither an injection site nor this set is
#: dead surface.
USER_ENV_KNOBS = frozenset((
    COMPILE_CACHE_ENV,
    COMPILE_CACHE_DIR_ENV,
    RESUME_OVERLAP_ENV,
    CKPT_SNAPSHOT_ENV,
    PROFILE_DIR_ENV,
    PROFILE_STEPS_ENV,
    STEP_TIMES_ENV,
    JAX_PLATFORM_ENV,
    MODEL_FLOPS_ENV,
    PEAK_FLOPS_ENV,
    LOG_JSON_ENV,
    TRACE_DIR_ENV,
    SHARDY_ENV,
    VIRTUAL_DEVICES_PER_SLICE_ENV,
    PALLAS_ENV,
    FA_BLOCK_Q_ENV,
    FA_BLOCK_K_ENV,
    PREFETCH_STALL_ENV,
    FLEET_SEED_ENV,
    FLEET_JOBS_ENV,
    SIM_KERNEL_ENV,
    CHAOS_SEED_ENV,
    API_RETRIES_ENV,
    QUARANTINE_AFTER_ENV,
    QUARANTINE_DELAY_ENV,
    NODE_FLAP_GRACE_ENV,
    CRASHLOOP_AFTER_ENV,
    CRASHLOOP_WINDOW_ENV,
    CRASHLOOP_DELAY_ENV,
    CKPT_FAULT_ENV,
    INCIDENT_RING_ENV,
    INCIDENT_BUNDLES_ENV,
    HBM_SAMPLE_STEPS_ENV,
    RESIZE_POLL_ENV,
    RESIZE_FASTPATH_ENV,
    RESIZE_LIVE_ENV,
    RESIZE_BARRIER_ENV,
    RESIZE_FAULT_ENV,
    SERVE_SLOTS_ENV,
    SERVE_MAX_LEN_ENV,
    SERVE_PREFILL_CHUNK_ENV,
    SERVE_QUEUE_CAP_ENV,
    SERVE_RATE_ENV,
    SERVE_REQUESTS_ENV,
    SERVE_QUANT_ENV,
    SERVE_SCALE_UP_QUEUE_ENV,
    SERVE_SCALE_DOWN_QUEUE_ENV,
    SERVE_SCALE_COOLDOWN_ENV,
    TSDB_INTERVAL_ENV,
    TSDB_POINTS_ENV,
    TSDB_MAX_SERIES_ENV,
    SLO_EVAL_ENV,
    SLO_WINDOWS_ENV,
    SLO_BURN_ENV,
    SLO_EVENT_P99_MS_ENV,
    SLO_RESTART_P99_S_ENV,
    SLO_GOODPUT_FLOOR_ENV,
    SLO_SERVE_P99_MS_ENV,
    SLO_TTFT_P99_MS_ENV,
    PROFILE_INTERVAL_MS_ENV,
    PROFILE_SEED_ENV,
    REQTRACE_RING_ENV,
    REQTRACE_WINDOW_ENV,
))

#: Env vars the controller injects for consumers *outside* this codebase --
#: libtpu/XLA read the TPU_WORKER_* pair and the MEGASCALE_* coordinator,
#: and TRAININGJOB_PORTS is the reference operator's contract with arbitrary
#: framework entrypoints.  TJA011 treats membership here as read evidence.
EXTERNAL_CONSUMER_ENV = frozenset((
    TPU_WORKER_ID_ENV,
    TPU_WORKER_HOSTNAMES_ENV,
    MEGASCALE_COORDINATOR_ENV,
    PORTS_ENV,
    # Injected for *user* workloads to adapt to the launching runtime; the
    # bundled workloads don't need it (they are runtime-agnostic).
    RUNTIME_ENV,
))

# --- GKE TPU node selectors / resources (north star: BASELINE.json) ---------
GKE_TPU_ACCELERATOR_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_SELECTOR = "cloud.google.com/gke-tpu-topology"
GKE_SPOT_SELECTOR = "cloud.google.com/gke-spot"
TPU_RESOURCE = "google.com/tpu"

# --- container/port name convention (reference: constants.go:41-44) ---------
CONTAINER_PREFIX = "aitj-"
PORT_PREFIX = "aitj-"
DEFAULT_COORDINATOR_PORT = 8476

# --- event reasons (reference: constants.go:23-39) --------------------------
# Every reason ever passed to EventRecorder.event() is declared here and
# listed in EVENT_REASONS below -- the registry tools/analyze TJA007 checks
# call sites against (an ad-hoc reason string is invisible to dashboards and
# `kubectl get events --field-selector reason=...` filters).
POD_TEMPLATE_RESTART_POLICY_REASON = "SettedPodTemplateRestartPolicy"
EXITED_WITH_CODE_REASON = "ExitedWithCode"

PENDING_REASON = "TrainingJobPending"
CREATING_REASON = "TrainingJobCreating"
RUNNING_REASON = "TrainingJobRunning"
SUCCEEDED_REASON = "TrainingJobSucceed"
FAILED_REASON = "TrainingJobFailed"
TIMEOUT_REASON = "TrainingJobTimeout"
RESTARTING_REASON = "TrainingJobRestarting"
TERMINATING_REASON = "TrainingJobTerminating"
PREEMPTED_REASON = "TrainingJobPreempted"
NODE_FAIL_REASON = "TrainingJobNodeFail"
SCALING_REASON = "TrainingJobScaling"  # TPU extension: elastic resize

# Elastic-resize fast path reasons (scope Resize, docs/ELASTIC.md):
# ResizeStarted marks the survivor-keepalive drain opening (only failed
# pods deleted), ReshardCompleted the generation republish once the drain
# converges, ReshardFellBack the downgrade to the restart-the-world path
# (survivors below the group's min width, so no quorum to reshard from).
RESIZE_STARTED_REASON = "ResizeStarted"
RESHARD_COMPLETED_REASON = "ReshardCompleted"
RESHARD_FELL_BACK_REASON = "ReshardFellBack"
# ResizePublishFailed: the atomic generation publish exhausted its retry
# budget -- survivors are polling for a doc that never arrived, so the
# resize is wedged on the channel, not on the workload.
RESIZE_PUBLISH_FAILED_REASON = "ResizePublishFailed"
# SyncQuarantined: a job key failed N consecutive reconciles and was parked
# in the workqueue quarantine -- it will be retried on a slow flat cadence
# instead of the exponential ladder, and one successful sync releases it.
SYNC_QUARANTINED_REASON = "SyncQuarantined"
# Node-flap damping (docs/CHAOS.md data plane): a job's pod sits on a node
# that went NotReady but is still inside TRAININGJOB_NODE_FLAP_GRACE_S --
# NODE_FAIL is suppressed for the rest of the grace window (one event per
# flap episode; the node recovering inside the window costs nothing).
NODE_FLAP_SUPPRESSED_REASON = "NodeFlapSuppressed"
# Crash-loop quarantine (docs/CHAOS.md): a replica group's restarts kept
# failing fast, so the restart machinery parked it at a flat retry cadence
# (Quarantined, once per episode) until a clean run releases it (Released).
CRASHLOOP_QUARANTINED_REASON = "CrashLoopQuarantined"
CRASHLOOP_RELEASED_REASON = "CrashLoopReleased"

# Telemetry-plane reasons (obs/telemetry.py watchdog): a replica's step
# counter stopped advancing for N x its median step time / started moving
# again.  Events, not phase transitions -- a stalled replica is still
# Running as far as the kubelet knows; that is exactly why pod phase alone
# cannot see it.
STEP_STALLED_REASON = "StepStalled"
STEP_RESUMED_REASON = "StepResumed"

# Incident flight recorder (obs/incident.py): an incident bundle was
# assembled for the job -- the event message names the bundle id and its
# phase-attributed downtime so `kubectl get events` points straight at
# /debug/incidents.
INCIDENT_RECORDED_REASON = "IncidentRecorded"

# Fleet SLO plane (obs/slo.py): a declared objective's burn rate crossed
# its threshold in both alerting windows (SLOBreach) / the short window's
# burn dropped back to zero (SLORecovered).  Fleet-scoped -- recorded
# against a synthetic FleetSLO object, not any one job, so per-job event
# streams are not polluted by fleet-wide verdicts.
SLO_BREACH_REASON = "SLOBreach"
SLO_RECOVERED_REASON = "SLORecovered"

# Action-trail reasons (previously inline literals at call sites).
VALIDATION_FAILED_REASON = "ValidationFailed"
SUCCESSFUL_CREATE_POD_REASON = "SuccessfulCreatePod"
SUCCESSFUL_DELETE_POD_REASON = "SuccessfulDeletePod"
SUCCESSFUL_CREATE_SERVICE_REASON = "SuccessfulCreateService"
SUCCESSFUL_DELETE_SERVICE_REASON = "SuccessfulDeleteService"

#: The registry: the closed set of reasons recorder.event() may emit.
EVENT_REASONS = frozenset((
    POD_TEMPLATE_RESTART_POLICY_REASON,
    EXITED_WITH_CODE_REASON,
    PENDING_REASON,
    CREATING_REASON,
    RUNNING_REASON,
    SUCCEEDED_REASON,
    FAILED_REASON,
    TIMEOUT_REASON,
    RESTARTING_REASON,
    TERMINATING_REASON,
    PREEMPTED_REASON,
    NODE_FAIL_REASON,
    SCALING_REASON,
    RESIZE_STARTED_REASON,
    RESHARD_COMPLETED_REASON,
    RESHARD_FELL_BACK_REASON,
    RESIZE_PUBLISH_FAILED_REASON,
    SYNC_QUARANTINED_REASON,
    NODE_FLAP_SUPPRESSED_REASON,
    CRASHLOOP_QUARANTINED_REASON,
    CRASHLOOP_RELEASED_REASON,
    STEP_STALLED_REASON,
    STEP_RESUMED_REASON,
    INCIDENT_RECORDED_REASON,
    SLO_BREACH_REASON,
    SLO_RECOVERED_REASON,
    VALIDATION_FAILED_REASON,
    SUCCESSFUL_CREATE_POD_REASON,
    SUCCESSFUL_DELETE_POD_REASON,
    SUCCESSFUL_CREATE_SERVICE_REASON,
    SUCCESSFUL_DELETE_SERVICE_REASON,
))

# --- legal phase transitions (TJA013 phase-transition-exhaustiveness) -------
# The phase state machine, declared: source phase -> phases the status
# machine may move it to.  Spellings match api/types.py TrainingJobPhase
# (this module cannot import types.py -- types.py imports it).  Same-phase
# refreshes are always legal and not listed.  Ending phases are terminal
# (update_job_conditions' is_job_completed guard enforces it at runtime;
# the analyzer enforces it at lint time).
PHASE_TRANSITIONS = {
    "": ("Pending", "Creating", "Running", "Terminating", "Failed"),
    "Pending": ("Creating", "Running", "Scaling", "Restarting", "Terminating",
                "Failed", "Timeout", "Preempted", "NodeFail"),
    "Creating": ("Pending", "Running", "Scaling", "Restarting", "Terminating",
                 "Succeed", "Failed", "Timeout", "Preempted", "NodeFail"),
    "Running": ("Pending", "Creating", "Scaling", "Restarting", "Terminating",
                "Succeed", "Failed", "Timeout", "Preempted", "NodeFail"),
    "Restarting": ("Pending", "Creating", "Running", "Scaling", "Terminating",
                   "Failed", "Timeout", "Preempted", "NodeFail"),
    "Scaling": ("Pending", "Creating", "Running", "Restarting", "Terminating",
                "Succeed", "Failed", "Timeout", "Preempted", "NodeFail"),
    "Terminating": ("Succeed", "Failed", "Timeout", "Preempted", "NodeFail"),
    "Succeed": (),
    "Failed": (),
    "Timeout": (),
    "Preempted": (),
    "NodeFail": (),
}

# --- fatal container-waiting reasons (reference: constants.go:46-56) --------
ERROR_CONTAINER_STATUS = (
    "CreateContainerConfigError",
    "CreateContainerError",
    "ImagePullBackOff",
    "ImageInspectError",
    "ErrImagePull",
    "ErrImageNeverPull",
    "RegistryUnavailable",
    "InvalidImageName",
)

# --- shard-state inventory (TJA027 shard-state-discipline) ------------------
# Every module-level mutable singleton in the package, classified for the
# horizontal controller scale-out (ROADMAP item 3).  The analyzer derives
# the singleton universe from the ASTs (container displays/constructors and
# project-class constructions at module level) and holds it against this
# registry: unclassified state is an error, stale entries are errors, and
# a witnessed mutation of a ``constant`` entry is an error at the write
# site.  ``python -m tools.analyze --report shard-state`` emits the full
# machine-readable inventory (docs/STATIC_ANALYSIS.md).
#
# Classifications:
#   constant            -- built at import, never mutated; shards may each
#                          hold a copy with no coordination.
#   shard_local         -- keyed by job (or another shardable key): each
#                          shard owning its keys' slice keeps the truth
#                          intact.  Safe to scale out as-is.
#   lock_guarded_shared -- one copy per process, threads coordinate via a
#                          witnessed lock.  Safe per process; a scale-out
#                          gets one per shard (acceptable for metrics/
#                          traces, which scrape per-process anyway).
#   shard_hostile       -- semantics assume a single global writer over
#                          the whole keyspace; splitting the keyspace
#                          splits the truth.  The scale-out worklist.
SHARD_STATE_CONSTANT = "constant"
SHARD_STATE_LOCAL = "shard_local"
SHARD_STATE_LOCK_GUARDED = "lock_guarded_shared"
SHARD_STATE_HOSTILE = "shard_hostile"

SHARD_STATE_REGISTRY = {
    # Import-time tables, never written after construction (the registry
    # classifies itself: it is a module-level dict too).
    "api.constants.SHARD_STATE_REGISTRY": SHARD_STATE_CONSTANT,
    "api.constants.PHASE_TRANSITIONS": SHARD_STATE_CONSTANT,
    "api.types.PHASE_REASON": SHARD_STATE_CONSTANT,
    "client.kube.KINDS": SHARD_STATE_CONSTANT,
    "data.tokens._DTYPES": SHARD_STATE_CONSTANT,
    "data.tokens._CODES": SHARD_STATE_CONSTANT,
    "fleet.harness._SETTLED_PHASES": SHARD_STATE_CONSTANT,
    "models.bert.SHARDING_RULES": SHARD_STATE_CONSTANT,
    "models.moe.SHARDING_RULES": SHARD_STATE_CONSTANT,
    "models.resnet.SHARDING_RULES": SHARD_STATE_CONSTANT,
    "obs.trace.NOOP_SPAN": SHARD_STATE_CONSTANT,
    # Per-job keyed recorders: each controller shard owning its jobs'
    # slice keeps incident rings / goodput ledgers / telemetry coherent.
    "obs.incident.INCIDENTS": SHARD_STATE_LOCAL,
    "obs.goodput.GOODPUT": SHARD_STATE_LOCAL,
    "obs.telemetry.TELEMETRY": SHARD_STATE_LOCAL,
    # Request ledger: keyed by job like the incident recorder -- a shard
    # owning a job's serve replicas owns its whole request audit.
    "obs.reqtrace.REQTRACE": SHARD_STATE_LOCAL,
    # Process-wide, lock-coordinated: one per shard is the correct shape
    # (metrics and traces are scraped per process; the sink address and
    # port cursor are process-scoped by construction).
    "obs.trace.TRACER": SHARD_STATE_LOCK_GUARDED,
    "utils.metrics.METRICS": SHARD_STATE_LOCK_GUARDED,
    # SLO plane (docs/SLO.md): the tsdb samples the process-local METRICS
    # registry, the burn-rate engine reads the process-local tsdb, and the
    # profiler samples the process's own threads -- one instance per shard
    # is the correct shape, coordinated by their own locks.
    "obs.tsdb.TSDB": SHARD_STATE_LOCK_GUARDED,
    "obs.slo.SLOS": SHARD_STATE_LOCK_GUARDED,
    "obs.profiler.PROFILER": SHARD_STATE_LOCK_GUARDED,
    # Profiler's active-span map: thread ident -> innermost open Span.
    # Each thread writes only its own key (GIL-atomic dict ops), the same
    # per-thread locality the tracer's contextvar gives -- shard-local by
    # thread, not cross-shard state.
    "obs.trace._THREAD_SPANS": SHARD_STATE_LOCAL,
    "obs.telemetry._published": SHARD_STATE_LOCK_GUARDED,
    "runtime.localproc._port_cursor": SHARD_STATE_LOCK_GUARDED,
    # The event sequencer total-orders events per shard: lock-guarded
    # (epoch, shard, seq) keys, so a sharded fleet's merged stream sorts
    # without cross-shard coordination.  Retired the registry's last
    # shard_hostile entry (a bare itertools.count): ROADMAP item 3's
    # first refactor target, closed by the EventSeq API.
    "utils.events.EVENT_SEQ": SHARD_STATE_LOCK_GUARDED,
}
