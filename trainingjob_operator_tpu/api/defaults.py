"""Defaulting for TPUTrainingJob.

Reference: pkg/apis/aitrainingjob/v1/defaults.go:15-53, applied at sync time
(controller.go:297).  Same defaults, plus elastic and TPU defaults.
"""

from __future__ import annotations

from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    EndingPolicy,
    EdlPolicy,
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
)


def set_default_replica(spec: ReplicaSpec) -> None:
    """Reference: defaults.go:15-31."""
    if spec.replicas is None:
        if spec.tpu is not None and spec.tpu.topology:
            # TPU groups default to the slice geometry: one pod per TPU-VM
            # host across slice_count slices.
            from trainingjob_operator_tpu.api.tpu import total_hosts

            try:
                spec.replicas = total_hosts(spec.tpu)
            except ValueError:
                spec.replicas = 1
        elif spec.min_replicas is not None:
            # An elastic spec may give only a [min, max] range; start at min.
            spec.replicas = spec.min_replicas
        else:
            # Reference defaults a missing Replicas to 1 (defaults.go:16-18).
            spec.replicas = 1
    if not spec.restart_policy:
        spec.restart_policy = RestartPolicy.NEVER
    if not spec.restart_scope:
        spec.restart_scope = RestartScope.ALL
    if not spec.fail_policy:
        spec.fail_policy = EndingPolicy.ANY
    if not spec.complete_policy:
        spec.complete_policy = EndingPolicy.ALL
    # Elastic defaults (new): min/max default to the fixed width; edl policy
    # defaults to Never so behavior matches the reference unless opted in.
    if spec.min_replicas is None:
        spec.min_replicas = spec.replicas
    if spec.max_replicas is None:
        spec.max_replicas = max(spec.replicas, spec.min_replicas)
    if not spec.edl_policy:
        spec.edl_policy = EdlPolicy.NEVER
    if spec.tpu is not None and spec.tpu.slice_count < 1:
        spec.tpu.slice_count = 1


def set_defaults(job: TPUTrainingJob) -> TPUTrainingJob:
    """Reference: SetDefaults_AITrainingJob, defaults.go:34-53.  Mutates and
    returns the job."""
    if job.spec.clean_pod_policy is None:
        job.spec.clean_pod_policy = CleanPodPolicy.ALL
    if not job.spec.fail_policy:
        job.spec.fail_policy = EndingPolicy.ANY
    if not job.spec.complete_policy:
        job.spec.complete_policy = EndingPolicy.ALL
    for spec in job.spec.replica_specs.values():
        set_default_replica(spec)
    return job
