"""The ``TPUTrainingJob`` resource model.

Reference: ``pkg/apis/aitrainingjob/`` -- same spec/status/phase/policy surface,
extended with first-class TPU fields (accelerator/topology/slice semantics) and
*implemented* min/max elasticity (the reference declares MinReplicas/MaxReplicas
and EdlPolicy but never consumes them; see SURVEY.md §2.6).
"""

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    EdlPolicy,
    EndingPolicy,
    ReplicaSpec,
    ReplicaStatus,
    RestartPolicy,
    RestartScope,
    TPUSpec,
    TPUTrainingJob,
    TrainingJobCondition,
    TrainingJobPhase,
    TrainingJobSpec,
    TrainingJobStatus,
)
from trainingjob_operator_tpu.api.defaults import set_defaults
from trainingjob_operator_tpu.api.validation import ValidationError, validate_job

__all__ = [
    "constants",
    "CleanPodPolicy",
    "EdlPolicy",
    "EndingPolicy",
    "ReplicaSpec",
    "ReplicaStatus",
    "RestartPolicy",
    "RestartScope",
    "TPUSpec",
    "TPUTrainingJob",
    "TrainingJobCondition",
    "TrainingJobPhase",
    "TrainingJobSpec",
    "TrainingJobStatus",
    "set_defaults",
    "ValidationError",
    "validate_job",
]
