"""TPUTrainingJob API types: spec, status, phases, policies.

Reference: pkg/apis/aitrainingjob/v1/types.go + replica.go + framework.go.
Same field surface and enum spellings, with TPU-first extensions:

- ``TPUSpec`` per replica group (accelerator/topology/slice semantics) that the
  controller turns into GKE nodeSelectors, ``google.com/tpu`` resources and
  JAX/TPU env injection.
- ``min_replicas``/``max_replicas``/``edl_policy`` carry *implemented* elastic
  semantics (the reference declares them but never consumes them,
  zz_generated.deepcopy.go:90-96 is their only use; SURVEY.md §2.6).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ObjectMeta,
    PodTemplateSpec,
    from_iso,
    iso,
)


# ---------------------------------------------------------------------------
# Enums (string constants; spellings match reference types.go / replica.go)
# ---------------------------------------------------------------------------

class TrainingJobPhase:
    """Reference: v1/types.go:100-124 (10 phases, incl. the "" None phase)."""

    NONE = ""
    PENDING = "Pending"
    CREATING = "Creating"
    RUNNING = "Running"
    SUCCEEDED = "Succeed"  # sic -- reference spells the phase "Succeed"
    FAILED = "Failed"
    TIMEOUT = "Timeout"
    RESTARTING = "Restarting"
    TERMINATING = "Terminating"
    PREEMPTED = "Preempted"
    NODE_FAIL = "NodeFail"
    # TPU extension: elastic resize in progress (treated as a live phase).
    SCALING = "Scaling"


#: Phases that end a job (reference: constants.go:58-64).
ENDING_PHASES = (
    TrainingJobPhase.SUCCEEDED,
    TrainingJobPhase.FAILED,
    TrainingJobPhase.TIMEOUT,
    TrainingJobPhase.PREEMPTED,
    TrainingJobPhase.NODE_FAIL,
)

#: Phases in which the reconcile loop runs (reference: controller.go:298-304).
RECONCILABLE_PHASES = (
    TrainingJobPhase.NONE,
    TrainingJobPhase.PENDING,
    TrainingJobPhase.CREATING,
    TrainingJobPhase.RUNNING,
    TrainingJobPhase.RESTARTING,
    TrainingJobPhase.TERMINATING,
    TrainingJobPhase.SCALING,
)

#: phase -> condition reason (reference: constants.go:65-77).
PHASE_REASON = {
    TrainingJobPhase.NONE: "",
    TrainingJobPhase.PENDING: constants.PENDING_REASON,
    TrainingJobPhase.CREATING: constants.CREATING_REASON,
    TrainingJobPhase.RUNNING: constants.RUNNING_REASON,
    TrainingJobPhase.SUCCEEDED: constants.SUCCEEDED_REASON,
    TrainingJobPhase.FAILED: constants.FAILED_REASON,
    TrainingJobPhase.TIMEOUT: constants.TIMEOUT_REASON,
    TrainingJobPhase.RESTARTING: constants.RESTARTING_REASON,
    TrainingJobPhase.TERMINATING: constants.TERMINATING_REASON,
    TrainingJobPhase.PREEMPTED: constants.PREEMPTED_REASON,
    TrainingJobPhase.NODE_FAIL: constants.NODE_FAIL_REASON,
    TrainingJobPhase.SCALING: constants.SCALING_REASON,
}


class RestartPolicy:
    """Reference: v1/replica.go:25-30 (6 values)."""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    ON_NODE_FAIL = "OnNodeFail"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"
    ON_NODE_FAIL_WITH_EXIT_CODE = "OnNodeFailWithExitCode"

    VALUES = (ALWAYS, ON_FAILURE, ON_NODE_FAIL, NEVER, EXIT_CODE,
              ON_NODE_FAIL_WITH_EXIT_CODE)


class RestartScope:
    """Reference: v1/replica.go:31-33.  ``RESIZE`` is a TPU extension
    (VirtualFlow-style elastic resize, docs/ELASTIC.md): delete only the
    failed pods, keep survivors alive, and republish a bumped rendezvous
    generation so the surviving processes re-form the world in place."""

    ALL = "All"
    REPLICA = "Replica"
    POD = "Pod"
    RESIZE = "Resize"

    VALUES = (ALL, REPLICA, POD, RESIZE)


class EndingPolicy:
    """Reference: v1/replica.go:57-63."""

    ALL = "All"
    RANK0 = "Rank0"
    ANY = "Any"
    NONE = "None"

    VALUES = (ALL, RANK0, ANY, NONE)


class EdlPolicy:
    """Reference: v1/replica.go:51-56.  Implemented here (elastic resize),
    unlike the reference where the field is dead (SURVEY.md §2.6)."""

    AUTO = "Auto"
    MANUAL = "Manual"
    NEVER = "Never"

    VALUES = (AUTO, MANUAL, NEVER)


class CleanPodPolicy:
    """Reference: v1/types.go:67-72."""

    ALL = "All"
    NONE = "None"

    VALUES = (ALL, NONE)


# ---------------------------------------------------------------------------
# TPU extension spec
# ---------------------------------------------------------------------------

@dataclass
class TPUSpec:
    """First-class TPU fields for a replica group (north star: BASELINE.json).

    A replica group with a ``TPUSpec`` is provisioned as TPU pod-slices: one pod
    per TPU-VM host, ``slice_count`` slices, gang-scheduled per slice, with GKE
    ``cloud.google.com/gke-tpu-*`` nodeSelectors and JAX/TPU env injection.
    """

    accelerator: str = ""          # e.g. "tpu-v5-lite-podslice" / "tpu-v5e"
    topology: str = ""             # e.g. "2x4", "4x4", "4x8"
    slice_count: int = 1           # number of slices (multislice data-parallel)
    chips_per_host: int = 4        # v5e TPU-VM host = 4 chips
    preemptible: bool = False      # spot/preemptible capacity

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.accelerator:
            d["accelerator"] = self.accelerator
        if self.topology:
            d["topology"] = self.topology
        if self.slice_count != 1:
            d["sliceCount"] = self.slice_count
        if self.chips_per_host != 4:
            d["chipsPerHost"] = self.chips_per_host
        if self.preemptible:
            d["preemptible"] = True
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUSpec":
        return cls(
            accelerator=d.get("accelerator", ""),
            topology=d.get("topology", ""),
            slice_count=int(d.get("sliceCount", 1)),
            chips_per_host=int(d.get("chipsPerHost", 4)),
            preemptible=bool(d.get("preemptible", False)),
        )


# ---------------------------------------------------------------------------
# ReplicaSpec / ReplicaStatus
# ---------------------------------------------------------------------------

@dataclass
class ReplicaSpec:
    """Reference: v1/replica.go:9-20."""

    replicas: Optional[int] = None
    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    restart_limit: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: str = ""
    restart_scope: str = ""
    fail_policy: str = ""
    complete_policy: str = ""
    edl_policy: str = ""
    tpu: Optional[TPUSpec] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.replicas is not None:
            d["replicas"] = self.replicas
        if self.min_replicas is not None:
            d["minReplicas"] = self.min_replicas
        if self.max_replicas is not None:
            d["maxReplicas"] = self.max_replicas
        if self.restart_limit is not None:
            d["restartLimit"] = self.restart_limit
        d["template"] = self.template.to_dict()
        if self.restart_policy:
            d["restartPolicy"] = self.restart_policy
        if self.restart_scope:
            d["restartScope"] = self.restart_scope
        if self.fail_policy:
            d["failPolicy"] = self.fail_policy
        if self.complete_policy:
            d["completePolicy"] = self.complete_policy
        if self.edl_policy:
            d["edlPolicy"] = self.edl_policy
        if self.tpu is not None:
            d["tpu"] = self.tpu.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaSpec":
        return cls(
            replicas=_opt_int(d.get("replicas")),
            min_replicas=_opt_int(d.get("minReplicas")),
            max_replicas=_opt_int(d.get("maxReplicas")),
            restart_limit=_opt_int(d.get("restartLimit")),
            template=PodTemplateSpec.from_dict(d.get("template") or {}),
            restart_policy=d.get("restartPolicy", ""),
            restart_scope=d.get("restartScope", ""),
            fail_policy=d.get("failPolicy", ""),
            complete_policy=d.get("completePolicy", ""),
            edl_policy=d.get("edlPolicy", ""),
            tpu=TPUSpec.from_dict(d["tpu"]) if d.get("tpu") else None,
        )


@dataclass
class ReplicaStatus:
    """Reference: v1/replica.go:36-49 (6 counters)."""

    pending: int = 0
    scheduled: int = 0
    active: int = 0
    succeeded: int = 0
    restarting: int = 0
    failed: int = 0

    def reset(self) -> None:
        self.pending = self.scheduled = self.active = 0
        self.succeeded = self.restarting = self.failed = 0

    def total(self) -> int:
        return (self.pending + self.scheduled + self.active + self.succeeded
                + self.restarting + self.failed)

    def to_dict(self) -> Dict[str, Any]:
        return {"pending": self.pending, "scheduled": self.scheduled,
                "active": self.active, "succeeded": self.succeeded,
                "restarting": self.restarting, "failed": self.failed}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaStatus":
        return cls(
            pending=int(d.get("pending", 0)),
            scheduled=int(d.get("scheduled", 0)),
            active=int(d.get("active", 0)),
            succeeded=int(d.get("succeeded", 0)),
            restarting=int(d.get("restarting", 0)),
            failed=int(d.get("failed", 0)),
        )


# ---------------------------------------------------------------------------
# Job spec / status / condition
# ---------------------------------------------------------------------------

@dataclass
class TrainingJobSpec:
    """Reference: v1/types.go:41-62."""

    restarting_exit_code: str = ""          # e.g. "137,128"
    framework_type: str = ""                # e.g. "jax", "paddle", "tensorflow"
    fault_tolerant: bool = False
    priority: str = ""
    scheduler_name: str = ""
    time_limit: Optional[int] = None        # seconds
    clean_pod_policy: Optional[str] = None  # CleanPodPolicy
    fail_policy: str = ""                   # EndingPolicy
    complete_policy: str = ""               # EndingPolicy
    replica_specs: Dict[str, ReplicaSpec] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.restarting_exit_code:
            d["restartingExitCode"] = self.restarting_exit_code
        if self.framework_type:
            d["frameworkType"] = self.framework_type
        if self.fault_tolerant:
            d["faultTolerant"] = True
        if self.priority:
            d["priority"] = self.priority
        if self.scheduler_name:
            d["schedulerName"] = self.scheduler_name
        if self.time_limit is not None:
            d["timeLimit"] = self.time_limit
        if self.clean_pod_policy is not None:
            d["cleanPodPolicy"] = self.clean_pod_policy
        if self.fail_policy:
            d["failPolicy"] = self.fail_policy
        if self.complete_policy:
            d["completePolicy"] = self.complete_policy
        d["replicaSpecs"] = {name: s.to_dict() for name, s in self.replica_specs.items()}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainingJobSpec":
        return cls(
            restarting_exit_code=str(d.get("restartingExitCode", "")),
            framework_type=d.get("frameworkType", ""),
            fault_tolerant=bool(d.get("faultTolerant", False)),
            priority=d.get("priority", ""),
            scheduler_name=d.get("schedulerName", ""),
            time_limit=_opt_int(d.get("timeLimit")),
            clean_pod_policy=d.get("cleanPodPolicy"),
            fail_policy=d.get("failPolicy", ""),
            complete_policy=d.get("completePolicy", ""),
            replica_specs={name: ReplicaSpec.from_dict(s)
                           for name, s in (d.get("replicaSpecs") or {}).items()},
        )


# The job condition reuses the shared Condition shape
# (reference: v1/types.go:128-142).
TrainingJobCondition = Condition


@dataclass
class TrainingJobStatus:
    """Reference: v1/types.go:76-95 (with the json-tag quirks fixed,
    SURVEY.md §8: RestartCountes typo'd tag, RestartReplicaName missing tag)."""

    phase: str = TrainingJobPhase.NONE
    conditions: List[Condition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    restart_counts: Dict[str, int] = field(default_factory=dict)
    restart_replica_name: str = ""
    start_time: Optional[float] = None
    start_running_time: Optional[float] = None
    end_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    # TPU extension: current elastic width per replica group (replicas actually
    # provisioned right now; differs from spec.replicas while degraded).
    elastic_replicas: Dict[str, int] = field(default_factory=dict)
    # TPU extension: elastic-resize drain marker (mirrors restart_replica_name:
    # while set, reconcile stalls until the group's pods drain, then the group
    # is recreated at the new width with fresh rendezvous env).
    scaling_replica_name: str = ""
    # TPU extension: per-group wall time of the last elastic resize and number
    # of re-expand probes since the group last ran at full width (drives the
    # exponential scale-up backoff; keyed by replica name so independent
    # elastic groups don't corrupt each other's schedule).
    last_scale_times: Dict[str, float] = field(default_factory=dict)
    scale_up_attempts: Dict[str, int] = field(default_factory=dict)
    # TPU extension: in-flight non-destructive re-expand probes (rtype ->
    # target width).  While set, reservation pods are provisioned beyond the
    # elastic width; the running group is only re-rendezvoused once they all
    # schedule, so a failed probe never tears down running work.
    scale_probes: Dict[str, int] = field(default_factory=dict)
    # TPU extension: elastic-resize fast path (scope Resize, docs/ELASTIC.md).
    # While resize_replica_name is set, reconcile stalls until the group's
    # *failed* pods drain; survivors stay alive and the bumped rendezvous
    # generation is republished to them.  lost_indices records the replica
    # indices vacated by resize (holes the reconciler must not refill);
    # rendezvous_generation is the monotonically increasing world epoch.
    resize_replica_name: str = ""
    lost_indices: Dict[str, List[int]] = field(default_factory=dict)
    rendezvous_generation: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"phase": self.phase}
        if self.conditions:
            d["conditions"] = [c.to_dict() for c in self.conditions]
        if self.replica_statuses:
            d["replicaStatuses"] = {n: s.to_dict() for n, s in self.replica_statuses.items()}
        if self.restart_counts:
            d["restartCounts"] = dict(self.restart_counts)
        if self.restart_replica_name:
            d["restartReplicaName"] = self.restart_replica_name
        if self.start_time is not None:
            d["startTime"] = iso(self.start_time)
        if self.start_running_time is not None:
            d["startRunningTime"] = iso(self.start_running_time)
        if self.end_time is not None:
            d["endTime"] = iso(self.end_time)
        if self.last_reconcile_time is not None:
            d["lastReconcileTime"] = iso(self.last_reconcile_time)
        if self.elastic_replicas:
            d["elasticReplicas"] = dict(self.elastic_replicas)
        if self.scaling_replica_name:
            d["scalingReplicaName"] = self.scaling_replica_name
        if self.last_scale_times:
            d["lastScaleTimes"] = {n: iso(t) for n, t in self.last_scale_times.items()}
        if self.scale_up_attempts:
            d["scaleUpAttempts"] = dict(self.scale_up_attempts)
        if self.scale_probes:
            d["scaleProbes"] = dict(self.scale_probes)
        if self.resize_replica_name:
            d["resizeReplicaName"] = self.resize_replica_name
        if self.lost_indices:
            d["lostIndices"] = {n: list(v) for n, v in self.lost_indices.items()}
        if self.rendezvous_generation:
            d["rendezvousGeneration"] = self.rendezvous_generation
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainingJobStatus":
        return cls(
            phase=d.get("phase", TrainingJobPhase.NONE),
            conditions=[Condition.from_dict(c) for c in d.get("conditions") or []],
            replica_statuses={n: ReplicaStatus.from_dict(s)
                              for n, s in (d.get("replicaStatuses") or {}).items()},
            restart_counts={n: int(v) for n, v in (d.get("restartCounts") or {}).items()},
            restart_replica_name=d.get("restartReplicaName", ""),
            start_time=from_iso(d.get("startTime")),
            start_running_time=from_iso(d.get("startRunningTime")),
            end_time=from_iso(d.get("endTime")),
            last_reconcile_time=from_iso(d.get("lastReconcileTime")),
            elastic_replicas={n: int(v) for n, v in (d.get("elasticReplicas") or {}).items()},
            scaling_replica_name=d.get("scalingReplicaName", ""),
            last_scale_times={n: from_iso(t)
                              for n, t in (d.get("lastScaleTimes") or {}).items()},
            scale_up_attempts={n: int(v)
                               for n, v in (d.get("scaleUpAttempts") or {}).items()},
            scale_probes={n: int(v)
                          for n, v in (d.get("scaleProbes") or {}).items()},
            resize_replica_name=d.get("resizeReplicaName", ""),
            lost_indices={n: [int(i) for i in v]
                          for n, v in (d.get("lostIndices") or {}).items()},
            rendezvous_generation=int(d.get("rendezvousGeneration", 0)),
        )


@dataclass
class TPUTrainingJob:
    """The CR (reference: v1/types.go:29-38)."""

    KIND = constants.KIND

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TrainingJobSpec = field(default_factory=TrainingJobSpec)
    status: TrainingJobStatus = field(default_factory=TrainingJobStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def deepcopy(self) -> "TPUTrainingJob":
        """Reference: zz_generated.deepcopy.go DeepCopy."""
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": constants.API_VERSION,
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUTrainingJob":
        api_version = d.get("apiVersion", constants.API_VERSION)
        kind = d.get("kind", cls.KIND)
        # Accept the reference's group/kind spelling for drop-in manifests.
        accepted_kinds = (cls.KIND, "AITrainingJob")
        if kind not in accepted_kinds:
            raise ValueError(f"unexpected kind {kind!r}, want one of {accepted_kinds}")
        del api_version  # any version accepted; schema is forward-compatible
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=TrainingJobSpec.from_dict(d.get("spec") or {}),
            status=TrainingJobStatus.from_dict(d.get("status") or {}),
        )

    @classmethod
    def from_yaml(cls, text: str) -> "TPUTrainingJob":
        import yaml

        return cls.from_dict(yaml.safe_load(text))

    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)


def _opt_int(v: Any) -> Optional[int]:
    return None if v is None else int(v)


def is_failed_phase(phase: str) -> bool:
    """An ending phase that is not Succeeded (reference: status.go:89-99)."""
    return phase in ENDING_PHASES and phase != TrainingJobPhase.SUCCEEDED
