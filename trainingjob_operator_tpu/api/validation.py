"""Validation for TPUTrainingJob -- real, wired-in validation.

The reference ships a dead validation package (references a nonexistent type
and an undefined logger, imported by nothing: validation/validation.go:10-32,
and the controller carries a matching ``FIXME: need to validate trainingjob``,
trainingjob.go:21,33).  This implements what that package intended -- replica
specs must have containers with images -- plus enum/elastic/TPU checks, and the
controller actually calls it.
"""

from __future__ import annotations

from typing import List

from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    EdlPolicy,
    EndingPolicy,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
)


class ValidationError(ValueError):
    """Raised when a TPUTrainingJob spec is invalid."""


def validate_job(job: TPUTrainingJob, require_image: bool = False) -> List[str]:
    """Return a list of violations (empty == valid).

    ``require_image`` enforces the reference's intended image check
    (validation.go:20-25); the local-process runtime runs command-only pods, so
    images are optional there.
    """
    errs: List[str] = []
    if not job.metadata.name:
        errs.append("metadata.name: required")
    spec = job.spec
    if not spec.replica_specs:
        errs.append("spec.replicaSpecs: at least one replica group is required")
    if spec.clean_pod_policy is not None and spec.clean_pod_policy not in CleanPodPolicy.VALUES:
        errs.append(f"spec.cleanPodPolicy: invalid value {spec.clean_pod_policy!r}")
    if spec.fail_policy and spec.fail_policy not in EndingPolicy.VALUES:
        errs.append(f"spec.failPolicy: invalid value {spec.fail_policy!r}")
    if spec.complete_policy and spec.complete_policy not in EndingPolicy.VALUES:
        errs.append(f"spec.completePolicy: invalid value {spec.complete_policy!r}")
    if spec.time_limit is not None and spec.time_limit <= 0:
        errs.append("spec.timeLimit: must be > 0 seconds")
    if spec.restarting_exit_code:
        for tok in spec.restarting_exit_code.split(","):
            tok = tok.strip()
            if tok and not _is_int(tok):
                errs.append(f"spec.restartingExitCode: {tok!r} is not an integer")

    for rname, rspec in spec.replica_specs.items():
        prefix = f"spec.replicaSpecs[{rname}]"
        if rspec.restart_policy and rspec.restart_policy not in RestartPolicy.VALUES:
            errs.append(f"{prefix}.restartPolicy: invalid value {rspec.restart_policy!r}")
        if rspec.restart_scope and rspec.restart_scope not in RestartScope.VALUES:
            errs.append(f"{prefix}.restartScope: invalid value {rspec.restart_scope!r}")
        if rspec.fail_policy and rspec.fail_policy not in EndingPolicy.VALUES:
            errs.append(f"{prefix}.failPolicy: invalid value {rspec.fail_policy!r}")
        if rspec.complete_policy and rspec.complete_policy not in EndingPolicy.VALUES:
            errs.append(f"{prefix}.completePolicy: invalid value {rspec.complete_policy!r}")
        if rspec.edl_policy and rspec.edl_policy not in EdlPolicy.VALUES:
            errs.append(f"{prefix}.edlPolicy: invalid value {rspec.edl_policy!r}")
        if rspec.replicas is not None and rspec.replicas < 0:
            errs.append(f"{prefix}.replicas: must be >= 0")
        if rspec.restart_limit is not None and rspec.restart_limit < 0:
            errs.append(f"{prefix}.restartLimit: must be >= 0")
        if (rspec.min_replicas is not None and rspec.max_replicas is not None
                and rspec.min_replicas > rspec.max_replicas):
            errs.append(f"{prefix}: minReplicas > maxReplicas")
        if (rspec.min_replicas is not None and rspec.replicas is not None
                and rspec.min_replicas > rspec.replicas):
            errs.append(f"{prefix}: minReplicas > replicas")
        if (rspec.max_replicas is not None and rspec.replicas is not None
                and rspec.max_replicas < rspec.replicas):
            errs.append(f"{prefix}: maxReplicas < replicas")

        containers = rspec.template.spec.containers
        if not containers:
            # Reference intent: validation.go:17-19.
            errs.append(f"{prefix}.template.spec.containers: must not be empty")
        for c in containers:
            if not c.name:
                errs.append(f"{prefix}: container with empty name")
            if require_image and not c.image:
                # Reference intent: validation.go:20-25.
                errs.append(f"{prefix}: container {c.name!r} has no image")

        if rspec.tpu is not None:
            tpu = rspec.tpu
            if not tpu.topology:
                errs.append(f"{prefix}.tpu.topology: required when tpu is set")
            elif not _valid_topology(tpu.topology):
                errs.append(f"{prefix}.tpu.topology: invalid topology {tpu.topology!r}")
            if tpu.slice_count < 1:
                errs.append(f"{prefix}.tpu.sliceCount: must be >= 1")
            if tpu.chips_per_host < 1:
                errs.append(f"{prefix}.tpu.chipsPerHost: must be >= 1")
            if tpu.topology and _valid_topology(tpu.topology):
                # Replicas must match the slice geometry: one pod per TPU-VM
                # host, slice_count slices (multislice rendezvous depends on
                # index // hosts_per_slice mapping cleanly).
                from trainingjob_operator_tpu.api.tpu import total_hosts

                want = total_hosts(tpu)
                if rspec.replicas is not None and rspec.replicas != want:
                    errs.append(
                        f"{prefix}.replicas: {rspec.replicas} does not match the "
                        f"TPU geometry (topology {tpu.topology} x "
                        f"{tpu.slice_count} slice(s) = {want} hosts)")
                # Elastic bounds resize in whole slices (the runnable unit).
                from trainingjob_operator_tpu.api.tpu import resolve_slice_shape

                hosts = resolve_slice_shape(tpu).hosts
                for field_name, val in (("minReplicas", rspec.min_replicas),
                                        ("maxReplicas", rspec.max_replicas)):
                    if val is not None and hosts > 1 and val % hosts != 0:
                        errs.append(
                            f"{prefix}.{field_name}: {val} is not a whole "
                            f"number of slices (hosts per slice = {hosts})")
    return errs


def validate_job_or_raise(job: TPUTrainingJob, require_image: bool = False) -> None:
    errs = validate_job(job, require_image=require_image)
    if errs:
        raise ValidationError("; ".join(errs))


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def _valid_topology(topology: str) -> bool:
    """Valid iff the resolver's grammar accepts it (single source of truth:
    api/tpu.py parse_topology)."""
    from trainingjob_operator_tpu.api.tpu import parse_topology

    try:
        parse_topology(topology)
        return True
    except ValueError:
        return False
