"""TPU accelerator/topology model.

Maps a ``TPUSpec`` (accelerator + topology + slice count) to concrete
provisioning facts: chips per slice, hosts per slice, GKE nodeSelectors and
``google.com/tpu`` resource counts.  This is the TPU-native replacement for the
reference's implicit "a replica is one generic pod" assumption
(reference: pkg/controller/pod.go:186-193 creates one pod per index; here an
index maps to one TPU-VM *host* of a slice, and a replica group maps to
``slice_count`` gang-scheduled slices).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import TPUSpec


def parse_topology(topology: str) -> Tuple[int, ...]:
    """'4x4' -> (4, 4); '2x2x4' -> (2, 2, 4)."""
    try:
        dims = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"invalid TPU topology {topology!r}") from e
    if len(dims) not in (2, 3) or any(d <= 0 for d in dims):
        raise ValueError(f"invalid TPU topology {topology!r}")
    return dims


def chips_in_topology(topology: str) -> int:
    return math.prod(parse_topology(topology))


@dataclass(frozen=True)
class SliceShape:
    """Resolved provisioning facts for one slice of a replica group."""

    accelerator: str
    topology: str
    chips: int
    hosts: int            # pods (TPU-VM hosts) per slice
    chips_per_host: int

    def node_selectors(self, preemptible: bool = False) -> Dict[str, str]:
        sel = {
            constants.GKE_TPU_ACCELERATOR_SELECTOR: self.accelerator,
            constants.GKE_TPU_TOPOLOGY_SELECTOR: self.topology,
        }
        if preemptible:
            sel[constants.GKE_SPOT_SELECTOR] = "true"
        return sel

    def tpu_resources(self) -> Dict[str, int]:
        return {constants.TPU_RESOURCE: self.chips_per_host}


def resolve_slice_shape(tpu: TPUSpec) -> SliceShape:
    """Compute hosts-per-slice from topology and chips/host.

    v5e examples: topology 2x4 = 8 chips = 2 hosts; 4x4 = 16 chips = 4 hosts;
    4x8 = 32 chips = 8 hosts (4 chips per TPU-VM host).
    """
    if not tpu.topology:
        raise ValueError("TPUSpec.topology is required to resolve a slice shape")
    chips = chips_in_topology(tpu.topology)
    cph = max(1, tpu.chips_per_host)
    hosts = max(1, math.ceil(chips / cph))
    return SliceShape(
        accelerator=tpu.accelerator or "tpu-v5-lite-podslice",
        topology=tpu.topology,
        chips=chips,
        hosts=hosts,
        chips_per_host=min(cph, chips),
    )


def total_hosts(tpu: TPUSpec) -> int:
    """Total pods for the replica group: hosts/slice x slice_count."""
    return resolve_slice_shape(tpu).hosts * max(1, tpu.slice_count)


def mesh_axes_for(tpu: TPUSpec) -> List[Tuple[str, int]]:
    """Suggested workload mesh: DCN data-parallel across slices, ICI within.

    The operator provisions topology; the workload layer turns this into a
    ``jax.sharding.Mesh`` (parallel/mesh.py).  Returned as (axis, size) pairs:
    [("slice", slice_count), ("host", hosts), ("chip", chips_per_host)].
    """
    shape = resolve_slice_shape(tpu)
    return [("slice", max(1, tpu.slice_count)), ("host", shape.hosts),
            ("chip", shape.chips_per_host)]
