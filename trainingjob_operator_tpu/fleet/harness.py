"""Fleet harness: thousands of churning jobs against the sim cluster.

Drives a seeded :mod:`~trainingjob_operator_tpu.fleet.churn` schedule --
Poisson creates, operator-level preemptions (annotation), pod kills (exit
137 + EXIT_CODE restart), mid-flight CR deletes -- through a real
``TrainingJobController`` + ``SimRuntime`` pair sharing one object tracker,
then judges convergence:

- every job settles at the phase its fate predicts (Succeed / Running /
  Preempted / restarted-Running), or is gone if it was deleted;
- expectations never wedge (an unsettled job with unsatisfied expectations
  is reported as such, not just "wrong phase");
- after a GC sweep no pod outlives its owning job.

Along the way it measures event-to-pod-visible latency per transition kind
(job create -> first pod ADDED, preempt-annotate -> phase visibly moves,
pod kill -> replacement pod ADDED) straight off the tracker's watch stream,
so the number reflects what a client would see, not controller internals.

The controller can be handed a latency-injecting clientset view
(``api_latency``): every *write* verb sleeps like a round trip to a real
API server while reads stay cache-fast (informers/listers are local caches
in real deployments too).  That is what makes worker-parallelism measurable
under the GIL -- workers overlap API waits, not Python bytecode -- and is
the basis of the ``control_plane`` bench leg (bench.py).

CLI (``make fleet-smoke``)::

    python -m trainingjob_operator_tpu.fleet.harness --jobs 200 --seed 0

Seed/job-count defaults honor TRAININGJOB_FLEET_SEED / TRAININGJOB_FLEET_JOBS.
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.chaos import (
    ChaosMonkey,
    ChaosTracker,
    chaos_clientset,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.informers import InformerFactory
from trainingjob_operator_tpu.client.tracker import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import (
    LATENCY_MS_BUCKETS,
    TrainingJobController,
)
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    PodTemplateSpec,
)
from trainingjob_operator_tpu.fleet.chaos import (
    ChaosGenerator,
    ChaosProfile,
)
from trainingjob_operator_tpu.fleet.churn import (
    FATE_COMPLETE,
    FATE_DELETE,
    FATE_POD_FAIL,
    FATE_PREEMPT,
    FATE_STEADY,
    ChurnGenerator,
    ChurnProfile,
    JobPlan,
)
from trainingjob_operator_tpu.runtime.sim import (
    EXIT_CODE_ANNOTATION,
    REQ_RATE_ANNOTATION,
    REQ_TPOT_ANNOTATION,
    REQ_TTFT_ANNOTATION,
    RUN_SECONDS_ANNOTATION,
    SimRuntime,
    resolve_kernel,
)
from trainingjob_operator_tpu.obs.incident import INCIDENTS
from trainingjob_operator_tpu.obs.profiler import PROFILER
from trainingjob_operator_tpu.obs.reqtrace import REQTRACE
from trainingjob_operator_tpu.obs.slo import SLOS, default_slos
from trainingjob_operator_tpu.obs.tsdb import TSDB
from trainingjob_operator_tpu.utils.metrics import METRICS

RTYPE = "trainer"

#: Phases a fate is allowed to settle at.
_SETTLED_PHASES = {
    FATE_COMPLETE: (TrainingJobPhase.SUCCEEDED,),
    FATE_STEADY: (TrainingJobPhase.RUNNING,),
    FATE_PREEMPT: (TrainingJobPhase.PREEMPTED,),
    FATE_POD_FAIL: (TrainingJobPhase.RUNNING,),
}


class _LatencyClient:
    """Typed-client proxy charging a fixed sleep per *mutating* verb.

    Reads (`get`/`list`) pass through untouched: against a real cluster the
    controller reads from informer caches, so only writes pay a round trip.
    """

    def __init__(self, inner: Any, latency: float):
        self._inner = inner
        self._latency = latency

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def _pay(self) -> None:
        time.sleep(self._latency)

    def create(self, obj):
        self._pay()
        return self._inner.create(obj)

    def update(self, obj):
        self._pay()
        return self._inner.update(obj)

    def update_status(self, obj):
        self._pay()
        return self._inner.update_status(obj)

    def delete(self, namespace, name, grace_period=None):
        self._pay()
        return self._inner.delete(namespace, name, grace_period)


def latency_clientset(cs: Clientset, api_latency: float) -> Clientset:
    """A second view over ``cs.tracker`` whose write verbs sleep
    ``api_latency`` seconds.  Hand this to the controller; keep the raw
    clientset for the sim (kubelet writes are node-local in real life)."""
    ctl = Clientset(tracker=cs.tracker)
    if api_latency > 0.0:
        ctl.trainingjobs = _LatencyClient(ctl.trainingjobs, api_latency)
        ctl.pods = _LatencyClient(ctl.pods, api_latency)
        ctl.services = _LatencyClient(ctl.services, api_latency)
        ctl.events = _LatencyClient(ctl.events, api_latency)
    return ctl


class _LatencyRecorder:
    """Event -> pod-visible latency, measured off the tracker watch stream.

    ``mark_*`` is called by the driver immediately *before* it issues the
    triggering API call (so the sample can never go negative against the
    asynchronous controller); the watch handlers complete the pair when the
    effect becomes visible to any watching client.
    """

    def __init__(self, cs: Clientset):
        self._lock = threading.Lock()
        self._pending_create: Dict[str, float] = {}   # job key -> t0
        self._pending_preempt: Dict[str, float] = {}  # job key -> t0
        self._pending_fail: Dict[str, float] = {}     # pod key -> t0
        self.samples: Dict[str, List[float]] = {
            "create": [], "preempt": [], "pod_fail": []}
        self._unsubs = [
            cs.tracker.watch(constants.KIND, self._on_job_event),
            cs.tracker.watch(Pod.KIND, self._on_pod_event),
        ]

    def close(self) -> None:
        for unsub in self._unsubs:
            unsub()
        self._unsubs = []

    # -- driver side ---------------------------------------------------------

    def mark_create(self, job_key: str) -> None:
        with self._lock:
            self._pending_create[job_key] = time.monotonic()

    def mark_preempt(self, job_key: str) -> None:
        with self._lock:
            self._pending_preempt[job_key] = time.monotonic()

    def mark_pod_fail(self, pod_key: str) -> None:
        with self._lock:
            self._pending_fail[pod_key] = time.monotonic()

    # -- watch side ----------------------------------------------------------

    def _sample(self, kind: str, t0: float) -> None:
        ms = (time.monotonic() - t0) * 1000.0
        self.samples[kind].append(ms)
        METRICS.observe("trainingjob_event_to_visible_ms", ms,
                        buckets=LATENCY_MS_BUCKETS, kind=kind)

    def _on_job_event(self, event: WatchEvent) -> None:
        job = event.obj
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            if event.type == DELETED:
                self._pending_preempt.pop(key, None)
                self._pending_create.pop(key, None)
                return
            if event.type == MODIFIED and key in self._pending_preempt:
                # Visible as soon as the phase moves off the pre-preempt
                # steady state -- Terminating first, then Preempted.
                if job.status.phase in (TrainingJobPhase.TERMINATING,
                                        TrainingJobPhase.PREEMPTED):
                    self._sample("preempt", self._pending_preempt.pop(key))

    def _on_pod_event(self, event: WatchEvent) -> None:
        if event.type != ADDED:
            return
        pod = event.obj
        pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL)
        job_key = f"{pod.metadata.namespace}/{job_name}" if job_name else None
        with self._lock:
            if pod_key in self._pending_fail:
                # The replacement pod reuses the (job, rtype, index) name.
                self._sample("pod_fail", self._pending_fail.pop(pod_key))
            elif job_key is not None and job_key in self._pending_create:
                self._sample("create", self._pending_create.pop(job_key))

    # -- reporting -----------------------------------------------------------

    def percentiles(self) -> Dict[str, Any]:
        allv = sorted(v for vs in self.samples.values() for v in vs)

        def pct(q: float) -> float:
            if not allv:
                return 0.0
            idx = min(len(allv) - 1, max(0, math.ceil(q * len(allv)) - 1))
            return allv[idx]

        return {
            "count": len(allv),
            "p50": round(pct(0.50), 3),
            "p99": round(pct(0.99), 3),
            "max": round(allv[-1], 3) if allv else 0.0,
            "by_kind": {k: len(v) for k, v in self.samples.items()},
        }


@dataclass
class FleetReport:
    """Everything a run proved (or failed to): the harness's verdict plus
    the control-plane numbers bench.py republishes."""

    jobs: int
    replicas_total: int
    workers: int
    seed: int
    converged: bool
    violations: List[str]
    wall_seconds: float
    sync_count: int
    reconciles_per_s: float
    #: Which sim kubelet kernel ran (docs/FLEET.md): "event" or "scan".
    sim_kernel: str
    #: Timer events the event kernel dispatched (0 under scan) and the same
    #: per wall second -- the O(events) cost the kernel actually paid,
    #: reported beside reconciles/s for the scan-vs-event A/B.
    sim_events_total: int
    sim_events_per_s: float
    #: Sim kubelet loop cost: passes through the kernel loop and the CPU
    #: seconds they burned (thread time).  The scan kernel pays one pass per
    #: tick whether or not anything happened -- O(pods x ticks); the event
    #: kernel pays only for armed deadlines -- O(events).  Both kernels
    #: deliver the same pod transitions on a seeded run, so cpu_scan /
    #: cpu_event is the kernel's reconcile-throughput speedup.
    sim_loop_passes: int
    sim_cpu_seconds: float
    event_to_visible_ms: Dict[str, Any]
    workqueue_depth_high_water: int
    workqueue_retries_total: int
    workqueue_coalesced_total: int
    phase_counts: Dict[str, int] = field(default_factory=dict)
    #: Per-fate incident downtime attribution (obs/incident.py): for each
    #: disrupted fate, incident count and per-phase p50/p99 ms -- "restart-
    #: all costs X ms, Y% of it in reschedule" as a fleet-measured fact.
    downtime_phases: Dict[str, Any] = field(default_factory=dict)
    #: Downtime ms the flight recorder could NOT attribute to a named phase
    #: (``unknown`` residue).  The harness files a violation when nonzero.
    #: ``unknown`` time inside a declared chaos window is attributed to the
    #: fault plane first (docs/CHAOS.md) and does not count here.
    unattributed_downtime_ms: float = 0.0
    #: Controller write retries absorbed by client/retry.py during this run
    #: (sum of trainingjob_api_retries_total across verbs).
    api_retries_total: int = 0
    #: Pod restarts the controller performed during this run (delta of
    #: ``trainingjob_restarts_total``) -- the node-chaos bench compares this
    #: between damped and undamped arms (restart amplification).
    restarts_total: int = 0
    #: Chaos summary when a chaos profile ran: seed, plan digest, injected
    #: fault counts by kind, informer relists.  None on a clean run.
    chaos: Optional[Dict[str, Any]] = None
    #: SLO engine verdicts when the plane ran (--slo): per-objective burn
    #: rates/breach counters plus how many SLOBreach events and stamped
    #: incident bundles the run produced.  None with the plane off.
    slo_verdicts: Optional[Dict[str, Any]] = None
    #: Span profiler summary when it ran (--profile): top span stacks by
    #: CPU%, worker span-attribution ratio, measured overhead.  None off.
    profile_top: Optional[Dict[str, Any]] = None
    #: Request-plane audit when it ran (--request-obs): the ledger rollup
    #: (records, outcomes, orphans after reconcile, tail-sampling drops)
    #: plus incident-bundle ``requests`` stanza coverage.  None with the
    #: plane off; nonzero orphans file a violation, mirroring
    #: ``unattributed_downtime_ms``.
    requests: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "jobs": self.jobs,
            "replicas_total": self.replicas_total,
            "workers": self.workers,
            "seed": self.seed,
            "converged": self.converged,
            "violations": self.violations,
            "wall_seconds": round(self.wall_seconds, 3),
            "sync_count": self.sync_count,
            "reconciles_per_s": round(self.reconciles_per_s, 2),
            "sim_kernel": self.sim_kernel,
            "sim_events_total": self.sim_events_total,
            "sim_events_per_s": round(self.sim_events_per_s, 2),
            "sim_loop_passes": self.sim_loop_passes,
            "sim_cpu_seconds": round(self.sim_cpu_seconds, 3),
            "event_to_visible_ms": self.event_to_visible_ms,
            "workqueue_depth_high_water": self.workqueue_depth_high_water,
            "workqueue_retries_total": self.workqueue_retries_total,
            "workqueue_coalesced_total": self.workqueue_coalesced_total,
            "phase_counts": self.phase_counts,
            "downtime_phases": self.downtime_phases,
            "unattributed_downtime_ms": round(self.unattributed_downtime_ms,
                                              3),
            "api_retries_total": self.api_retries_total,
            "restarts_total": self.restarts_total,
            "chaos": self.chaos,
            "slo_verdicts": self.slo_verdicts,
            "profile_top": self.profile_top,
            "requests": self.requests,
        }


def build_job(plan: JobPlan, with_ports: bool = False,
              node_fail_restart: bool = False,
              request_obs: bool = False) -> TPUTrainingJob:
    """A sim-runnable job from a plan.  No container ports by default: the
    service reconciler then creates nothing, which keeps a 100k-replica run
    about pods (ports=True doubles the object count for DNS realism).

    ``node_fail_restart`` (node-chaos runs) gives every job
    ``ON_NODE_FAIL_WITH_EXIT_CODE`` restart semantics -- the realistic TPU
    training config: a dead node restarts the group instead of terminally
    failing the job, so node faults are survivable and restart counts
    measure the controller's damping (docs/CHAOS.md).

    ``request_obs`` adds the request-synthesis annotations (sim opens and
    completes request ids per tick) -- only then does the run produce
    request records, which is what keeps the plane-off arm byte-identical."""
    ports = ([ContainerPort(name="aitj-7777", container_port=7777)]
             if with_ports else [])
    annotations = {
        RUN_SECONDS_ANNOTATION: f"{plan.run_seconds:.3f}",
        EXIT_CODE_ANNOTATION: "0",
    }
    if request_obs:
        annotations[REQ_RATE_ANNOTATION] = "2"
        annotations[REQ_TTFT_ANNOTATION] = "40"
        annotations[REQ_TPOT_ANNOTATION] = "5"
    template = PodTemplateSpec(
        metadata=ObjectMeta(annotations=annotations),
        spec=PodSpec(containers=[Container(name="aitj-main", ports=ports)]))
    job = TPUTrainingJob(metadata=ObjectMeta(
        name=plan.name, namespace=plan.namespace))
    replica_kw: Dict[str, Any] = {}
    if node_fail_restart:
        replica_kw = dict(
            restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
            restart_scope=RestartScope.ALL)
    elif plan.fate == FATE_POD_FAIL:
        replica_kw = dict(restart_policy=RestartPolicy.EXIT_CODE,
                          restart_scope=RestartScope.ALL)
    job.spec.replica_specs[RTYPE] = ReplicaSpec(
        replicas=plan.replicas, template=template, **replica_kw)
    if node_fail_restart or plan.fate == FATE_POD_FAIL:
        job.spec.restarting_exit_code = "137,143"
    return job


class FleetHarness:
    """One fleet run: build cluster, drive the schedule, judge convergence."""

    def __init__(self, profile: ChurnProfile, workers: int = 4,
                 pace: bool = True, api_latency: float = 0.0,
                 resync_period: float = 2.0, resync_shards: int = 8,
                 gc_interval: float = 5.0, pods_per_node: int = 64,
                 converge_timeout: float = 60.0, with_ports: bool = False,
                 sim_tick: float = 0.02, sim_kernel: Optional[str] = None,
                 max_wall_seconds: float = 0.0,
                 chaos_profile: Optional[ChaosProfile] = None,
                 nodes_per_slice: int = 4,
                 slo_plane: bool = False, profiler: bool = False,
                 request_obs: bool = False,
                 progress: Optional[Callable[[str], None]] = None):
        self.profile = profile
        self.workers = workers
        self.pace = pace
        self.api_latency = api_latency
        self.resync_period = resync_period
        self.resync_shards = resync_shards
        self.gc_interval = gc_interval
        self.pods_per_node = pods_per_node
        self.converge_timeout = converge_timeout
        self.with_ports = with_ports
        # Sim kubelet tick: under the scan kernel the per-tick lifecycle
        # walk is O(live pods), so a fleet-sized run wants a coarser tick
        # than the 5 ms test default; the event kernel only uses it as the
        # watchdog/serve-snapshot cadence.
        self.sim_tick = sim_tick
        self.sim_kernel = resolve_kernel(sim_kernel)
        # Wall-clock ceiling: 0 disables; otherwise a run past it files a
        # violation (CI's regression tripwire for the event kernel -- see
        # `make fleet-smoke`).
        self.max_wall_seconds = max_wall_seconds
        # Seeded control-plane fault plan (docs/CHAOS.md): when set, the
        # controller's API view and watch streams ride the chaos plane while
        # the sim and the driver keep the clean view.
        self.chaos_profile = chaos_profile
        # Failure-domain granularity: every ``nodes_per_slice`` sim nodes
        # share one NODE_SLICE_LABEL value, so a plan's domain_down fault
        # kills a correlated group (docs/CHAOS.md).
        self.nodes_per_slice = max(1, nodes_per_slice)
        # Fleet SLO plane (docs/SLO.md): tsdb sweeper + burn-rate engine
        # (--slo) and the sampling span profiler (--profile).  Off by
        # default -- the planes observe the run, never shape it, and the
        # slo-smoke determinism arm proves exactly that.
        self.slo_plane = slo_plane
        self.with_profiler = profiler
        # Request-lifecycle plane (docs/SERVING.md): jobs get the request-
        # synthesis annotations and the audit ledger runs; at the end the
        # harness reconciles submitted vs terminal ids and files a
        # violation for any orphan.
        self.request_obs = request_obs
        self._progress = progress or (lambda _msg: None)
        self.violations: List[str] = []

    # -- the run -------------------------------------------------------------

    def run(self) -> FleetReport:
        plans = ChurnGenerator(self.profile).plan()
        total_replicas = sum(p.replicas for p in plans)

        cs = Clientset()
        cs_ctl = latency_clientset(cs, self.api_latency)
        monkey: Optional[ChaosMonkey] = None
        chaos_plan = None
        informer_factory: Optional[InformerFactory] = None
        if self.chaos_profile is not None:
            chaos_plan = ChaosGenerator(self.chaos_profile).plan()
            monkey = ChaosMonkey(chaos_plan)
            # The controller's writes go through the chaos plane stacked on
            # the latency view; its informers watch a ChaosTracker so stream
            # drops and stale lists hit the cache path too.  The sim and the
            # driver keep the clean clientset -- only the control plane is
            # under test.
            cs_ctl = chaos_clientset(cs_ctl, monkey)
            informer_factory = InformerFactory(
                ChaosTracker(cs.tracker, monkey))
        tc = TrainingJobController(
            cs_ctl, informer_factory=informer_factory,
            options=OperatorOptions(
                resync_period=self.resync_period,
                resync_shards=self.resync_shards,
                gc_interval=self.gc_interval,
                thread_num=self.workers,
            ))
        sim = SimRuntime(cs, tick=self.sim_tick,
                         pods_per_node=self.pods_per_node,
                         kernel=self.sim_kernel)
        for i in range(max(1, math.ceil(total_replicas / self.pods_per_node))):
            sim.add_node(f"fleet-n{i:04d}", labels={
                constants.NODE_SLICE_LABEL:
                    f"slice-{i // self.nodes_per_slice:03d}"})
        recorder = _LatencyRecorder(cs)

        sync_count_before = self._sync_count()
        retries_before = self._counter_sum("trainingjob_api_retries_total")
        relists_before = self._counter_sum(
            "trainingjob_informer_relists_total")
        restarts_before = self._counter_sum("trainingjob_restarts_total")
        sim.start()
        tc.run(workers=self.workers)
        if monkey is not None:
            # Arm the time-shaped faults only once the controller is live so
            # spike/drop offsets line up with the churn schedule's clock, and
            # register the windows with the flight recorder for attribution.
            INCIDENTS.clear_chaos_windows()
            monkey.attach()
            for w_kind, w_start, w_end in monkey.windows_abs():
                INCIDENTS.record_chaos_window(w_kind, w_start, w_end)
            if chaos_plan is not None and chaos_plan.node_faults:
                if self.sim_kernel != "event":
                    self.violations.append(
                        "node faults planned but the scan kernel cannot "
                        "schedule them (use the event kernel)")
                else:
                    # Data-plane faults execute inside the sim's timer-queue
                    # kernel: flaps thaw (not exit-137) on recovery, kills
                    # stay dead, domain kills down every node in one slice.
                    sim.schedule_node_faults(chaos_plan.node_faults,
                                             on_fault=monkey.record_fault)
        if self.slo_plane:
            # Fresh rings per run: the store and engine are process-global
            # (back-to-back in-process runs would otherwise see each
            # other's history).
            TSDB.reset()
            TSDB.start()
            SLOS.configure(default_slos())
            SLOS.start()
        if self.with_profiler:
            PROFILER.reset()
            PROFILER.start()
        if self.request_obs:
            # Fresh ledger per run, same reasoning as the tsdb above.
            REQTRACE.reset()
            REQTRACE.start()
        started = time.monotonic()
        downtime_phases: Dict[str, Any] = {}
        unattributed = 0.0
        slo_verdicts: Optional[Dict[str, Any]] = None
        profile_top: Optional[Dict[str, Any]] = None
        requests_report: Optional[Dict[str, Any]] = None
        try:
            self._drive(cs, sim, recorder, plans, started)
            # Let every planned node fault fire (and every flap recover)
            # before judging: a fault landing after the verdict would
            # un-settle jobs and make the final phase counts racy.
            if self._node_faults_planned():
                fault_deadline = time.monotonic() + (
                    self.chaos_profile.duration + 30.0)
                while (sim.pending_node_faults()
                       and time.monotonic() < fault_deadline):
                    time.sleep(0.05)
            converged = self._await_convergence(cs, tc, plans)
            # Harvest incident bundles BEFORE the GC sweep: deleting a
            # finished job makes the next sync forget its incident state.
            downtime_phases, unattributed = self._collect_downtime(plans)
            if self.request_obs:
                # Drain boundary: evict every batch still open on a live
                # pod (steady jobs keep serving until shutdown), THEN
                # reconcile submitted vs terminal ids.  Residue after that
                # means a death path dropped requests on the floor.
                sim.flush_open_requests()
                orphans = REQTRACE.reconcile(time.time())
                requests_report = self._collect_requests(plans, orphans)
            if self.slo_plane:
                # One final sweep + evaluation so short runs still get
                # verdicts from end-of-run data, then fold in what the run
                # actually produced: SLOBreach events in the store and
                # incident bundles stamped with a breached objective.
                TSDB.sample()
                SLOS.evaluate()
                slo_verdicts = SLOS.verdicts()
                slo_verdicts["breach_events"] = sum(
                    1 for ev in cs.events.list(None)
                    if ev.reason == constants.SLO_BREACH_REASON)
                slo_verdicts["stamped_bundles"] = sum(
                    1 for plan in plans
                    for bundle in (INCIDENTS.bundles(plan.key) or [])
                    if bundle.get("slo_breaches"))
            if self.with_profiler:
                profile_top = PROFILER.report(top=10)
            self._gc_sweep(cs, tc)
            wall = time.monotonic() - started
        finally:
            tc.stop()
            sim.stop()
            recorder.close()
            if monkey is not None:
                monkey.close()
            if self.slo_plane:
                SLOS.stop()
                TSDB.stop()
            if self.with_profiler:
                PROFILER.stop()
            if self.request_obs:
                REQTRACE.stop()
        if unattributed > 0.0:
            self.violations.append(
                f"incident recorder left {unattributed:.1f} ms of downtime "
                f"unattributed (phase 'unknown')")
        if 0.0 < self.max_wall_seconds < wall:
            self.violations.append(
                f"wall clock {wall:.1f}s exceeded the "
                f"{self.max_wall_seconds:.1f}s ceiling (sim kernel "
                f"{self.sim_kernel!r} regressed?)")

        sync_count = self._sync_count() - sync_count_before
        api_retries = int(self._counter_sum("trainingjob_api_retries_total")
                          - retries_before)
        restarts_total = int(self._counter_sum("trainingjob_restarts_total")
                             - restarts_before)
        chaos_report: Optional[Dict[str, Any]] = None
        if monkey is not None and chaos_plan is not None:
            chaos_report = {
                "seed": self.chaos_profile.seed,
                "plan_digest": chaos_plan.digest(),
                "faults": {k: int(v)
                           for k, v in sorted(monkey.faults.items())},
                "informer_relists": int(
                    self._counter_sum("trainingjob_informer_relists_total")
                    - relists_before),
            }
        phase_counts = self._phase_counts(cs)
        return FleetReport(
            jobs=len(plans),
            replicas_total=total_replicas,
            workers=self.workers,
            seed=self.profile.seed,
            converged=converged and not self.violations,
            violations=list(self.violations),
            wall_seconds=wall,
            sync_count=sync_count,
            reconciles_per_s=(sync_count / wall) if wall > 0 else 0.0,
            sim_kernel=self.sim_kernel,
            sim_events_total=sim.events_total,
            sim_events_per_s=(sim.events_total / wall) if wall > 0 else 0.0,
            sim_loop_passes=sim.loop_passes,
            sim_cpu_seconds=sim.loop_cpu_seconds,
            event_to_visible_ms=recorder.percentiles(),
            workqueue_depth_high_water=tc.work_queue.depth_high_water,
            workqueue_retries_total=tc.work_queue.retries_total,
            workqueue_coalesced_total=tc.work_queue.coalesced_total,
            phase_counts=phase_counts,
            downtime_phases=downtime_phases,
            unattributed_downtime_ms=unattributed,
            api_retries_total=api_retries,
            restarts_total=restarts_total,
            chaos=chaos_report,
            slo_verdicts=slo_verdicts,
            profile_top=profile_top,
            requests=requests_report,
        )

    @staticmethod
    def _collect_downtime(plans: List[JobPlan]
                          ) -> Tuple[Dict[str, Any], float]:
        """Aggregate every plan's retained incident bundles into per-fate
        per-phase p50/p99 ms, plus the total ``unknown`` residue."""
        by_fate: Dict[str, Dict[str, List[float]]] = {}
        counts: Dict[str, int] = {}
        unattributed = 0.0
        for plan in plans:
            bundles = INCIDENTS.bundles(plan.key)
            if not bundles:
                continue
            phases = by_fate.setdefault(plan.fate, {})
            for bundle in bundles:
                counts[plan.fate] = counts.get(plan.fate, 0) + 1
                for phase, ms in bundle["phases"].items():
                    phases.setdefault(phase, []).append(ms)
                # ``unknown`` residue overlapping a declared chaos window is
                # attributed to the fault plane, not left dangling: the ring
                # went dark because the apiserver (by design) did.
                residue = bundle["phases"].get("unknown", 0.0)
                if residue > 0.0:
                    residue = max(0.0, residue
                                  - bundle.get("chaos_overlap_ms", 0.0))
                unattributed += residue

        def pct(values: List[float], q: float) -> float:
            ordered = sorted(values)
            idx = min(int(q * len(ordered)), len(ordered) - 1)
            return round(ordered[idx], 3)

        report = {
            fate: {
                "count": counts.get(fate, 0),
                "phases": {phase: {"p50": pct(vals, 0.50),
                                   "p99": pct(vals, 0.99)}
                           for phase, vals in sorted(phases.items())
                           if any(v > 0.0 for v in vals) or phase == "unknown"},
            }
            for fate, phases in sorted(by_fate.items())
        }
        return report, unattributed

    def _collect_requests(self, plans: List[JobPlan],
                          orphans: int) -> Dict[str, Any]:
        """Request-plane verdict: the ledger rollup plus incident-bundle
        ``requests`` stanza coverage.  Nonzero orphans file a violation
        (mirror of ``unattributed_downtime_ms``); so does a restart
        incident whose window the ledger can still prove overlapped
        requests (re-running the finalizer's own overlap query) while its
        bundle carries no stanza.  A pod killed before its first serve
        tick genuinely overlapped nothing -- no stanza is correct there,
        not a hole."""
        if orphans > 0:
            self.violations.append(
                f"request audit ledger found {orphans} orphaned request(s) "
                f"(submitted but never terminal)")
        bundles_total = 0
        bundles_with_requests = 0
        for plan in plans:
            for bundle in (INCIDENTS.bundles(plan.key) or []):
                bundles_total += 1
                if bundle.get("requests"):
                    bundles_with_requests += 1
                elif plan.fate == FATE_POD_FAIL and REQTRACE.window(
                        plan.key, bundle["started"],
                        bundle["started"] + bundle["downtime_ms"] / 1e3):
                    self.violations.append(
                        f"{plan.key}: restart incident #{bundle['id']} "
                        f"overlapped in-flight requests but its bundle "
                        f"carries no requests stanza")
        report = REQTRACE.summary()
        report["orphaned_after_reconcile"] = orphans
        report["incident_bundles"] = bundles_total
        report["bundles_with_requests"] = bundles_with_requests
        return report

    @staticmethod
    def _sync_count() -> int:
        return int(METRICS.snapshot().get(
            "trainingjob_reconcile_latency_ms_count", 0))

    @staticmethod
    def _counter_sum(prefix: str) -> float:
        """Sum of every labeled counter series under ``prefix`` (counters
        render as ``name{label="..."}`` keys in the snapshot)."""
        return sum(v for k, v in METRICS.snapshot().items()
                   if k.startswith(prefix) and isinstance(v, (int, float)))

    def _node_faults_planned(self) -> bool:
        """True when the chaos profile draws any data-plane node faults."""
        p = self.chaos_profile
        return p is not None and bool(
            p.node_flaps or p.node_kills or p.domain_kills)

    # -- schedule driver -----------------------------------------------------

    def _drive(self, cs: Clientset, sim: SimRuntime,
               recorder: _LatencyRecorder, plans: List[JobPlan],
               started: float) -> None:
        events: List[Tuple[float, int, str, JobPlan]] = []
        seq = 0
        for plan in plans:
            heapq.heappush(events, (plan.create_at, seq, "create", plan))
            seq += 1
            if plan.disrupt_at > 0.0:
                heapq.heappush(events, (plan.disrupt_at, seq, plan.fate, plan))
                seq += 1

        fail_attempts: Dict[str, int] = {}
        fired = 0
        while events:
            at, _, kind, plan = heapq.heappop(events)
            if self.pace:
                delay = at - (time.monotonic() - started)
                if delay > 0:
                    time.sleep(delay)
            if kind == "create":
                recorder.mark_create(plan.key)
                cs.trainingjobs.create(build_job(
                    plan, self.with_ports,
                    node_fail_restart=self._node_faults_planned(),
                    request_obs=self.request_obs))
            elif kind == FATE_PREEMPT:
                self._fire_preempt(cs, recorder, plan)
            elif kind == FATE_DELETE:
                try:
                    cs.trainingjobs.delete(plan.namespace, plan.name)
                except NotFoundError:
                    self.violations.append(
                        f"{plan.key}: vanished before scheduled delete")
            elif kind == FATE_POD_FAIL:
                if not self._fire_pod_fail(cs, sim, recorder, plan):
                    # Target pod not Running yet (deep backlog at fleet
                    # scale): push the kill back a beat, for a long while.
                    attempts = fail_attempts.get(plan.key, 0) + 1
                    fail_attempts[plan.key] = attempts
                    if attempts * 0.25 >= self.converge_timeout:
                        self.violations.append(
                            f"{plan.key}: pod_fail target never became "
                            f"Running; kill not delivered")
                    else:
                        if not self.pace:
                            time.sleep(0.02)
                        retry_at = max(at, time.monotonic() - started) + 0.25
                        heapq.heappush(
                            events, (retry_at, seq, FATE_POD_FAIL, plan))
                        seq += 1
                    continue
            fired += 1
            if fired % 500 == 0:
                self._progress(f"fired {fired} churn events")

    def _fire_preempt(self, cs: Clientset, recorder: _LatencyRecorder,
                      plan: JobPlan) -> None:
        """Operator-level preemption: the PREEMPTED annotation asks the
        controller to drain the job into the Preempted phase."""
        for _ in range(100):
            try:
                job = cs.trainingjobs.get(plan.namespace, plan.name)
            except NotFoundError:
                self.violations.append(
                    f"{plan.key}: vanished before scheduled preemption")
                return
            job.metadata.annotations[TrainingJobPhase.PREEMPTED] = (
                "fleet churn: simulated capacity reclaim")
            recorder.mark_preempt(plan.key)
            try:
                cs.trainingjobs.update(job)
                return
            except ConflictError:
                continue  # controller won the write; re-read and retry
        self.violations.append(f"{plan.key}: preempt annotation never landed")

    def _fire_pod_fail(self, cs: Clientset, sim: SimRuntime,
                       recorder: _LatencyRecorder, plan: JobPlan) -> bool:
        """Kill one replica with exit 137 once it is actually Running (a
        kill before the kubelet starts the container is a no-op)."""
        pod_name = f"{plan.name}-{RTYPE}-{plan.fail_index}"
        try:
            pod = cs.pods.get(plan.namespace, pod_name)
        except NotFoundError:
            return False
        if pod.status.phase != PodPhase.RUNNING:
            return False
        recorder.mark_pod_fail(f"{plan.namespace}/{pod_name}")
        sim.preempt_pod(plan.namespace, pod_name, exit_code=137)
        return True

    # -- judgement -----------------------------------------------------------

    def _plan_state(self, cs: Clientset, plan: JobPlan
                    ) -> Tuple[bool, str]:
        """(settled?, describe-actual) for one plan."""
        try:
            job = cs.trainingjobs.get(plan.namespace, plan.name)
        except NotFoundError:
            if plan.fate == FATE_DELETE:
                return True, "deleted"
            return False, "missing"
        if plan.fate == FATE_DELETE:
            return False, f"still present in phase {job.status.phase!r}"
        phase = job.status.phase
        want = _SETTLED_PHASES[plan.fate]
        if phase not in want:
            return False, f"phase {phase!r}, want one of {want}"
        if plan.fate == FATE_POD_FAIL:
            restarts = job.status.restart_counts.get(RTYPE, 0)
            if restarts < 1:
                return False, f"Running but restart_counts={restarts}, want >=1"
        return True, phase

    def _await_convergence(self, cs: Clientset, tc: TrainingJobController,
                           plans: List[JobPlan]) -> bool:
        """Poll until every plan settles; on timeout, file one violation per
        unsettled plan (with the wedged-expectations detail when that is
        the reason it cannot make progress)."""
        deadline = time.monotonic() + self.converge_timeout
        unsettled = list(plans)
        while True:
            unsettled = [p for p in unsettled
                         if not self._plan_state(cs, p)[0]]
            if not unsettled:
                return True
            if time.monotonic() >= deadline:
                break
            self._progress(f"{len(unsettled)} jobs not settled yet")
            time.sleep(min(0.25, max(0.02, len(unsettled) / 2000.0)))
        for plan in unsettled[:50]:
            settled, actual = self._plan_state(cs, plan)
            if settled:
                continue
            detail = f"{plan.key} ({plan.fate}): {actual}"
            try:
                job = cs.trainingjobs.get(plan.namespace, plan.name)
                if not tc.satisfied_expectations(job):
                    detail += " [expectations wedged]"
            except NotFoundError:
                pass
            self.violations.append(detail)
        if len(unsettled) > 50:
            self.violations.append(
                f"... and {len(unsettled) - 50} more unsettled jobs")
        return False

    def _gc_sweep(self, cs: Clientset, tc: TrainingJobController) -> None:
        """Force a GC pass, let the sim finalize the deletions, then assert
        no pod outlives its owning job."""
        if tc._gc is not None:
            tc._gc.clean_garbage_pods()
        deadline = time.monotonic() + 15.0
        orphans: List[str] = []
        while time.monotonic() < deadline:
            orphans = self._orphan_pods(cs)
            if not orphans:
                return
            time.sleep(0.1)
        for key in orphans[:20]:
            self.violations.append(f"orphan pod after GC: {key}")
        if len(orphans) > 20:
            self.violations.append(f"... and {len(orphans) - 20} more orphans")

    @staticmethod
    def _orphan_pods(cs: Clientset) -> List[str]:
        live_jobs = {f"{j.metadata.namespace}/{j.metadata.name}"
                     for j in cs.trainingjobs.list(None)}
        orphans = []
        for pod in cs.pods.list(None):
            owner = pod.metadata.labels.get(constants.JOB_NAME_LABEL)
            if owner and f"{pod.metadata.namespace}/{owner}" not in live_jobs:
                orphans.append(f"{pod.metadata.namespace}/{pod.metadata.name}")
        return orphans

    @staticmethod
    def _phase_counts(cs: Clientset) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for job in cs.trainingjobs.list(None):
            phase = job.status.phase or "<none>"
            counts[phase] = counts.get(phase, 0) + 1
        return counts


def _env_opt_int(name: str) -> Optional[int]:
    """Int from the environment, or None when unset/garbled."""
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m trainingjob_operator_tpu.fleet.harness",
        description="Seeded churn run against the sim cluster; exits 0 only "
                    "if the fleet converged with zero invariant violations.")
    ap.add_argument("--jobs", type=int,
                    default=int(os.environ.get(constants.FLEET_JOBS_ENV, "200")))
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get(constants.FLEET_SEED_ENV, "0")))
    ap.add_argument("--duration", type=float, default=4.0,
                    help="Arrival window, seconds.")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--replicas-min", type=int, default=2)
    ap.add_argument("--replicas-max", type=int, default=12)
    ap.add_argument("--api-latency", type=float, default=0.0,
                    help="Injected per-write API latency for the controller, "
                         "seconds.")
    ap.add_argument("--no-pace", action="store_true",
                    help="Fire the schedule as fast as possible (backlog "
                         "saturation mode) instead of at its timestamps.")
    ap.add_argument("--converge-timeout", type=float, default=60.0)
    ap.add_argument("--resync-period", type=float, default=10.0)
    ap.add_argument("--gc-interval", type=float, default=10.0)
    ap.add_argument("--pods-per-node", type=int, default=64)
    ap.add_argument("--sim-kernel", choices=("event", "scan"), default=None,
                    help="Sim kubelet kernel (default: TRAININGJOB_SIM_KERNEL "
                         "or 'event').")
    ap.add_argument("--max-wall-seconds", type=float, default=0.0,
                    help="Fail the run (violation + nonzero exit) if wall "
                         "clock exceeds this; 0 disables.")
    ap.add_argument("--with-ports", action="store_true",
                    help="Give containers a port so per-index headless "
                         "Services are reconciled too.")
    ap.add_argument("--chaos", action="store_true",
                    help="Run the controller under a seeded control-plane "
                         "fault plan (docs/CHAOS.md): API errors/timeouts/"
                         "conflicts, latency spikes, watch drops, stale "
                         "lists.")
    ap.add_argument("--chaos-seed", type=int,
                    default=_env_opt_int(constants.CHAOS_SEED_ENV),
                    help="Chaos plan seed (default: TRAININGJOB_CHAOS_SEED, "
                         "else --seed).")
    ap.add_argument("--node-chaos", action="store_true",
                    help="Add seeded data-plane node faults to the plan "
                         "(implies --chaos): transient flaps that thaw, "
                         "permanent node kills, failure-domain kills.")
    ap.add_argument("--node-flaps", type=int, default=3,
                    help="Transient NotReady->recover flaps in the plan "
                         "(with --node-chaos).")
    ap.add_argument("--node-kills", type=int, default=1,
                    help="Permanent single-node kills in the plan.")
    ap.add_argument("--domain-kills", type=int, default=1,
                    help="Failure-domain kills (every node in one slice).")
    ap.add_argument("--nodes-per-slice", type=int, default=4,
                    help="Sim nodes per failure domain (slice label).")
    ap.add_argument("--slo", action="store_true",
                    help="Run the fleet SLO plane during the run "
                         "(docs/SLO.md): tsdb sweeper + burn-rate engine; "
                         "the report gains slo_verdicts.")
    ap.add_argument("--profile", action="store_true",
                    help="Run the sampling span profiler during the run; "
                         "the report gains profile_top (per-span CPU%%, "
                         "attribution ratio, overhead).")
    ap.add_argument("--request-obs", action="store_true",
                    help="Run the request-lifecycle plane (docs/SERVING.md): "
                         "jobs synthesize per-request records, the audit "
                         "ledger reconciles submitted vs terminal ids, and "
                         "the report gains a requests rollup (orphans file "
                         "violations).")
    ap.add_argument("--quiet", action="store_true",
                    help="Suppress progress lines; print only the report.")
    args = ap.parse_args(argv)

    profile = ChurnProfile(
        jobs=args.jobs, duration=args.duration, seed=args.seed,
        replicas=(args.replicas_min, args.replicas_max))
    chaos_profile = None
    if args.chaos or args.node_chaos:
        chaos_seed = (args.chaos_seed if args.chaos_seed is not None
                      else args.seed)
        # Fault windows cover the arrival window plus the settling tail so
        # drops/spikes land while the controller still has work in flight.
        node_kw: Dict[str, Any] = {}
        if args.node_chaos:
            node_kw = dict(node_flaps=args.node_flaps,
                           node_kills=args.node_kills,
                           domain_kills=args.domain_kills)
        chaos_profile = ChaosProfile(seed=chaos_seed,
                                     duration=args.duration + 2.0,
                                     **node_kw)
    progress = None if args.quiet else (
        lambda msg: print(f"[fleet] {msg}", file=sys.stderr, flush=True))
    harness = FleetHarness(
        profile, workers=args.workers, pace=not args.no_pace,
        api_latency=args.api_latency, converge_timeout=args.converge_timeout,
        resync_period=args.resync_period, gc_interval=args.gc_interval,
        pods_per_node=args.pods_per_node, with_ports=args.with_ports,
        sim_kernel=args.sim_kernel, max_wall_seconds=args.max_wall_seconds,
        chaos_profile=chaos_profile, nodes_per_slice=args.nodes_per_slice,
        slo_plane=args.slo, profiler=args.profile,
        request_obs=args.request_obs,
        progress=progress)
    report = harness.run()
    print(json.dumps(report.to_dict(), indent=2))
    return 0 if report.converged else 1


if __name__ == "__main__":
    sys.exit(main())
