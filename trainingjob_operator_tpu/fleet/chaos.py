"""Seeded chaos-schedule generation for the control-plane chaos plane.

The churn plane (``churn.py``) injects *workload*-shaped faults -- pods
die, jobs are preempted or deleted.  This module is its control-plane
twin: a ``ChaosProfile`` statistically describes how the *apiserver*
misbehaves (per-verb error rates, latency brownouts, watch-stream drops,
stale list reads), and ``ChaosGenerator`` expands it into a concrete
``ChaosPlan`` the same way ``ChurnGenerator`` expands a churn profile:
all randomness flows through one ``random.Random(seed)``, so the same
(profile, seed) pair reproduces the exact fault sequence byte-for-byte.
``ChaosPlan.digest()`` pins that property in `make chaos-smoke`.

Determinism shape: per-verb faults are *precomputed decision streams* --
decision ``i`` of the "update" stream applies to the ``i``-th update call,
whenever it happens to arrive.  That makes the fault sequence a pure
function of the seed and the call *order*, independent of wall-clock
timing, which is as deterministic as an injected-fault plane can be under
a threaded controller.  Time-shaped faults (latency windows, watch drops)
are scheduled on the run clock instead, like churn disruptions.

The *injection mechanics* (proxies that consume this plan) live in
``client/chaos.py``; this module is pure planning and is import-cheap.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from trainingjob_operator_tpu.api import constants

#: Verbs that get an independent fault-decision stream.  ``update`` and
#: ``update_status`` are the conflict-prone verbs; creates/deletes only
#: draw unavailable/timeout faults.
CHAOS_VERBS = ("create", "update", "update_status", "delete")

#: Fault kinds a per-verb decision can carry (besides "ok").
FAULT_UNAVAILABLE = "unavailable"   # 5xx-style ApiUnavailableError
FAULT_TIMEOUT = "timeout"           # deadline elapses, request not applied
FAULT_CONFLICT = "conflict"         # optimistic-concurrency conflict storm

#: Kinds whose watch streams can be dropped (the tracker keys watches by
#: object kind, so these are KIND strings, not resource names).
WATCHED_KINDS = (constants.KIND, "Pod", "Service")

#: Node-fault kinds (the data-plane stream, executed by the sim's event
#: kernel -- runtime/sim.py schedule_node_faults).
FAULT_NODE_FLAP = "node_flap"       # NotReady for `down` seconds, recovers
FAULT_NODE_DOWN = "node_down"       # one node dies permanently
FAULT_DOMAIN_DOWN = "domain_down"   # a whole slice's nodes die together


@dataclass(frozen=True)
class ChaosProfile:
    """Statistical description of control-plane misbehavior.  Frozen so a
    profile can be shared between a run and its replay."""

    seed: int = 0
    #: Seconds over which time-shaped faults (spikes, drops) are placed;
    #: match the churn profile's duration plus convergence slack.
    duration: float = 6.0
    #: Per-call probability of a transient 5xx on any write verb.
    error_rate: float = 0.02
    #: Per-call probability of a timeout on any write verb.
    timeout_rate: float = 0.01
    #: Extra per-call conflict probability on update/update_status.
    conflict_rate: float = 0.03
    #: Length of each verb's precomputed decision stream.  Calls beyond
    #: the stream succeed (the chaos window is over).
    decisions_per_verb: int = 20000
    #: Simulated server latency added to each timed-out call, seconds.
    timeout_hold: float = 0.05
    #: Count of latency brownout windows spread over ``duration``.
    latency_spikes: int = 3
    #: Per-call added latency inside a spike window, drawn uniformly.
    spike_delay: Tuple[float, float] = (0.01, 0.05)
    #: Width of each spike window, drawn uniformly.
    spike_duration: Tuple[float, float] = (0.2, 0.6)
    #: Watch-stream drops spread over ``duration`` (round-robin across
    #: WATCHED_KINDS so every informer takes at least one hit).
    watch_drops: int = 3
    #: Resumption gap after a drop before informers may reconnect --
    #: deltas committed inside the gap are exactly what the relist must
    #: recover.
    drop_gap: Tuple[float, float] = (0.05, 0.25)
    #: Per-call probability that a plain list() returns the previous
    #: (stale) snapshot for that kind, modeling a lagging follower read.
    stale_rate: float = 0.10
    #: Length of the stale-list decision stream.
    stale_decisions: int = 2000
    #: Data-plane node-fault streams (all default 0 = no node chaos, which
    #: keeps every pre-existing profile's plan byte-identical): transient
    #: NotReady flaps, permanent single-node deaths, and failure-domain
    #: kills that down every node sharing a slice label together.
    node_flaps: int = 0
    #: Seconds a flapped node stays NotReady, drawn uniformly.
    flap_down: Tuple[float, float] = (0.3, 0.9)
    node_kills: int = 0
    domain_kills: int = 0


@dataclass(frozen=True)
class NodeFault:
    at: float         # seconds from chaos attach
    kind: str         # FAULT_NODE_FLAP | FAULT_NODE_DOWN | FAULT_DOMAIN_DOWN
    #: Abstract victim id, resolved at schedule time against the sorted
    #: live node (or slice) list as ``target % len(candidates)`` -- the
    #: plan stays a pure function of the seed, never of cluster size.
    target: int
    down: float       # NotReady seconds for flaps; 0.0 for permanent kills


@dataclass(frozen=True)
class LatencySpike:
    start: float      # seconds from chaos attach
    end: float
    delay: float      # seconds added to each call inside the window


@dataclass(frozen=True)
class WatchDrop:
    at: float         # seconds from chaos attach
    gap: float        # seconds the stream stays down
    kind: str         # which WATCHED_KINDS stream dies


@dataclass(frozen=True)
class ChaosPlan:
    """A fully expanded, deterministic fault schedule."""

    profile: ChaosProfile
    #: verb -> tuple of decisions, each "ok" | FAULT_* .
    decisions: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    spikes: Tuple[LatencySpike, ...] = ()
    drops: Tuple[WatchDrop, ...] = ()
    #: Decision stream for stale list reads (True = serve stale).
    stale: Tuple[bool, ...] = ()
    #: Data-plane node faults, sorted by fire time.
    node_faults: Tuple[NodeFault, ...] = ()

    def canonical(self) -> str:
        """Canonical JSON of the full fault schedule (profile included):
        two plans are the same fault sequence iff their canonicals match."""
        doc = {
            "profile": {k: getattr(self.profile, k)
                        for k in sorted(self.profile.__dataclass_fields__)},
            "decisions": {v: list(d) for v, d in sorted(self.decisions.items())},
            "spikes": [[s.start, s.end, s.delay] for s in self.spikes],
            "drops": [[d.at, d.gap, d.kind] for d in self.drops],
            "stale": [int(b) for b in self.stale],
            "node_faults": [[f.at, f.kind, f.target, f.down]
                            for f in self.node_faults],
        }
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.sha256(self.canonical().encode()).hexdigest()


class ChaosGenerator:
    """Expands a :class:`ChaosProfile` into a deterministic ``ChaosPlan``."""

    def __init__(self, profile: ChaosProfile):
        self.profile = profile

    def plan(self) -> ChaosPlan:
        p = self.profile
        rng = random.Random(p.seed)

        decisions: Dict[str, Tuple[str, ...]] = {}
        for verb in CHAOS_VERBS:
            conflicty = verb in ("update", "update_status")
            stream: List[str] = []
            for _ in range(p.decisions_per_verb):
                roll = rng.random()
                if roll < p.error_rate:
                    stream.append(FAULT_UNAVAILABLE)
                elif roll < p.error_rate + p.timeout_rate:
                    stream.append(FAULT_TIMEOUT)
                elif conflicty and roll < (p.error_rate + p.timeout_rate
                                           + p.conflict_rate):
                    stream.append(FAULT_CONFLICT)
                else:
                    stream.append("ok")
            decisions[verb] = tuple(stream)

        spikes = tuple(sorted(
            (LatencySpike(
                start=(start := rng.uniform(0.0, p.duration)),
                end=start + rng.uniform(*p.spike_duration),
                delay=rng.uniform(*p.spike_delay),
            ) for _ in range(p.latency_spikes)),
            key=lambda s: s.start))

        drops = tuple(sorted(
            (WatchDrop(
                at=rng.uniform(0.0, p.duration),
                gap=rng.uniform(*p.drop_gap),
                kind=WATCHED_KINDS[i % len(WATCHED_KINDS)],
            ) for i in range(p.watch_drops)),
            key=lambda d: d.at))

        stale = tuple(rng.random() < p.stale_rate
                      for _ in range(p.stale_decisions))

        # Node-fault draws come LAST: appending streams never perturbs the
        # draws above, so a control-plane-only profile's plan stays
        # byte-identical to what the same seed produced before the
        # data-plane streams existed.
        node_faults: List[NodeFault] = []
        for _ in range(p.node_flaps):
            node_faults.append(NodeFault(
                at=rng.uniform(0.0, p.duration), kind=FAULT_NODE_FLAP,
                target=rng.randrange(1 << 16),
                down=rng.uniform(*p.flap_down)))
        for _ in range(p.node_kills):
            node_faults.append(NodeFault(
                at=rng.uniform(0.0, p.duration), kind=FAULT_NODE_DOWN,
                target=rng.randrange(1 << 16), down=0.0))
        for _ in range(p.domain_kills):
            node_faults.append(NodeFault(
                at=rng.uniform(0.0, p.duration), kind=FAULT_DOMAIN_DOWN,
                target=rng.randrange(1 << 16), down=0.0))
        node_faults.sort(key=lambda f: (f.at, f.kind, f.target))

        return ChaosPlan(profile=p, decisions=decisions,
                         spikes=spikes, drops=drops, stale=stale,
                         node_faults=tuple(node_faults))
