"""Seeded churn-schedule generation for the fleet harness.

A ``ChurnProfile`` describes a fleet statistically (how many jobs, how fast
they arrive, how wide they are, what fraction get disrupted and how); a
``ChurnGenerator`` expands it into a concrete, fully deterministic schedule
of ``JobPlan``s.  All randomness flows through one ``random.Random(seed)``
so the same (profile, seed) pair always produces byte-identical plans --
the property the determinism test and `make fleet-smoke` rely on.

Arrivals are a Poisson process normalized onto ``[0, duration]``: draw
exponential inter-arrival gaps, then rescale the cumulative times so the
last job lands at ``duration``.  Normalizing (instead of tuning a rate)
keeps the wall-clock envelope of a run independent of the job count.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

#: The disruption fates a planned job can be assigned.
FATE_COMPLETE = "complete"    # runs to Succeed on its own
FATE_STEADY = "steady"        # runs "forever"; must settle at Running
FATE_PREEMPT = "preempt"      # operator-level preemption via annotation
FATE_POD_FAIL = "pod_fail"    # one pod killed with 137; EXIT_CODE restart
FATE_DELETE = "delete"        # client deletes the CR mid-flight

FATES = (FATE_COMPLETE, FATE_STEADY, FATE_PREEMPT, FATE_POD_FAIL, FATE_DELETE)


@dataclass(frozen=True)
class ChurnProfile:
    """Statistical description of a fleet run.  Frozen so a profile can be
    shared between a run and its replay without aliasing surprises."""

    jobs: int = 200
    #: Seconds over which creates arrive (Poisson, normalized).
    duration: float = 4.0
    seed: int = 0
    #: Replica width drawn uniformly from this inclusive range.
    replicas: Tuple[int, int] = (2, 12)
    #: run-seconds annotation range for completing jobs.
    run_seconds: Tuple[float, float] = (0.05, 0.4)
    #: run-seconds for jobs that must still be Running at the end.
    steady_run_seconds: float = 3600.0
    #: Seconds after a job's create at which its disruption (preempt /
    #: pod_fail / delete) fires, drawn uniformly.
    disruption_delay: Tuple[float, float] = (0.3, 1.2)
    #: Relative fate weights; zero removes a fate from the draw.
    fate_weights: Dict[str, float] = field(default_factory=lambda: {
        FATE_COMPLETE: 0.45,
        FATE_STEADY: 0.15,
        FATE_PREEMPT: 0.12,
        FATE_POD_FAIL: 0.18,
        FATE_DELETE: 0.10,
    })
    namespace: str = "default"

    def total_replicas(self) -> int:
        """Upper bound used for capacity provisioning (exact total comes
        from the generated plan)."""
        return self.jobs * self.replicas[1]


@dataclass(frozen=True)
class JobPlan:
    """One job's concrete fate.  Everything the harness needs to create,
    disrupt, and later judge the job is pinned here at plan time."""

    name: str
    namespace: str
    create_at: float          # seconds from run start
    replicas: int
    fate: str
    run_seconds: float
    #: When the disruption fires (absolute, seconds from run start);
    #: 0.0 for fates without one.
    disrupt_at: float = 0.0
    #: Replica index the pod_fail fate kills.
    fail_index: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


class ChurnGenerator:
    """Expands a :class:`ChurnProfile` into a deterministic ``JobPlan`` list."""

    def __init__(self, profile: ChurnProfile):
        self.profile = profile

    def plan(self) -> List[JobPlan]:
        p = self.profile
        rng = random.Random(p.seed)
        arrivals = self._arrival_times(rng, p.jobs, p.duration)
        fates = [f for f in FATES if p.fate_weights.get(f, 0.0) > 0.0]
        weights = [p.fate_weights[f] for f in fates]

        plans: List[JobPlan] = []
        for i, at in enumerate(arrivals):
            fate = rng.choices(fates, weights=weights, k=1)[0]
            replicas = rng.randint(*p.replicas)
            if fate == FATE_COMPLETE:
                run_seconds = rng.uniform(*p.run_seconds)
            else:
                # Disrupted and steady jobs must outlive the run on their
                # own -- the schedule, not the workload, ends them.
                run_seconds = p.steady_run_seconds
            disrupt_at = 0.0
            fail_index = 0
            if fate in (FATE_PREEMPT, FATE_POD_FAIL, FATE_DELETE):
                disrupt_at = at + rng.uniform(*p.disruption_delay)
                if fate == FATE_POD_FAIL:
                    fail_index = rng.randrange(replicas)
            plans.append(JobPlan(
                name=f"fleet-{p.seed}-{i:05d}",
                namespace=p.namespace,
                create_at=at,
                replicas=replicas,
                fate=fate,
                run_seconds=run_seconds,
                disrupt_at=disrupt_at,
                fail_index=fail_index,
            ))
        return plans

    @staticmethod
    def _arrival_times(rng: random.Random, n: int, duration: float) -> List[float]:
        if n <= 0:
            return []
        gaps = [rng.expovariate(1.0) for _ in range(n)]
        total = 0.0
        times = []
        for g in gaps:
            total += g
            times.append(total)
        if total <= 0.0:
            return [0.0] * n
        scale = duration / total
        return [t * scale for t in times]
