"""Fleet-scale churn harness: drive thousands of concurrent TPUTrainingJobs
over the sim runtime with a seeded disruption schedule and assert the control
plane converges (docs/FLEET.md)."""

from trainingjob_operator_tpu.fleet.churn import ChurnGenerator, ChurnProfile, JobPlan

__all__ = [
    "ChurnGenerator",
    "ChurnProfile",
    "JobPlan",
    "FleetHarness",
    "FleetReport",
]


def __getattr__(name):
    # Lazy: `python -m trainingjob_operator_tpu.fleet.harness` would otherwise
    # trip runpy's found-in-sys.modules warning via an eager import here.
    if name in ("FleetHarness", "FleetReport"):
        from trainingjob_operator_tpu.fleet import harness
        return getattr(harness, name)
    raise AttributeError(name)
