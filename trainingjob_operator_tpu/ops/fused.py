"""Small fused Pallas kernels: RMSNorm.

RMSNorm is HBM-bandwidth bound; the fused kernel reads each row once, keeps
the reduction in VMEM (f32), and writes once -- no intermediate mean-square
array round-trips to HBM.  Backward rematerializes through the XLA reference
(same math).  Off TPU the entrypoint dispatches to the reference
(ops.use_pallas); TRAININGJOB_PALLAS=interpret exercises the real kernel on
CPU.
"""

from __future__ import annotations

import functools

import jax


def _rmsnorm_kernel(x_ref, scale_ref, o_ref, *, eps: float):
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.float32)                     # [BR, D]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps) * scale_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _rmsnorm_forward(x2d, scale, *, eps: float, block_rows: int,
                     interpret: bool):
    from jax.experimental import pallas as pl

    rows, d = x2d.shape
    block_rows = min(block_rows, rows)
    kernel = functools.partial(_rmsnorm_kernel, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(x2d, scale)


def _reference(x, scale, *, eps: float):
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)
            * scale.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x, scale, eps):
    from trainingjob_operator_tpu.ops import pallas_interpret, use_pallas

    if not use_pallas():
        return _reference(x, scale, eps=eps)
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    rows = x2d.shape[0]
    block = rows
    for candidate in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if rows % candidate == 0:
            block = candidate
            break
    out = _rmsnorm_forward(x2d, scale, eps=eps, block_rows=block,
                           interpret=pallas_interpret())
    return out.reshape(shape)


def _rmsnorm_fwd(x, scale, eps):
    return _rmsnorm(x, scale, eps), (x, scale)


def _rmsnorm_bwd(eps, res, g):
    x, scale = res
    _, vjp = jax.vjp(lambda x_, s_: _reference(x_, s_, eps=eps), x, scale)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def rmsnorm(x, scale, eps: float = 1e-5):
    """Fused RMSNorm over the last axis; differentiable, dtype-preserving."""
    return _rmsnorm(x, scale, float(eps))
