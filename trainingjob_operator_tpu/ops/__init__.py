"""TPU kernels (Pallas) with XLA fallbacks.

The compute path of this framework is XLA; these kernels cover the spots
where hand-scheduling beats the compiler -- flash attention (VMEM-resident
softmax statistics, no [T, T] materialization in HBM) and small fusions.
Every op dispatches: Pallas on TPU, numerically-identical XLA reference
elsewhere (CPU tests, interpret mode), so call sites never branch.

Reference parity note: the reference operator has no kernels (it is a Go
control plane, SURVEY.md §0); this package exists because the TPU build owns
the workload layer too (SURVEY.md §7).
"""

import os

from trainingjob_operator_tpu.api import constants


def use_pallas() -> bool:
    """Pallas on real TPU unless explicitly disabled; interpret mode when
    TRAININGJOB_PALLAS=interpret (testing the kernels off-TPU)."""
    mode = os.environ.get(constants.PALLAS_ENV, "auto")
    if mode in ("0", "off"):
        return False
    if mode == "interpret":
        return True
    import jax

    return jax.default_backend() == "tpu"


def pallas_interpret() -> bool:
    import jax

    return (os.environ.get(constants.PALLAS_ENV) == "interpret"
            or jax.default_backend() != "tpu")


from trainingjob_operator_tpu.ops.flash_attention import flash_attention  # noqa: E402,F401
from trainingjob_operator_tpu.ops.fused import rmsnorm  # noqa: E402,F401
