"""Flash attention as Pallas TPU kernels -- forward AND backward.

Online-softmax attention: for each query block the forward kernel streams KV
blocks through VMEM, keeping running max/denominator statistics in f32 -- the
[T, T] score matrix never exists in HBM, so HBM traffic is O(T*D) instead of
O(T^2) and the block matmuls stay on the MXU.  GQA maps query head h to KV
head h // (Hq/Hkv) in the BlockSpec index map, so grouped KV is never
repeated in memory.  Causal query blocks stop their KV loop at the diagonal
(no wasted blocks above it).

Backward is the FlashAttention-2 scheme as two Pallas kernels: probabilities
are recomputed blockwise in VMEM from the saved log-sum-exp (never saved to
HBM), accumulation in f32.  The dQ kernel iterates KV blocks per query block;
the dK/dV kernel iterates query blocks per KV block (starting at the causal
diagonal), producing per-query-head dK/dV that are group-summed for GQA.
``delta = rowsum(dO * O)`` is the one cheap XLA precomputation.

For sequence-parallel long context, use parallel/ringattention.py; this
kernel is the single-device fast path the ring's per-step block computation
mirrors.

Off TPU the public entrypoint dispatches to the same-math XLA reference
(ops.use_pallas), and TRAININGJOB_PALLAS=interpret runs the real kernels in
interpreter mode for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

NEG_INF = -1e30
#: TPU lane width: per-row softmax stats cross the kernel boundary lane-
#: replicated as [..., T, LANE] because Mosaic tiles the last two block dims.
LANE = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
            block_k: int, padded_len: int, kv_len: int, scale: float,
            causal: bool, window: int):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [BQ, D]
    bq, d = q.shape

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # KV blocks strictly above the diagonal contribute nothing; the last
        # needed block is the one holding column (qi+1)*block_q - 1 (ceil
        # division -- counting from the block *start* under-counts whenever
        # block_q % block_k != 0 and skips diagonal blocks).
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = padded_len // block_k
    # Sliding window: row i attends cols (i - window, i]; KV blocks wholly
    # left of the window never enter the loop -- attention work per query
    # becomes O(window), not O(T).
    start_kb = (jnp.maximum(qi * block_q - window + 1, 0) // block_k
                if (causal and window) else 0)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = cols < kv_len  # padded key rows never attend
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            valid = jnp.logical_and(valid, cols <= rows)
            if window:
                valid = jnp.logical_and(valid, cols > rows - window)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        correction = jnp.exp(m - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = l * correction + p.sum(axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(start_kb, num_kb, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # Log-sum-exp per query row: the only softmax statistic the backward
    # kernels need to recompute probabilities exactly.  Lane-replicated to
    # [BQ, 128] -- Mosaic requires the last two block dims tiled (8, 128),
    # which a [.., BQ] vector layout cannot satisfy.
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(jnp.maximum(l, 1e-30)),
                                     (m.shape[0], LANE))


def _pad_seq(x, padded: int):
    import jax.numpy as jnp

    T = x.shape[2]
    if padded == T:
        return x
    width = [(0, 0)] * x.ndim
    width[2] = (0, padded - T)
    return jnp.pad(x, width)


def _padded_len(T: int, block_q: int, block_k: int) -> int:
    import math

    step = math.lcm(block_q, block_k)
    return math.ceil(T / step) * step


def _flash_forward(q, k, v, *, scale: float, causal: bool,
                   block_q: int, block_k: int, interpret: bool,
                   window: int = 0):
    """q: [B, Hq, T, D]; k/v: [B, Hkv, T, D] -> (out [B, Hq, T, D],
    lse [B, Hq, T] f32)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Hkv = k.shape[1]
    group = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)

    # Pad the sequence up to the block grid; padded key positions are masked
    # inside the kernel (cols < kv_len), padded query rows are sliced off.
    padded = _padded_len(T, block_q, block_k)
    q = _pad_seq(q, padded)
    k = _pad_seq(k, padded)
    v = _pad_seq(v, padded)

    grid = (B, H, padded // block_q)
    kernel = functools.partial(_kernel, block_q=block_q, block_k=block_k,
                               padded_len=padded, kv_len=T, scale=scale,
                               causal=causal, window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, padded, D),
                         lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, padded, D),
                         lambda b, h, i: (b, h // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANE), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, padded, LANE), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :T, :], lse[:, :, :T, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_q: int, block_k: int, padded_len: int,
                   kv_len: int, scale: float, causal: bool, window: int):
    """dQ for one query block: stream KV blocks, recompute p from lse."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)          # [BQ, D]
    do = do_ref[0, 0].astype(jnp.float32)        # [BQ, D]
    lse = lse_ref[0, 0][:, 0:1]                  # [BQ, 1] f32 (lane 0)
    delta = delta_ref[0, 0][:, 0:1]              # [BQ, 1] f32
    bq, d = q.shape

    if causal:
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = padded_len // block_k
    start_kb = (jnp.maximum(qi * block_q - window + 1, 0) // block_k
                if (causal and window) else 0)

    def body(kb, dq):
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        z = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = cols < kv_len
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            valid = jnp.logical_and(valid, cols <= rows)
            if window:
                valid = jnp.logical_and(valid, cols > rows - window)
        p = jnp.where(valid, jnp.exp(z - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        dz = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            dz, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    dq = jax.lax.fori_loop(start_kb, num_kb, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, block_k: int,
                    padded_len: int, kv_len: int, scale: float, causal: bool,
                    window: int, group: int):
    """dK/dV for one KV block: stream query blocks from the causal diagonal
    down.  The grid runs over KV heads; the GQA group's query heads are
    accumulated here in VMEM, so only [B, Hkv, T, D] ever reaches HBM."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)          # [BK, D]
    v = v_ref[0, 0].astype(jnp.float32)          # [BK, D]
    bk, d = k.shape

    num_qb = padded_len // block_q
    # First query block intersecting the diagonal: earlier blocks are fully
    # above it (all rows < first col of this KV block) and contribute 0.
    qb_start = (ki * block_k) // block_q if causal else 0
    if causal and window:
        # Last query row this KV block can serve is its last col + window-1;
        # later q blocks are wholly outside the band.
        num_qb = jnp.minimum(
            num_qb, (ki * block_k + block_k + window - 2) // block_q + 1)

    def body(qb, carry):
        dk, dv = carry
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 1)
        valid = cols < kv_len
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            valid = jnp.logical_and(valid, cols <= rows)
            if window:
                valid = jnp.logical_and(valid, cols > rows - window)
        for g in range(group):  # static unroll over the GQA group
            q = q_ref[0, g, pl.ds(qb * block_q, block_q), :].astype(
                jnp.float32)
            do = do_ref[0, g, pl.ds(qb * block_q, block_q), :].astype(
                jnp.float32)
            lse = lse_ref[0, g, pl.ds(qb * block_q, block_q), 0:1]
            delta = delta_ref[0, g, pl.ds(qb * block_q, block_q), 0:1]
            z = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [BQ, BK]
            p = jnp.where(valid, jnp.exp(z - lse), 0.0)       # [BQ, BK]
            dv = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [BK, D]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # [BQ, BK]
            dz = p * (dp - delta) * scale
            dk = dk + jax.lax.dot_general(
                dz, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [BK, D]
        return dk, dv

    zero = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, num_qb, body, (zero, zero))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, lse, g, *, scale: float, causal: bool,
                    block_q: int, block_k: int, interpret: bool, delta,
                    window: int = 0):
    """Pallas backward: q/g [B, H, T, D], k/v [B, Hkv, T, D], lse/delta
    [B, H, T] f32 -> (dq, dk, dv) in the input dtypes/shapes."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Hkv = k.shape[1]
    group = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    padded = _padded_len(T, block_q, block_k)

    qp, kp, vp, gp = (_pad_seq(x, padded) for x in (q, k, v, g))
    # Padded rows carry lse=0/delta=0 and zero dO, so every gradient
    # contribution from them vanishes (p*0 or 0@...).  Stats are lane-
    # replicated to [.., T, 128] at the kernel boundary (Mosaic tiling).
    lsep = jnp.broadcast_to(_pad_seq(lse[..., None], padded),
                            (B, H, padded, LANE))
    deltap = jnp.broadcast_to(_pad_seq(delta[..., None], padded),
                              (B, H, padded, LANE))

    common = dict(block_q=block_q, block_k=block_k, padded_len=padded,
                  kv_len=T, scale=scale, causal=causal, window=window)

    q_blocked = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0))
    kv_full = pl.BlockSpec((1, 1, padded, D),
                           lambda b, h, i: (b, h // group, 0, 0))
    stat_blocked = pl.BlockSpec((1, 1, block_q, LANE),
                                lambda b, h, i: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B, H, padded // block_q),
        in_specs=[q_blocked, kv_full, kv_full, q_blocked, stat_blocked,
                  stat_blocked],
        out_specs=q_blocked,
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)

    # dK/dV gridded over KV heads; the block index h covers query heads
    # [h*group, (h+1)*group) (contiguous under the h // group GQA mapping),
    # accumulated inside the kernel so HBM only ever sees [B, Hkv, T, D].
    qgrp_full = pl.BlockSpec((1, group, padded, D),
                             lambda b, h, i: (b, h, 0, 0))
    kv_blocked = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, 0))
    statgrp_full = pl.BlockSpec((1, group, padded, LANE),
                                lambda b, h, i: (b, h, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common, group=group),
        grid=(B, Hkv, padded // block_k),
        in_specs=[qgrp_full, kv_blocked, kv_blocked, qgrp_full, statgrp_full,
                  statgrp_full],
        out_specs=[kv_blocked, kv_blocked],
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, padded, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Hkv, padded, D), v.dtype)],
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)

    return dq[:, :, :T, :], dk[:, :, :T, :], dv[:, :, :T, :]


def _scores(q, k, *, scale: float, causal: bool, window: int = 0):
    """Masked f32 score matrix [B, H, Tq, Tk] (GQA keys repeated)."""
    import jax.numpy as jnp

    H, T = q.shape[1], q.shape[2]
    Hkv = k.shape[1]
    if H != Hkv:
        k = jnp.repeat(k, H // Hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        if window:
            # Banded: row i sees cols (i - window, i].
            mask = jnp.logical_and(mask, ~jnp.tril(
                jnp.ones((T, T), bool), -window))
        s = jnp.where(mask[None, None], s, NEG_INF)
    return s


def _reference(q, k, v, *, scale: float, causal: bool, window: int = 0):
    """Same math in plain XLA (f32 softmax statistics); [B, H, T, D]."""
    import jax.numpy as jnp

    H = q.shape[1]
    Hkv = v.shape[1]
    if H != Hkv:
        v = jnp.repeat(v, H // Hkv, axis=1)
    s = _scores(q, k, scale=scale, causal=causal, window=window)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _reference_lse(q, k, *, scale: float, causal: bool, window: int = 0):
    """Log-sum-exp rows of the reference scores -- [B, H, T] f32 (matches the
    forward kernel's second output)."""
    import jax.numpy as jnp

    s = _scores(q, k, scale=scale, causal=causal, window=window)
    m = s.max(-1)
    return m + jnp.log(jnp.exp(s - m[..., None]).sum(-1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, window):
    from trainingjob_operator_tpu.ops import pallas_interpret, use_pallas

    if use_pallas():
        out, _ = _flash_forward(q, k, v, scale=scale, causal=causal,
                                block_q=block_q, block_k=block_k,
                                interpret=pallas_interpret(), window=window)
        return out
    return _reference(q, k, v, scale=scale, causal=causal, window=window)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, window):
    from trainingjob_operator_tpu.ops import pallas_interpret, use_pallas

    if use_pallas():
        out, lse = _flash_forward(q, k, v, scale=scale, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  interpret=pallas_interpret(),
                                  window=window)
    else:
        out = _reference(q, k, v, scale=scale, causal=causal, window=window)
        lse = _reference_lse(q, k, scale=scale, causal=causal,
                             window=window)
    # Remat anchors ON THE RESIDUALS: under save_only_these_names("attn_out")
    # the backward reloads (out, lse) instead of re-running the quadratic
    # attention forward.  Tagging a tensor derived downstream of this
    # custom_vjp call would not help -- the residuals are what the backward
    # consumes, so they are what the policy must be able to save.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_out")
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, window, res, g):
    from trainingjob_operator_tpu.ops import pallas_interpret, use_pallas

    q, k, v, out, lse = res
    if use_pallas():
        import jax.numpy as jnp

        # delta = rowsum(dO * O): the only precomputation the FA-2 backward
        # needs beyond lse; cheap elementwise XLA.
        delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
        return _flash_backward(q, k, v, lse, g, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               interpret=pallas_interpret(), delta=delta,
                               window=window)
    # Off TPU: rematerialize through the reference (identical math).
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference(q_, k_, v_, scale=scale, causal=causal,
                                      window=window),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def default_blocks() -> "tuple[int, int]":
    """(block_q, block_k) defaults, overridable via TRAININGJOB_FA_BLOCK_Q/K
    (read at trace time; the on-chip tuner sweeps these without code edits)."""
    import os

    from trainingjob_operator_tpu.api import constants

    bq = int(os.environ.get(constants.FA_BLOCK_Q_ENV, "0") or 0)
    bk = int(os.environ.get(constants.FA_BLOCK_K_ENV, "0") or 0)
    return (bq or 128, bk or 128)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    window: int = 0):
    """Flash attention over [B, T, H, D] tensors (GQA: k/v may have fewer
    heads).  Pallas on TPU, XLA reference elsewhere; differentiable.

    ``window`` > 0 (causal only) restricts row i to keys (i - window, i]
    -- Mistral-style sliding-window attention.  The kernels skip KV blocks
    wholly outside the band, so attention work per query is O(window)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window and not causal:
        raise ValueError("window requires causal attention")
    dq, dk = default_blocks()
    block_q = block_q or dq
    block_k = block_k or dk
    # Kernel layout is [B, H, T, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, float(scale), causal, block_q, block_k,
                 int(window))
    return out.transpose(0, 2, 1, 3)


def attention_xla(q, k, v, *, causal: bool = True,
                  scale: Optional[float] = None, window: int = 0):
    """Identical-math attention on the pure-XLA path, [B, T, H, D].

    For contexts where a Pallas custom call cannot appear: inside shard_map
    bodies with ``auto`` axes (the pp pipeline -- GSPMD cannot partition an
    opaque custom call over the auto axes, but it partitions these einsums
    fine).  Differentiable via plain autodiff.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window and not causal:
        # Same contract as flash_attention: a silently ignored window would
        # compute the wrong attention pattern with no error.
        raise ValueError("window requires causal attention")
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _reference(qt, kt, vt, scale=float(scale), causal=causal,
                     window=window)
    return out.transpose(0, 2, 1, 3)


def flash_attention_pp(q, k, v, mesh, *, causal: bool = True,
                       scale: Optional[float] = None,
                       block_q: Optional[int] = None,
                       block_k: Optional[int] = None, window: int = 0):
    """Flash attention inside the gpipe stage body (models/llama.py pp path).

    The stage body already runs under a shard_map manual over ONLY ``pp``
    (parallel/pipeline.py): dp/fsdp/tp are still AUTO there, and a Pallas
    custom call is opaque to GSPMD -- so the kernel enters manual mode for
    those axes too via a NESTED partial-manual shard_map that takes its mesh
    from context (passing the concrete mesh again would clash with the
    outer abstract mesh, whose pp axis is already Manual).

    Falls back to the identical-math ``attention_xla`` when the runtime has
    no partial-manual shard_map, when the local microbatch/heads don't tile
    over the data/tp axes, or when the sequence is sp-sharded (local-T
    attention would be wrong math; GSPMD's gathers around the einsums are
    the correct fallback).  q: [B, T, Hq, D]; k/v: [B, T, Hkv, D].
    """
    import math

    from jax.sharding import PartitionSpec as P

    from trainingjob_operator_tpu.parallel.pipeline import (
        partial_manual_shard_map)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    tp = "tp" if "tp" in mesh.axis_names else None
    manual = frozenset(data_axes + ((tp,) if tp else ()))
    if not manual or all(mesh.shape[a] == 1 for a in manual):
        # pp is the only partitioned axis: the outer shard_map already made
        # everything per-shard, the kernel can run directly.
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               window=window)
    shmap = partial_manual_shard_map()
    n_data = math.prod(mesh.shape[a] for a in data_axes) if data_axes else 1
    n_tp = mesh.shape[tp] if tp else 1
    sp_sharded = "sp" in mesh.axis_names and mesh.shape["sp"] > 1
    if (shmap is None or sp_sharded or q.shape[0] % n_data
            or q.shape[2] % n_tp or k.shape[2] % n_tp):
        return attention_xla(q, k, v, causal=causal, scale=scale,
                             window=window)
    batch = (data_axes if len(data_axes) > 1
             else (data_axes[0] if data_axes else None))
    spec = P(batch, None, tp, None)
    fn = shmap(
        functools.partial(flash_attention, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, window=window),
        in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=manual, check_vma=False)
    return fn(q, k, v)


def flash_attention_sharded(q, k, v, mesh, *, causal: bool = True,
                            scale: Optional[float] = None,
                            block_q: Optional[int] = None,
                            block_k: Optional[int] = None,
                            window: int = 0):
    """Flash attention under a dp/fsdp x tp mesh via shard_map.

    A Pallas kernel is an opaque custom call to GSPMD, so it must run
    per-shard: batch is sharded over the data axes, heads over tp (attention
    is head-independent, and contiguous head blocks keep the GQA
    query->kv-head mapping local to the shard).  q/k/v: [B, T, H, D] global.
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map

        compat = {"check_vma": False}
    except ImportError:  # jax < 0.8
        from jax.experimental.shard_map import shard_map

        compat = {"check_rep": False}

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch = data_axes if len(data_axes) > 1 else (
        data_axes[0] if data_axes else None)
    tp = "tp" if "tp" in mesh.axis_names else None
    spec = P(batch, None, tp, None)

    fn = shard_map(
        functools.partial(flash_attention, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **compat)
    return fn(q, k, v)
