"""Flash attention as a Pallas TPU kernel.

Online-softmax attention: for each query block the kernel streams KV blocks
through VMEM, keeping running max/denominator statistics in f32 -- the [T, T]
score matrix never exists in HBM, so HBM traffic is O(T*D) instead of O(T^2)
and the block matmuls stay on the MXU.  GQA maps query head h to KV head
h // (Hq/Hkv) in the BlockSpec index map, so grouped KV is never repeated in
memory.  Causal query blocks stop their KV loop at the diagonal (no wasted
blocks above it).

Backward is rematerialized through the XLA reference implementation (exact
same math) -- the standard trade: recompute the O(T^2) probabilities at
higher FLOPs rather than save them.  For sequence-parallel long context, use
parallel/ringattention.py instead; this kernel is the single-device fast
path the ring's per-step block computation mirrors.

Off TPU the public entrypoint dispatches to the same-math XLA reference
(ops.use_pallas), and TRAININGJOB_PALLAS=interpret runs the real kernel in
interpreter mode for CPU tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
            padded_len: int, kv_len: int, scale: float, causal: bool):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [BQ, D]
    bq, d = q.shape

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    if causal:
        # KV blocks strictly above the diagonal contribute nothing.
        num_kb = (qi * block_q) // block_k + pl.cdiv(block_q, block_k)
    else:
        num_kb = padded_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = cols < kv_len  # padded key rows never attend
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            valid = jnp.logical_and(valid, cols <= rows)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        correction = jnp.exp(m - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_new = l * correction + p.sum(axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, scale: float, causal: bool,
                   block_q: int, block_k: int, interpret: bool):
    """q: [B, Hq, T, D]; k/v: [B, Hkv, T, D] -> [B, Hq, T, D]."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Hkv = k.shape[1]
    group = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)

    # Pad the sequence up to the block grid; padded key positions are masked
    # inside the kernel (cols < kv_len), padded query rows are sliced off.
    import math

    step = math.lcm(block_q, block_k)
    padded = math.ceil(T / step) * step
    if padded != T:
        width = ((0, 0), (0, 0), (0, padded - T), (0, 0))
        q = jnp.pad(q, width)
        k = jnp.pad(k, width)
        v = jnp.pad(v, width)

    grid = (B, H, padded // block_q)
    kernel = functools.partial(_kernel, block_q=block_q, block_k=block_k,
                               padded_len=padded, kv_len=T, scale=scale,
                               causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, padded, D),
                         lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, padded, D),
                         lambda b, h, i: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :T, :] if padded != T else out


def _reference(q, k, v, *, scale: float, causal: bool):
    """Same math in plain XLA (f32 softmax statistics); [B, H, T, D]."""
    import jax.numpy as jnp

    B, H, T, D = q.shape
    Hkv = k.shape[1]
    if H != Hkv:
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    from trainingjob_operator_tpu.ops import pallas_interpret, use_pallas

    if use_pallas():
        return _flash_forward(q, k, v, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              interpret=pallas_interpret())
    return _reference(q, k, v, scale=scale, causal=causal)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    return _flash(q, k, v, scale, causal, block_q, block_k), (q, k, v)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v = res
    # Rematerialize through the reference (identical math): trades O(T^2)
    # recompute FLOPs for not saving the probability matrix.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference(q_, k_, v_, scale=scale, causal=causal),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Flash attention over [B, T, H, D] tensors (GQA: k/v may have fewer
    heads).  Pallas on TPU, XLA reference elsewhere; differentiable."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # Kernel layout is [B, H, T, D].
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, float(scale), causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3)


def flash_attention_sharded(q, k, v, mesh, *, causal: bool = True,
                            scale: Optional[float] = None,
                            block_q: int = 128, block_k: int = 128):
    """Flash attention under a dp/fsdp x tp mesh via shard_map.

    A Pallas kernel is an opaque custom call to GSPMD, so it must run
    per-shard: batch is sharded over the data axes, heads over tp (attention
    is head-independent, and contiguous head blocks keep the GQA
    query->kv-head mapping local to the shard).  q/k/v: [B, T, H, D] global.
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map

        compat = {"check_vma": False}
    except ImportError:  # jax < 0.8
        from jax.experimental.shard_map import shard_map

        compat = {"check_rep": False}

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch = data_axes if len(data_axes) > 1 else (
        data_axes[0] if data_axes else None)
    tp = "tp" if "tp" in mesh.axis_names else None
    spec = P(batch, None, tp, None)

    fn = shard_map(
        functools.partial(flash_attention, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **compat)
    return fn(q, k, v)
