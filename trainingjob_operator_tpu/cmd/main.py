"""Operator entrypoint.

Reference: cmd/main.go:11-23 + cmd/app/server.go:26-109 -- parse flags, build
clients/informers/controller, optionally leader-elect, run until signaled.

Usage:
    python -m trainingjob_operator_tpu.cmd.main --backend localproc \\
        --apply examples/mnist-cpu.yaml --watch
"""

from __future__ import annotations

import argparse
import logging
import sys
import time
from typing import Optional

from trainingjob_operator_tpu.api.types import ENDING_PHASES, TPUTrainingJob
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.utils.leader import LeaderElector
from trainingjob_operator_tpu.utils.signals import setup_signal_handler

log = logging.getLogger("trainingjob.main")


def build_backend(opt: OperatorOptions, args):
    """(clientset, runtime) for the selected backend.

    Reference: createClientSets + informer factory startup
    (cmd/app/server.go:43-51,111-151) collapsed to one switch.
    """
    if opt.backend == "sim":
        from trainingjob_operator_tpu.runtime.sim import SimRuntime

        clientset = Clientset()
        rt = SimRuntime(clientset)
        for i in range(args.nodes):
            rt.add_node(f"sim-{i}")
        return clientset, rt
    if opt.backend == "localproc":
        from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime

        clientset = Clientset()
        return clientset, LocalProcRuntime(clientset, nodes=args.nodes)
    if opt.backend == "kube":
        from trainingjob_operator_tpu.client.kube import KubeClientset
        from trainingjob_operator_tpu.runtime.kube import KubeRuntime

        clientset = KubeClientset.from_options(opt)
        return clientset, KubeRuntime(
            clientset, telemetry_port=args.telemetry_port,
            telemetry_advertise=args.telemetry_advertise_addr)
    raise SystemExit(f"unknown backend {opt.backend!r}")


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser("tpu-trainingjob-operator")
    OperatorOptions.add_flags(parser)
    parser.add_argument("--apply", action="append", default=[],
                        help="YAML manifest(s) to create after startup.")
    parser.add_argument("--watch", action="store_true",
                        help="Print job phase transitions; exit when applied "
                             "jobs reach an ending phase.")
    parser.add_argument("--nodes", type=int, default=2,
                        help="Virtual node count for sim/localproc backends.")
    parser.add_argument("--metrics-port", type=int, default=0,
                        help="Serve /metrics, /metrics.json, /healthz, "
                             "/readyz, /debug/threads, /debug/traces, "
                             "/debug/events and /debug/steps on this port "
                             "(0 = disabled).")
    parser.add_argument("--telemetry-port", type=int, default=0,
                        help="Kube backend: listen on this port for workload "
                             "step telemetry and inject the sink address "
                             "into pods (0 = telemetry disabled).")
    parser.add_argument("--telemetry-advertise-addr", default="",
                        help="Kube backend: address workloads should dial "
                             "for the telemetry sink (host[:port]); defaults "
                             "to the operator pod's IP at the bound port. "
                             "Set it when the operator sits behind a "
                             "Service or hostNetwork remap.")
    parser.add_argument("--log-json", action="store_true",
                        help="Emit structured JSON log lines (one object per "
                             "line) instead of text.")
    parser.add_argument("--trace-out", default="",
                        help="On shutdown, write the reconcile trace ring as "
                             "Chrome trace_event JSON to this path "
                             "(load in Perfetto / chrome://tracing).")
    parser.add_argument("--slo-plane", action="store_true",
                        help="Run the fleet SLO plane (docs/SLO.md): tsdb "
                             "sweeper, burn-rate engine and sampling span "
                             "profiler; served at /debug/timeseries, "
                             "/debug/slo and /debug/profile.")
    parser.add_argument("--request-obs", action="store_true",
                        help="Run the request-lifecycle plane "
                             "(docs/SERVING.md): record per-request terminal "
                             "states arriving over the telemetry wire into "
                             "the ledger behind /debug/requests and the "
                             "/debug/serve TTFT/TPOT columns.")
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)
    opt = OperatorOptions.from_args(args)

    level = (logging.DEBUG if args.verbose >= 2 else
             logging.INFO if args.verbose == 1 else logging.WARNING)
    if args.log_json:
        import os

        from trainingjob_operator_tpu.api import constants
        from trainingjob_operator_tpu.obs.logs import configure_logging

        configure_logging(json_output=True, level=level)
        # Propagate to workload subprocesses (localproc backend) so their
        # step records come out as structured JSON too.
        os.environ[constants.LOG_JSON_ENV] = "1"
    else:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")

    stop = setup_signal_handler()
    clientset, runtime = build_backend(opt, args)
    controller = TrainingJobController(clientset, options=opt)

    metrics_server = None
    if args.metrics_port:
        from trainingjob_operator_tpu.obs.incident import INCIDENTS
        from trainingjob_operator_tpu.obs.profiler import PROFILER
        from trainingjob_operator_tpu.obs.reqtrace import REQTRACE
        from trainingjob_operator_tpu.obs.slo import SLOS
        from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
        from trainingjob_operator_tpu.obs.trace import TRACER
        from trainingjob_operator_tpu.obs.tsdb import TSDB
        from trainingjob_operator_tpu.utils.metrics import serve_metrics

        metrics_server = serve_metrics(
            args.metrics_port, tracer=TRACER,
            events_fn=lambda: clientset.events.list(None),
            ready_fn=controller.ready, telemetry=TELEMETRY,
            incidents=INCIDENTS, tsdb=TSDB, slos=SLOS, profiler=PROFILER,
            reqtrace=REQTRACE)
        print(f"metrics on :{args.metrics_port}/metrics")

    def run_operator():
        if args.slo_plane:
            from trainingjob_operator_tpu.obs.profiler import PROFILER
            from trainingjob_operator_tpu.obs.slo import SLOS
            from trainingjob_operator_tpu.obs.tsdb import TSDB

            TSDB.start()
            SLOS.start()
            PROFILER.start()
        if args.request_obs:
            from trainingjob_operator_tpu.obs.reqtrace import REQTRACE

            REQTRACE.start()
        runtime.start()
        controller.run()
        applied = []
        for path in args.apply:
            with open(path) as f:
                job = TPUTrainingJob.from_yaml(f.read())
            clientset.trainingjobs.create(job)
            applied.append((job.namespace, job.name))
            print(f"created {job.namespace}/{job.name}")
        try:
            if args.watch and applied:
                _watch(clientset, applied, stop)
            else:
                stop.wait()
        finally:
            controller.stop()
            runtime.stop()
            if args.slo_plane:
                from trainingjob_operator_tpu.obs.profiler import PROFILER
                from trainingjob_operator_tpu.obs.slo import SLOS
                from trainingjob_operator_tpu.obs.tsdb import TSDB

                SLOS.stop()
                PROFILER.stop()
                TSDB.stop()
            if args.request_obs:
                from trainingjob_operator_tpu.obs.reqtrace import REQTRACE

                REQTRACE.stop()
            if metrics_server is not None:
                metrics_server.shutdown()
            if args.trace_out:
                from trainingjob_operator_tpu.obs.trace import TRACER

                with open(args.trace_out, "w") as f:
                    f.write(TRACER.export_chrome())
                print(f"reconcile trace written to {args.trace_out}")

    if opt.leader_election.leader_elect:
        if opt.backend == "kube":
            # Cluster-wide Lease lock (reference: server.go:85-106).
            from trainingjob_operator_tpu.utils.leader import KubeLeaderElector

            # on_lost=stop.set: a deposed leader must stop reconciling, not
            # run split-brain against its successor (RunOrDie exits there).
            KubeLeaderElector(clientset.rest, opt.leader_election).run(
                run_operator, stop=stop, on_lost=stop.set)
        else:
            LeaderElector(opt.leader_election).run(run_operator, stop=stop)
    else:
        run_operator()
    return 0


def _watch(clientset: Clientset, applied, stop) -> None:
    last = {}
    while not stop.is_set():
        done = 0
        for ns, name in applied:
            try:
                job = clientset.trainingjobs.get(ns, name)
            except KeyError:
                continue
            phase = job.status.phase
            if last.get((ns, name)) != phase:
                last[(ns, name)] = phase
                counts = {r: s.to_dict() for r, s in job.status.replica_statuses.items()}
                print(f"[{time.strftime('%H:%M:%S')}] {ns}/{name}: "
                      f"{phase or '(none)'} {counts}")
            if phase in ENDING_PHASES:
                done += 1
        if done == len(applied):
            for ns, name in applied:
                print(f"final: {ns}/{name} -> "
                      f"{clientset.trainingjobs.get(ns, name).status.phase}")
            return
        stop.wait(0.1)


if __name__ == "__main__":
    sys.exit(main())
