"""CLI / process bootstrap layer (reference: cmd/)."""
