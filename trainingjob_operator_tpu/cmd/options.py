"""Operator configuration.

Reference: cmd/app/options/options.go:12-72 -- same knobs and defaults
(ThreadNum=1, ResyncPeriod=10s, CreatingDurationTime=15min, leader-election
lease 15s / renew 5s / retry 3s).  Time fields are seconds (floats).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import List, Optional

from trainingjob_operator_tpu.api import constants


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class LeaderElectionConfig:
    """Reference: k8s leaderelectionconfig defaults (options.go:39-53)."""

    leader_elect: bool = False
    lease_duration: float = 15.0
    renew_deadline: float = 5.0
    retry_period: float = 3.0
    lock_path: str = ""  # file lock for local HA; Lease object on k8s


@dataclass
class OperatorOptions:
    """Reference: TrainingJobOperatorOption (options.go:12-23)."""

    master_url: str = ""
    kubeconfig: str = ""
    run_in_cluster: bool = False
    thread_num: int = 1
    creating_restart_time: float = 0.0        # --creating-restart-period
    creating_duration_time: float = 15 * 60.0  # --creating-duration-period
    enable_creating_failed: bool = False
    namespace: str = ""                        # "" = all namespaces
    resync_period: float = 10.0
    # Shards the periodic resync snapshot into this many hash-stable buckets
    # enqueued evenly across the period, so a fleet-sized job set never lands
    # on the workqueue as one storm (controller._resync_loop).
    resync_shards: int = 8
    gc_interval: float = 600.0                 # reference: controller.go:204
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    backend: str = "sim"                       # sim | localproc | kube
    # Elastic resize (TPU extension; the reference never resizes, SURVEY §2.6):
    # how long a pod may sit unschedulable before the group shrinks to the
    # replicas that did get capacity, and how long a degraded group runs before
    # the first re-expand probe (doubles per failed probe, capped at 15 min).
    scale_pending_time: float = 30.0
    scale_up_delay: float = 30.0
    # Sync-loop failure quarantine (workqueue): a key failing this many
    # consecutive reconciles parks for quarantine_delay seconds instead of
    # hot-looping the exponential ladder; 0 disables.  Env-overridable so a
    # wedged production fleet can be tuned without a rollout.
    quarantine_after: int = field(default_factory=lambda: _env_int(
        constants.QUARANTINE_AFTER_ENV, 8))
    quarantine_delay: float = field(default_factory=lambda: _env_float(
        constants.QUARANTINE_DELAY_ENV, 30.0))

    @classmethod
    def add_flags(cls, parser: argparse.ArgumentParser) -> None:
        """Reference: AddFlags (options.go:61-72)."""
        parser.add_argument("--master", dest="master_url", default="",
                            help="Address of the cluster API server (kube backend).")
        parser.add_argument("--kubeconfig", default="",
                            help="Path to a kubeconfig (kube backend, out-of-cluster).")
        parser.add_argument("--run-in-cluster", action="store_true",
                            help="Operator runs inside the cluster.")
        parser.add_argument("--thread-num", type=int, default=1,
                            help="Number of reconcile worker threads.")
        parser.add_argument("--namespace", default="",
                            help="Namespace to watch (default: all).")
        parser.add_argument("--resync-period", type=float, default=10.0,
                            help="Informer resync interval, seconds.")
        parser.add_argument("--resync-shards", type=int, default=8,
                            help="Buckets the resync enqueue is spread across "
                                 "within each period (jitter at fleet scale).")
        parser.add_argument("--creating-restart-period", type=float, default=0.0,
                            dest="creating_restart_time",
                            help="Window during which container-create errors retry, seconds.")
        parser.add_argument("--creating-duration-period", type=float, default=15 * 60.0,
                            dest="creating_duration_time",
                            help="Grace before a stuck-creating pod restarts, seconds.")
        parser.add_argument("--enable-creating-failed", action="store_true",
                            help="Fail the job when container creation exceeds the retry window.")
        parser.add_argument("--gc-interval", type=float, default=600.0,
                            help="Orphan-pod GC sweep interval, seconds.")
        parser.add_argument("--leader-elect", action="store_true",
                            help="Enable leader election before running.")
        parser.add_argument("--leader-elect-lock", default="", dest="leader_lock",
                            help="Path of the leader-election lock file.")
        parser.add_argument("--backend", choices=("sim", "localproc", "kube"),
                            default="sim", help="Cluster runtime backend.")
        parser.add_argument("--scale-pending-period", type=float, default=30.0,
                            dest="scale_pending_time",
                            help="Unschedulable grace before an elastic group "
                                 "shrinks to scheduled capacity, seconds.")
        parser.add_argument("--scale-up-delay", type=float, default=30.0,
                            help="Delay before a degraded elastic group probes "
                                 "a re-expand, seconds (exponential backoff).")
        parser.add_argument("--quarantine-after", type=int,
                            default=_env_int(constants.QUARANTINE_AFTER_ENV, 8),
                            help="Consecutive failed syncs before a key is "
                                 "quarantined (0 disables).")
        parser.add_argument("--quarantine-delay", type=float,
                            default=_env_float(constants.QUARANTINE_DELAY_ENV, 30.0),
                            help="Seconds a quarantined key parks between "
                                 "retry attempts.")

    @classmethod
    def from_args(cls, args: argparse.Namespace) -> "OperatorOptions":
        opt = cls(
            master_url=args.master_url,
            kubeconfig=args.kubeconfig,
            run_in_cluster=args.run_in_cluster,
            thread_num=args.thread_num,
            namespace=args.namespace,
            resync_period=args.resync_period,
            resync_shards=args.resync_shards,
            creating_restart_time=args.creating_restart_time,
            creating_duration_time=args.creating_duration_time,
            enable_creating_failed=args.enable_creating_failed,
            gc_interval=args.gc_interval,
            backend=args.backend,
            scale_pending_time=args.scale_pending_time,
            scale_up_delay=args.scale_up_delay,
            quarantine_after=args.quarantine_after,
            quarantine_delay=args.quarantine_delay,
        )
        opt.leader_election.leader_elect = args.leader_elect
        opt.leader_election.lock_path = args.leader_lock
        return opt
