"""Core object model: the pod/service/node/event subset the control plane needs.

This is a from-scratch, Python-native equivalent of the slice of
``k8s.io/api/core/v1`` consumed by the reference controller
(reference: pkg/controller/pod.go, service.go, garbage_collection.go).  Objects
are mutable dataclasses; the object tracker (client/tracker.py) stores deep
copies and hands out deep copies, so holding a reference to an object never
aliases the "cluster" state -- the same discipline the k8s informer cache
enforces by convention.

Times are ``float`` POSIX timestamps (``now()``); serialization renders them
ISO-8601.  Every object serializes to/from plain dicts with camelCase keys so
YAML manifests look like the reference's (reference: example/paddle-mnist.yaml).
"""

from __future__ import annotations

import copy
import datetime as _dt
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def now() -> float:
    """Current time as a POSIX timestamp."""
    return time.time()


def new_uid() -> str:
    return str(uuid.uuid4())


def iso(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return _dt.datetime.fromtimestamp(ts, _dt.timezone.utc).isoformat()


def from_iso(s: Optional[str]) -> Optional[float]:
    if s is None:
        return None
    if isinstance(s, (int, float)):
        return float(s)
    # Real apiservers emit RFC3339 with a 'Z' suffix; fromisoformat only
    # learned 'Z' in Python 3.11, and 3.10 is supported (pyproject).
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    ts = _dt.datetime.fromisoformat(s)
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts.timestamp()


# ---------------------------------------------------------------------------
# Enums (string constants, matching corev1 spellings)
# ---------------------------------------------------------------------------

class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


class ConditionStatus:
    TRUE = "True"
    FALSE = "False"
    UNKNOWN = "Unknown"


class NodeConditionType:
    READY = "Ready"


class PodConditionType:
    SCHEDULED = "PodScheduled"
    READY = "Ready"


class RestartPolicy:
    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------

@dataclass
class OwnerReference:
    """Reference: metav1.OwnerReference as built by GenOwnerReference
    (reference: pkg/controller/controller.go:161-173)."""

    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "name": self.name,
            "uid": self.uid,
            "controller": self.controller,
            "blockOwnerDeletion": self.block_owner_deletion,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OwnerReference":
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=bool(d.get("controller", False)),
            block_owner_deletion=bool(d.get("blockOwnerDeletion", False)),
        )


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generate_name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None
    deletion_grace_period_seconds: Optional[int] = None

    def controller_of(self) -> Optional[OwnerReference]:
        """metav1.GetControllerOf equivalent."""
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "namespace": self.namespace}
        if self.uid:
            d["uid"] = self.uid
        if self.resource_version:
            d["resourceVersion"] = str(self.resource_version)
        if self.generate_name:
            d["generateName"] = self.generate_name
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.owner_references:
            d["ownerReferences"] = [r.to_dict() for r in self.owner_references]
        if self.creation_timestamp is not None:
            d["creationTimestamp"] = iso(self.creation_timestamp)
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = iso(self.deletion_timestamp)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectMeta":
        rv = d.get("resourceVersion", 0)
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=int(rv) if rv else 0,
            generate_name=d.get("generateName", ""),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            owner_references=[OwnerReference.from_dict(r) for r in d.get("ownerReferences") or []],
            creation_timestamp=from_iso(d.get("creationTimestamp")),
            deletion_timestamp=from_iso(d.get("deletionTimestamp")),
        )


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

@dataclass
class EnvVar:
    name: str
    value: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "value": self.value}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "EnvVar":
        return cls(name=d.get("name", ""), value=str(d.get("value", "")))


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "containerPort": self.container_port}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ContainerPort":
        return cls(name=d.get("name", ""), container_port=int(d.get("containerPort", 0)))


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    working_dir: str = ""
    #: raw corev1.VolumeMount dicts -- carried through verbatim (the
    #: controller never interprets them; stripping them would silently
    #: unmount a user's corpus/checkpoint volumes).
    volume_mounts: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.image:
            d["image"] = self.image
        if self.command:
            d["command"] = list(self.command)
        if self.args:
            d["args"] = list(self.args)
        if self.env:
            d["env"] = [e.to_dict() for e in self.env]
        if self.ports:
            d["ports"] = [p.to_dict() for p in self.ports]
        if self.resources:
            d["resources"] = copy.deepcopy(self.resources)
        if self.working_dir:
            d["workingDir"] = self.working_dir
        if self.volume_mounts:
            d["volumeMounts"] = copy.deepcopy(self.volume_mounts)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Container":
        return cls(
            name=d.get("name", ""),
            image=d.get("image", ""),
            command=list(d.get("command") or []),
            args=list(d.get("args") or []),
            env=[EnvVar.from_dict(e) for e in d.get("env") or []],
            ports=[ContainerPort.from_dict(p) for p in d.get("ports") or []],
            resources=copy.deepcopy(d.get("resources") or {}),
            working_dir=d.get("workingDir", ""),
            volume_mounts=copy.deepcopy(d.get("volumeMounts") or []),
        )


@dataclass
class ContainerState:
    """One-of waiting/running/terminated, like corev1.ContainerState."""

    waiting_reason: Optional[str] = None
    waiting_message: Optional[str] = None
    running_started_at: Optional[float] = None
    terminated_exit_code: Optional[int] = None
    terminated_reason: Optional[str] = None
    terminated_message: Optional[str] = None

    @property
    def waiting(self) -> bool:
        return self.waiting_reason is not None

    @property
    def running(self) -> bool:
        return self.running_started_at is not None and self.terminated_exit_code is None

    @property
    def terminated(self) -> bool:
        return self.terminated_exit_code is not None

    def to_dict(self) -> Dict[str, Any]:
        if self.terminated:
            return {"terminated": {"exitCode": self.terminated_exit_code,
                                   "reason": self.terminated_reason or "",
                                   "message": self.terminated_message or ""}}
        if self.waiting:
            return {"waiting": {"reason": self.waiting_reason,
                                "message": self.waiting_message or ""}}
        if self.running_started_at is not None:
            return {"running": {"startedAt": iso(self.running_started_at)}}
        return {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ContainerState":
        s = cls()
        if "terminated" in d:
            t = d["terminated"]
            s.terminated_exit_code = int(t.get("exitCode", 0))
            s.terminated_reason = t.get("reason")
            s.terminated_message = t.get("message")
        elif "waiting" in d:
            s.waiting_reason = d["waiting"].get("reason", "")
            s.waiting_message = d["waiting"].get("message")
        elif "running" in d:
            s.running_started_at = from_iso(d["running"].get("startedAt"))
        return s


@dataclass
class ContainerStatus:
    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    restart_count: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "state": self.state.to_dict(),
                "restartCount": self.restart_count}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ContainerStatus":
        return cls(
            name=d.get("name", ""),
            state=ContainerState.from_dict(d.get("state") or {}),
            restart_count=int(d.get("restartCount", 0)),
        )


# ---------------------------------------------------------------------------
# Conditions (shared shape for pods, nodes and jobs)
# ---------------------------------------------------------------------------

@dataclass
class Condition:
    type: str = ""
    status: str = ConditionStatus.TRUE
    reason: str = ""
    message: str = ""
    last_probe_time: Optional[float] = None
    last_transition_time: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastProbeTime": iso(self.last_probe_time),
            "lastTransitionTime": iso(self.last_transition_time),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Condition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", ConditionStatus.TRUE),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_probe_time=from_iso(d.get("lastProbeTime")),
            last_transition_time=from_iso(d.get("lastTransitionTime")),
        )


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------

@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    #: "" = unset (the cluster's own defaulting applies, like a Go zero
    #: value).  Keeping absence representable lets the pod plane warn about
    #: an *explicit* template restartPolicy it overrides without also
    #: warning on every manifest that simply omitted the field.
    restart_policy: str = ""
    scheduler_name: str = ""
    host_network: bool = False
    subdomain: str = ""
    priority_class_name: str = ""
    #: raw corev1.Volume dicts, round-tripped like volume_mounts above.
    volumes: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"containers": [c.to_dict() for c in self.containers]}
        if self.init_containers:
            d["initContainers"] = [c.to_dict() for c in self.init_containers]
        if self.node_name:
            d["nodeName"] = self.node_name
        if self.node_selector:
            d["nodeSelector"] = dict(self.node_selector)
        if self.restart_policy:
            d["restartPolicy"] = self.restart_policy
        if self.scheduler_name:
            d["schedulerName"] = self.scheduler_name
        if self.host_network:
            d["hostNetwork"] = True
        if self.subdomain:
            d["subdomain"] = self.subdomain
        if self.priority_class_name:
            d["priorityClassName"] = self.priority_class_name
        if self.volumes:
            d["volumes"] = copy.deepcopy(self.volumes)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PodSpec":
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers") or []],
            node_name=d.get("nodeName", ""),
            node_selector=dict(d.get("nodeSelector") or {}),
            restart_policy=d.get("restartPolicy", ""),
            scheduler_name=d.get("schedulerName", ""),
            host_network=bool(d.get("hostNetwork", False)),
            subdomain=d.get("subdomain", ""),
            priority_class_name=d.get("priorityClassName", ""),
            volumes=copy.deepcopy(d.get("volumes") or []),
        )


@dataclass
class PodStatus:
    phase: str = PodPhase.PENDING
    conditions: List[Condition] = field(default_factory=list)
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    start_time: Optional[float] = None
    reason: str = ""
    message: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"phase": self.phase}
        if self.conditions:
            d["conditions"] = [c.to_dict() for c in self.conditions]
        if self.container_statuses:
            d["containerStatuses"] = [c.to_dict() for c in self.container_statuses]
        if self.start_time is not None:
            d["startTime"] = iso(self.start_time)
        if self.reason:
            d["reason"] = self.reason
        if self.message:
            d["message"] = self.message
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PodStatus":
        return cls(
            phase=d.get("phase", PodPhase.PENDING),
            conditions=[Condition.from_dict(c) for c in d.get("conditions") or []],
            container_statuses=[ContainerStatus.from_dict(c)
                                for c in d.get("containerStatuses") or []],
            start_time=from_iso(d.get("startTime")),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
        )


@dataclass
class Pod:
    KIND = "Pod"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "metadata": self.metadata.to_dict(),
                "spec": self.spec.to_dict(), "status": self.status.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Pod":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
            status=PodStatus.from_dict(d.get("status") or {}),
        )


@dataclass
class PodTemplateSpec:
    """Reference: corev1.PodTemplateSpec used by ReplicaSpec.Template
    (reference: pkg/apis/aitrainingjob/v1/replica.go:14)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    def to_dict(self) -> Dict[str, Any]:
        return {"metadata": self.metadata.to_dict(), "spec": self.spec.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PodTemplateSpec":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
        )


# ---------------------------------------------------------------------------
# Service
# ---------------------------------------------------------------------------

@dataclass
class ServicePort:
    name: str = ""
    port: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "port": self.port}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServicePort":
        return cls(name=d.get("name", ""), port=int(d.get("port", 0)))


@dataclass
class ServiceSpec:
    cluster_ip: str = ""  # "None" => headless (reference: service.go:180)
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"clusterIP": self.cluster_ip, "selector": dict(self.selector),
                "ports": [p.to_dict() for p in self.ports]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServiceSpec":
        return cls(
            cluster_ip=d.get("clusterIP", ""),
            selector=dict(d.get("selector") or {}),
            ports=[ServicePort.from_dict(p) for p in d.get("ports") or []],
        )


@dataclass
class Service:
    KIND = "Service"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "metadata": self.metadata.to_dict(),
                "spec": self.spec.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Service":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=ServiceSpec.from_dict(d.get("spec") or {}),
        )


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------

@dataclass
class NodeStatus:
    conditions: List[Condition] = field(default_factory=list)
    # TPU extension: capacity advertised by the node, e.g. {"google.com/tpu": 4}.
    capacity: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"conditions": [c.to_dict() for c in self.conditions],
                "capacity": copy.deepcopy(self.capacity)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeStatus":
        return cls(
            conditions=[Condition.from_dict(c) for c in d.get("conditions") or []],
            capacity=copy.deepcopy(d.get("capacity") or {}),
        )


@dataclass
class Node:
    KIND = "Node"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def is_ready(self) -> bool:
        """A node is Ready iff it has condition Ready=True
        (reference: pkg/controller/pod.go:446-453)."""
        for cond in self.status.conditions:
            if cond.type == NodeConditionType.READY and cond.status == ConditionStatus.TRUE:
                return True
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "metadata": self.metadata.to_dict(),
                "status": self.status.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Node":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            status=NodeStatus.from_dict(d.get("status") or {}),
        )


def set_node_readiness(clientset: Any, name: str, ready: bool) -> None:
    """Flip a node's Ready condition through its client (shared by the
    runtimes' fault-injection paths)."""
    node = clientset.nodes.get_node(name)
    node.status.conditions = [Condition(
        type=NodeConditionType.READY,
        status=ConditionStatus.TRUE if ready else ConditionStatus.FALSE,
        last_transition_time=now(),
    )]
    clientset.nodes.update(node)


def make_ready_node(name: str, ready: bool = True, labels: Optional[Dict[str, str]] = None,
                    capacity: Optional[Dict[str, Any]] = None) -> Node:
    """Convenience constructor used by the sim runtime and tests."""
    return Node(
        metadata=ObjectMeta(name=name, namespace="", labels=dict(labels or {})),
        status=NodeStatus(
            conditions=[Condition(
                type=NodeConditionType.READY,
                status=ConditionStatus.TRUE if ready else ConditionStatus.FALSE,
                last_transition_time=now(),
            )],
            capacity=dict(capacity or {}),
        ),
    )


# ---------------------------------------------------------------------------
# Event (observability; reference: client-go record.EventRecorder usage,
# pkg/controller/controller.go:88-102)
# ---------------------------------------------------------------------------

@dataclass
class Event:
    KIND = "Event"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    source: str = ""
    timestamp: Optional[float] = None

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "involvedObject": {"kind": self.involved_kind, "name": self.involved_name,
                               "namespace": self.involved_namespace},
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "source": {"component": self.source},
            "eventTime": iso(self.timestamp),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Event":
        inv = d.get("involvedObject") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            involved_kind=inv.get("kind", ""),
            involved_name=inv.get("name", ""),
            involved_namespace=inv.get("namespace", ""),
            type=d.get("type", "Normal"),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            source=(d.get("source") or {}).get("component", ""),
            timestamp=from_iso(d.get("eventTime")),
        )
