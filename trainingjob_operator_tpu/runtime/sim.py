"""Simulated kubelet + scheduler: makes the in-memory cluster behave.

The reference operator assumes a real cluster underneath (kube-scheduler
assigns nodes, kubelets run containers and report status).  This module is
that substrate for the in-memory backend: a background loop that

- schedules Pending pods onto Ready nodes honoring ``node_selector`` and
  ``google.com/tpu`` chip capacity (gang-aware: a TPU gang label is placed
  all-or-nothing, the atomicity requirement of SURVEY.md §7 "hard parts" (a)),
- walks pods through Pending -> Running -> Succeeded/Failed using the
  ``sim.tpu.trainingjob.dev/*`` annotations as the "program",
- honors graceful deletion (finalizer -> SIGTERM analogue -> finalize), and
- exposes fault injection: fail/recover nodes, preempt pods -- the knobs
  SURVEY.md §4 says the reference exercises operationally (delete pods /
  mark nodes NotReady / set the Preempted annotation).

Two kernels drive the same semantics (docs/FLEET.md):

- **event** (default): a discrete-event kernel.  Every pod arms its *next*
  transition -- start delay, exit-at, graceful-delete expiry, step-synthesis
  cadence, serve-snapshot emission -- as a deadline in a deterministic
  ``TimerQueue`` (runtime/events.py), and the sim thread sleeps until the
  earliest one.  Watch events cancel-or-re-arm a pod's timers instead of
  waiting for a scan, and pending-gang placement is an event re-armed on
  node/capacity changes rather than an every-tick retry.  Cost is
  O(events), not O(pods x ticks): a parked fleet of settled or steady pods
  costs nothing.
- **scan** (``TRAININGJOB_SIM_KERNEL=scan``): the original fixed-cadence
  walk over the active pod set, kept as the A/B baseline and escape hatch.

Both kernels converge seeded runs to byte-identical phase counts; the
``fleet_sim`` bench leg (bench.py) gates the event kernel's throughput win.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.tracker import DELETED, WatchEvent
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ConditionStatus,
    ContainerState,
    ContainerStatus,
    Node,
    Pod,
    PodConditionType,
    PodPhase,
    make_ready_node,
    set_node_readiness,
)
from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
from trainingjob_operator_tpu.obs.trace import TRACER
from trainingjob_operator_tpu.runtime.base import PodStateRuntime
from trainingjob_operator_tpu.runtime.events import TimerQueue
from trainingjob_operator_tpu.utils.metrics import METRICS

log = logging.getLogger("trainingjob.sim")

#: Pod annotations that script the simulated workload.
RUN_SECONDS_ANNOTATION = "sim.tpu.trainingjob.dev/run-seconds"
EXIT_CODE_ANNOTATION = "sim.tpu.trainingjob.dev/exit-code"
START_DELAY_ANNOTATION = "sim.tpu.trainingjob.dev/start-delay"
#: Telemetry synthesis: a Running pod with step-ms set "trains", reporting
#: one step record per step-ms of wall time into the TELEMETRY aggregator
#: (the sim's substitute for the workload-side TelemetryEmitter; same
#: records, no socket).  The rank-targeted knobs live on the shared pod
#: template and select on the pod's TrainingJobReplicaIndex label:
#: straggler-rank runs straggler-factor x slower; stall-rank stops
#: advancing at stall-at-step (and its pod stays Running -- exactly the
#: "up but stuck" state the stall watchdog exists to catch).
STEP_MS_ANNOTATION = "sim.tpu.trainingjob.dev/step-ms"
TOKENS_PER_STEP_ANNOTATION = "sim.tpu.trainingjob.dev/tokens-per-step"
FLOPS_PER_STEP_ANNOTATION = "sim.tpu.trainingjob.dev/flops-per-step"
PEAK_FLOPS_ANNOTATION = "sim.tpu.trainingjob.dev/peak-flops"
STRAGGLER_RANK_ANNOTATION = "sim.tpu.trainingjob.dev/straggler-rank"
STRAGGLER_FACTOR_ANNOTATION = "sim.tpu.trainingjob.dev/straggler-factor"
STALL_RANK_ANNOTATION = "sim.tpu.trainingjob.dev/stall-rank"
STALL_AT_STEP_ANNOTATION = "sim.tpu.trainingjob.dev/stall-at-step"
#: Incident-plane synthesis: ckpt-ms/hbm-bytes ride every step record (the
#: fields a real workload's checkpoint pipeline and HBM sampler report);
#: restore-ms/compile-ms make a freshly (re)started pod first push one
#: resume record -- the workload tail the incident bundle attributes into
#: rendezvous/restore/compile phases.
CKPT_MS_ANNOTATION = "sim.tpu.trainingjob.dev/ckpt-ms"
HBM_BYTES_ANNOTATION = "sim.tpu.trainingjob.dev/hbm-bytes"
RESTORE_MS_ANNOTATION = "sim.tpu.trainingjob.dev/restore-ms"
COMPILE_MS_ANNOTATION = "sim.tpu.trainingjob.dev/compile-ms"
#: Live re-rendezvous synthesis (docs/ELASTIC.md): a Running pod with
#: rendezvous-ms set watches the SAME generation.json the controller
#: publishes (its container's TRAININGJOB_RESIZE_DIR env) and, once per
#: new generation, pushes the rendezvous record a real survivor's
#: fallback ladder would -- rendezvous-rung scripts which rung it reports
#: (default live).  This drives the incident bundle's rendezvous phase
#: and rung stamp end-to-end without a model.
RENDEZVOUS_MS_ANNOTATION = "sim.tpu.trainingjob.dev/rendezvous-ms"
RENDEZVOUS_RUNG_ANNOTATION = "sim.tpu.trainingjob.dev/rendezvous-rung"
#: Serving-plane synthesis: a Running pod with serve-queue-depth set
#: "serves", pushing one serve snapshot per kubelet tick (the records a
#: real workloads/serve.py DecodeService emits).  Queue depth is the
#: signal the controller's traffic-aware scale policy acts on, so a churn
#: script annotates depth above/below the scale thresholds to drive
#: scale-out/in end-to-end without running a model.
SERVE_QUEUE_ANNOTATION = "sim.tpu.trainingjob.dev/serve-queue-depth"
SERVE_SLOTS_ANNOTATION = "sim.tpu.trainingjob.dev/serve-slots"
SERVE_ACTIVE_ANNOTATION = "sim.tpu.trainingjob.dev/serve-active-slots"
SERVE_P99_ANNOTATION = "sim.tpu.trainingjob.dev/serve-p99-ms"
SERVE_TPS_ANNOTATION = "sim.tpu.trainingjob.dev/serve-tokens-per-sec"
#: Request-lifecycle synthesis (obs/reqtrace.py): a Running pod with
#: req-rate set "serves requests", opening req-rate new request ids per
#: kubelet tick and completing the previous tick's batch with TTFT/TPOT
#: from the annotations.  Every record carries the pod's submitted
#: high-water mark, so a pod killed mid-flight leaves a gap the ledger's
#: reconcile() must file as ``orphaned`` -- unless the sim flushes the
#: open batch as explicit ``evicted`` records on every death path, which
#: is exactly the audit the request-obs smoke pins (zero orphans through
#: scale-in drain and exit-137 restarts).
REQ_RATE_ANNOTATION = "sim.tpu.trainingjob.dev/req-rate"
REQ_TTFT_ANNOTATION = "sim.tpu.trainingjob.dev/req-ttft-ms"
REQ_TPOT_ANNOTATION = "sim.tpu.trainingjob.dev/req-tpot-ms"

#: Step records synthesized per pod per tick/step-event batch, at most (a
#: pod "catching up" after a long scheduler pause must not flood the
#: aggregator's window).
_MAX_STEPS_PER_TICK = 200

#: One event-kernel drain pops at most this many due timers, so a deadline
#: storm cannot starve the loop's stop/wake checks.
_MAX_EVENTS_PER_DRAIN = 4096

#: Cluster-singleton timer key (scheduler retry + stall watchdog).
_CLUSTER_KEY = "@cluster"

#: Per-pod timer kinds a lifecycle change must retarget together.
_POD_TIMER_KINDS = ("start", "exit", "grace", "step", "serve")


def resolve_kernel(kernel: Optional[str] = None) -> str:
    """Kernel choice: explicit argument wins, then the
    ``TRAININGJOB_SIM_KERNEL`` escape hatch, then the event kernel."""
    choice = kernel or os.environ.get(constants.SIM_KERNEL_ENV) or "event"
    if choice not in ("event", "scan"):
        raise ValueError(f"unknown sim kernel {choice!r} "
                         "(expected 'event' or 'scan')")
    return choice


@dataclass
class _PodRuntime:
    uid: str = ""
    scheduled_at: float = 0.0
    started_at: float = 0.0
    will_exit_at: Optional[float] = None
    exit_code: int = 0
    terminating_since: Optional[float] = None
    frozen_on: str = ""  # node whose failure froze this pod's reports
    frozen_at: float = 0.0  # when the freeze started (thaw shifts clocks by it)
    frozen_exit_at: Optional[float] = None  # exit deadline saved across a flap
    steps_reported: int = 0
    generation_reported: int = 0  # newest rendezvous generation synthesized
    req_next: int = 0  # next request id this pod will open
    # (id, opened_at) batch in flight; completed next tick or flushed as
    # evicted on the pod's death paths.
    req_open: List[Tuple[int, float]] = field(default_factory=list)


class SimRuntime(PodStateRuntime):
    """Drives pod/node behavior against a Clientset-backed tracker."""

    thread_name = "sim-kubelet"

    def __init__(self, clientset: Clientset,
                 start_delay: float = 0.0,
                 tick: float = 0.005,
                 termination_grace: float = 0.05,
                 pods_per_node: int = 64,
                 kernel: Optional[str] = None):
        super().__init__(clientset, tick)
        self._start_delay = start_delay
        self._termination_grace = termination_grace
        self._pods_per_node = pods_per_node
        self._kernel = resolve_kernel(kernel)
        # Discrete-event state: the deadline queue, the set of pending-
        # unscheduled pod keys (feeds the "sched" event), and a plain event
        # counter the fleet harness reports as events/s.  All are inert
        # under the scan kernel.
        self._timers = TimerQueue()
        self._pending: set = set()
        self.events_total = 0
        # Scheduled data-plane faults (schedule_node_faults): timer key ->
        # (fault, resolved node targets, on_fault callback); plus the set
        # of permanently killed nodes, so a flap-recovery timer landing on
        # a node a later domain kill took down never resurrects it.
        self._node_faults: Dict[str, tuple] = {}
        self._node_dead: set = set()
        # Watch-fed pod/node caches: at fleet scale a per-tick
        # ``pods.list()`` deepcopies the whole store (100k pods x 200 Hz is
        # the difference between a working sim and one that never catches
        # up).  The tracker hands each watch handler its own deepcopy, so
        # cached objects are privately owned; anything the tick loop is
        # about to MUTATE is copied first (a conflicted write must not
        # poison the cache for the retry).
        self._pods_cache: Dict[str, Pod] = {}
        # Settled pods (Succeeded/Failed, not being deleted) are inert to the
        # kubelet: nothing left to start, report, or exit.  A long-lived
        # fleet accumulates them (completed jobs linger until GC/TTL), so the
        # steady-state tick walks this ACTIVE subset only -- the full cache
        # is consulted just while something is pending (usage/gang maps must
        # see every placed pod).  Maintained event-driven alongside
        # ``_pods_cache``; a settled pod re-enters when deletion stamps it
        # (the finalize walk still owes it a ``finalize_delete``).
        self._active_cache: Dict[str, Pod] = {}
        self._nodes_cache: Dict[str, Node] = {}
        # Incremental scheduler accounting, maintained from the same watch
        # events: node -> [pod_count, tpu_used] and (namespace, gang) ->
        # live member count.  The pending branch used to snapshot the FULL
        # pod cache and rebuild both maps per tick -- O(pods) with 20k
        # settled pods parked in the cache, the fleet harness's ~175
        # reconciles/s ceiling (docs/FLEET.md).  Now a pending burst copies
        # O(nodes + gangs) dicts instead.  ``_placed``/``_gang_member``
        # remember each pod's counted contribution so MODIFIED events
        # reconcile exactly (schedule, delete-stamp, finalize).
        self._usage: Dict[str, list] = {}
        self._placed: Dict[str, tuple] = {}
        self._gang_totals: Dict[tuple, int] = {}
        self._gang_member: Dict[str, tuple] = {}
        self._unsubs = [
            clientset.tracker.watch(Pod.KIND, self._on_pod_event),
            clientset.tracker.watch(Node.KIND, self._on_node_event),
        ]
        now = time.time()
        with self._lock:
            for pod in clientset.tracker.list(Pod.KIND):
                key = f"{pod.namespace}/{pod.name}"
                self._on_pod_cached(key, pod)
                if self._kernel == "event":
                    self._arm_for_pod_locked(key, pod, now)
            for node in clientset.tracker.list(Node.KIND):
                self._nodes_cache[node.name] = node
        if self._kernel == "event":
            # The kubelet tick doubles as the step-progress watchdog tick
            # under the scan kernel; the event kernel keeps that cadence as
            # a self-re-arming cluster event (cheap: O(tracked replicas)).
            self._timers.arm(_CLUSTER_KEY, "watchdog", now + self._tick)
            METRICS.gauge("trainingjob_sim_event_queue_depth",
                          self._timers.depth)

    @staticmethod
    def _settled(pod: Pod) -> bool:
        return (pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
                and pod.metadata.deletion_timestamp is None)

    def _on_pod_cached(self, key: str, pod: Pod) -> None:
        """Caller holds the lock."""
        self._pods_cache[key] = pod
        if self._settled(pod):
            self._active_cache.pop(key, None)
        else:
            self._active_cache[key] = pod
        self._account_pod_locked(key, pod)

    def _account_pod_locked(self, key: str, pod: Optional[Pod]) -> None:
        """Reconcile ``key``'s contribution to the usage/gang maps (pass
        pod=None on deletion).  Placed pods occupy node capacity until they
        are GONE (settled pods still hold their sim placement); gang
        membership counts every live (not delete-stamped) pod carrying the
        label -- identical semantics to the per-tick passes this replaces."""
        old = self._placed.pop(key, None)
        if old is not None:
            node, tpu = old
            entry = self._usage.get(node)
            if entry is not None:
                entry[0] -= 1
                entry[1] -= tpu
                if entry[0] <= 0:
                    self._usage.pop(node, None)
        if pod is not None and pod.spec.node_name:
            tpu = self._pod_tpu_request(pod)
            self._placed[key] = (pod.spec.node_name, tpu)
            entry = self._usage.setdefault(pod.spec.node_name, [0, 0])
            entry[0] += 1
            entry[1] += tpu
        gang_key = self._gang_member.pop(key, None)
        if gang_key is not None:
            left = self._gang_totals.get(gang_key, 1) - 1
            if left > 0:
                self._gang_totals[gang_key] = left
            else:
                self._gang_totals.pop(gang_key, None)
        if pod is not None and pod.metadata.deletion_timestamp is None:
            label = pod.metadata.labels.get(constants.GANG_LABEL)
            if label:
                gang_key = (pod.namespace, label)
                self._gang_member[key] = gang_key
                self._gang_totals[gang_key] = (
                    self._gang_totals.get(gang_key, 0) + 1)

    def _on_pod_event(self, event: WatchEvent) -> None:
        pod = event.obj
        key = f"{pod.namespace}/{pod.name}"
        with self._lock:
            if event.type == DELETED:
                self._pods_cache.pop(key, None)
                self._active_cache.pop(key, None)
                self._account_pod_locked(key, None)
                # Force-deletes skip the grace flush: file any still-open
                # request batch as evicted before the state is dropped.
                self._flush_requests(pod, self._state.get(key), time.time())
                if self._kernel == "event":
                    self._state.pop(key, None)
                    self._pending.discard(key)
                    self._timers.cancel_all(key)
                    if self._pending:
                        # Freed capacity: a waiting gang may fit now.
                        self._arm_now_locked(_CLUSTER_KEY, "sched")
            else:
                self._on_pod_cached(key, pod)
                if self._kernel == "event":
                    self._arm_for_pod_locked(key, pod, time.time())

    def _on_node_event(self, event: WatchEvent) -> None:
        node = event.obj
        with self._lock:
            if event.type == DELETED:
                self._nodes_cache.pop(node.name, None)
            else:
                self._nodes_cache[node.name] = node
            if self._kernel == "event":
                # Capacity/readiness moved: re-arm everything on the node
                # (a recovered node resumes its pods' paused deadlines) and
                # give waiting gangs another placement attempt.  Node
                # events are rare -- cluster setup and fault injection --
                # so the O(active) re-arm walk stays off every hot path.
                if event.type != DELETED:
                    now = time.time()
                    for key, pod in self._active_cache.items():
                        if pod.spec.node_name == node.name:
                            self._arm_for_pod_locked(key, pod, now)
                if self._pending:
                    self._arm_now_locked(_CLUSTER_KEY, "sched")

    def stop(self) -> None:
        super().stop()
        for unsub in self._unsubs:
            unsub()
        self._unsubs = []
        if self._kernel == "event":
            METRICS.remove_gauge("trainingjob_sim_event_queue_depth")

    def _new_state(self, uid: str) -> _PodRuntime:
        return _PodRuntime(uid=uid)

    # -- cluster setup / fault injection -------------------------------------

    def add_node(self, name: str, labels: Optional[Dict[str, str]] = None,
                 tpu_chips: int = 0) -> None:
        capacity = {constants.TPU_RESOURCE: tpu_chips} if tpu_chips else {}
        self._cs.nodes.create(make_ready_node(name, labels=labels, capacity=capacity))

    def set_node_ready(self, name: str, ready: bool) -> None:
        set_node_readiness(self._cs, name, ready)

    def fail_node(self, name: str, kill_pods: bool = True) -> None:
        """Node goes NotReady; its pods stop reporting (like a dead TPU-VM
        host).  Pod objects linger -- exactly the state the controller's
        NodeFail detector must handle (pod.go:407-419)."""
        self.set_node_ready(name, False)
        if kill_pods:
            now = time.time()
            with self._lock:
                for key, rt in self._state.items():
                    pod = self._pods_cache.get(key)
                    if pod is not None and pod.spec.node_name == name:
                        rt.frozen_exit_at = rt.will_exit_at  # thaw restores it
                        rt.will_exit_at = None  # frozen: no further reports
                        rt.frozen_on = name
                        rt.frozen_at = now
                        if self._kernel == "event":
                            self._timers.cancel(key, "exit")
                            self._timers.cancel(key, "step")
                            self._timers.cancel(key, "serve")

    def recover_node(self, name: str, dead: bool = True) -> None:
        """Node comes back Ready.  ``dead=True`` (the default, and the
        historical behavior): pods frozen by fail_node are reported dead
        (exit 137), like a recovering kubelet reporting its containers
        gone.  ``dead=False`` models a *flap* -- the host was unreachable
        but its processes kept running -- so frozen pods thaw: their step
        and exit clocks shift by the pause so they resume telemetry where
        they left off instead of tripping the stall watchdog."""
        self.set_node_ready(name, True)
        now = time.time()
        with self._lock:
            for key, rt in self._state.items():
                if rt.frozen_on != name:
                    continue
                if dead:
                    rt.will_exit_at = now
                    rt.exit_code = 137
                    rt.frozen_on = ""
                    rt.frozen_exit_at = None
                    if self._kernel == "event":
                        self._arm_now_locked(key, "exit")
                else:
                    pause = now - rt.frozen_at if rt.frozen_at else 0.0
                    if rt.started_at:
                        rt.started_at += pause  # step targets don't jump
                    if rt.frozen_exit_at is not None:
                        shifted = rt.frozen_exit_at + pause
                        # A kill delivered DURING the freeze (preempt_pod
                        # stamped a fresh will_exit_at) must still win:
                        # keep the earliest exit.
                        rt.will_exit_at = (shifted if rt.will_exit_at is None
                                           else min(rt.will_exit_at, shifted))
                    rt.frozen_on = ""
                    rt.frozen_at = 0.0
                    rt.frozen_exit_at = None
                    if self._kernel == "event":
                        pod = self._pods_cache.get(key)
                        if pod is not None:
                            self._arm_for_pod_locked(key, pod, now)

    def schedule_node_faults(self, faults, on_fault=None) -> int:
        """Arm a ChaosPlan's data-plane stream (fleet/chaos.py
        ``node_faults``) on the event kernel's timer queue.  Each fault's
        abstract ``target`` is resolved NOW against the sorted live node
        list -- ``target % len(candidates)`` -- (domain kills resolve
        against the sorted set of ``NODE_SLICE_LABEL`` values and down
        every node in the chosen slice together), so the same plan on the
        same cluster always hits the same victims.  Flaps arm a
        ``chaos_recover`` timer ``down`` seconds after the hit and thaw
        with ``recover_node(dead=False)``; node/domain kills are permanent
        (a flap timer landing on a dead node is a no-op).  ``on_fault``
        is called with the fault kind as each entry fires.  Returns the
        number of faults scheduled.  Event kernel only: the scan kernel
        has no timer queue to carry the schedule."""
        if not faults:
            return 0
        if self._kernel != "event":
            raise RuntimeError(
                "schedule_node_faults requires the event kernel")
        now = time.time()
        scheduled = 0
        with self._lock:
            nodes = sorted(self._nodes_cache)
            domains: Dict[str, List[str]] = {}
            for name in nodes:
                slice_label = self._nodes_cache[name].metadata.labels.get(
                    constants.NODE_SLICE_LABEL)
                if slice_label:
                    domains.setdefault(slice_label, []).append(name)
            for i, fault in enumerate(faults):
                if fault.kind == "domain_down":
                    if not domains:
                        continue
                    doms = sorted(domains)
                    targets = tuple(domains[doms[fault.target % len(doms)]])
                else:
                    if not nodes:
                        continue
                    targets = (nodes[fault.target % len(nodes)],)
                key = f"@chaos/{i}"
                self._node_faults[key] = (fault, targets, on_fault)
                self._arm(key, "chaos", now + fault.at)
                scheduled += 1
        return scheduled

    def pending_node_faults(self) -> int:
        """Scheduled node faults that have not finished firing (a flap
        counts until its recovery timer has run).  Drivers wait for zero
        before judging convergence: a fault firing after the verdict would
        un-settle jobs nondeterministically."""
        with self._lock:
            return len(self._node_faults)

    def _fire_node_fault(self, key: str, now: float) -> None:
        with self._lock:
            entry = self._node_faults.get(key)
        if entry is None:
            return
        fault, targets, on_fault = entry
        hit = False
        for name in targets:
            if fault.kind == "node_flap":
                if name in self._node_dead:
                    continue  # permanently killed meanwhile: stays down
            else:
                self._node_dead.add(name)
            self.fail_node(name)
            hit = True
        if hit and on_fault is not None:
            try:
                on_fault(fault.kind)
            except Exception:
                log.exception("node-fault callback failed for %s", key)
        if fault.kind == "node_flap":
            self._arm(key, "chaos_recover", now + fault.down)
        else:
            with self._lock:
                self._node_faults.pop(key, None)

    def _fire_node_recover(self, key: str, now: float) -> None:
        with self._lock:
            entry = self._node_faults.pop(key, None)
        if entry is None:
            return
        _, targets, _ = entry
        for name in targets:
            if name not in self._node_dead:
                self.recover_node(name, dead=False)

    def preempt_pod(self, namespace: str, name: str, exit_code: int = 137) -> None:
        """SIGKILL analogue: container dies with the given code now."""
        with self._lock:
            rt = self._state.get(f"{namespace}/{name}")
            if rt is not None:
                rt.will_exit_at = time.time()
                rt.exit_code = exit_code
                if self._kernel == "event":
                    self._arm_now_locked(f"{namespace}/{name}", "exit")

    def flush_open_requests(self) -> int:
        """Drain boundary: evict every still-open synthesized request batch
        (the shutdown analogue of a serve drain), so the audit ledger can
        reconcile submitted vs terminal ids with no in-flight residue.
        Returns how many requests were flushed."""
        now = time.time()
        with self._lock:
            entries = [(self._pods_cache.get(key), rt)
                       for key, rt in self._state.items() if rt.req_open]
        flushed = 0
        for pod, rt in entries:
            if pod is None:
                rt.req_open = []
                continue
            flushed += len(rt.req_open)
            self._flush_requests(pod, rt, now)
        return flushed

    # -- the discrete-event kernel --------------------------------------------

    def _arm(self, key: str, kind: str, deadline: float) -> None:
        if self._timers.arm(key, kind, deadline):
            self.kick()  # new earliest deadline: wake the sleeping loop

    def _arm_now_locked(self, key: str, kind: str) -> None:
        self._arm(key, kind, time.time())

    def _rt_locked(self, key: str, uid: str) -> _PodRuntime:
        rt = self._state.get(key)
        if rt is None or (rt.uid and uid and rt.uid != uid):
            rt = self._new_state(uid)
            self._state[key] = rt
        return rt

    def _cancel_lifecycle_locked(self, key: str,
                                 keep: Tuple[str, ...] = ()) -> None:
        for kind in _POD_TIMER_KINDS:
            if kind not in keep:
                self._timers.cancel(key, kind)

    def _arm_for_pod_locked(self, key: str, pod: Pod, now: float) -> None:
        """Retarget ``key``'s timers from its freshly observed object: each
        watch event re-derives which single transition is next and arms
        exactly that.  Idempotent -- deadlines are derived from recorded
        state (scheduled_at, will_exit_at, terminating_since), so a re-arm
        from a no-op MODIFIED supersedes with the same instant."""
        rt = self._rt_locked(key, pod.metadata.uid)
        if pod.metadata.deletion_timestamp is not None:
            # Terminating: the grace clock is the only live deadline.  The
            # finalizer stamps terminating_since right after this event
            # drains; stamp first-observation time here so a created-then-
            # deleted-in-one-window pod can never wedge un-finalized.
            self._pending.discard(key)
            if rt.terminating_since is None:
                rt.terminating_since = now
            self._cancel_lifecycle_locked(key, keep=("grace",))
            self._arm(key, "grace",
                      rt.terminating_since + self._termination_grace)
            return
        phase = pod.status.phase
        if phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            self._pending.discard(key)
            self._cancel_lifecycle_locked(key)
            return
        if phase == PodPhase.PENDING and not pod.spec.node_name:
            # Newly pending feeds the scheduler event; an already-pending
            # pod's MODIFIED (e.g. our own Unschedulable condition echo)
            # must NOT re-arm it, or a never-fitting gang would spin.
            self._cancel_lifecycle_locked(key)
            if key not in self._pending:
                self._pending.add(key)
                self._arm_now_locked(_CLUSTER_KEY, "sched")
            return
        if phase == PodPhase.PENDING:
            # Scheduled: the start delay counts from first observation,
            # exactly like the scan kernel's walk.
            self._pending.discard(key)
            if rt.scheduled_at == 0.0:
                rt.scheduled_at = now
            try:
                delay = float(pod.metadata.annotations.get(
                    START_DELAY_ANNOTATION, self._start_delay))
            except ValueError:
                delay = self._start_delay
            self._arm(key, "start", rt.scheduled_at + delay)
            return
        if phase == PodPhase.RUNNING:
            self._pending.discard(key)
            self._timers.cancel(key, "start")
            if rt.frozen_on:
                # Dead host: no reports until recover_node re-arms "exit".
                self._cancel_lifecycle_locked(key)
                return
            if rt.will_exit_at is not None:
                self._arm(key, "exit", rt.will_exit_at)
            self._arm_step_locked(key, pod, rt)
            if ((pod.metadata.annotations.get(SERVE_QUEUE_ANNOTATION)
                 or pod.metadata.annotations.get(REQ_RATE_ANNOTATION))
                    and not self._timers.armed(key, "serve")):
                self._arm(key, "serve", now + self._tick)

    def _arm_step_locked(self, key: str, pod: Pod, rt: _PodRuntime) -> None:
        """Arm the next step-synthesis deadline: the instant step
        ``steps_reported + 1`` becomes due at the pod's effective step
        time.  A deliberately stalled rank stops re-arming at its cap (the
        watchdog's job starts where synthesis ends)."""
        interval = self._step_interval(pod)
        if interval is None or rt.started_at == 0.0:
            return
        cap = self._stall_cap(pod)
        if cap is not None and rt.steps_reported >= cap:
            return
        self._arm(key, "step",
                  rt.started_at + (rt.steps_reported + 1) * interval)

    @staticmethod
    def _step_interval(pod: Pod) -> Optional[float]:
        """Effective seconds per synthesized step, or None when the pod
        does not train (no/zero step-ms, malformed script, no owning job)."""
        ann = pod.metadata.annotations
        step_ms_raw = ann.get(STEP_MS_ANNOTATION)
        if not step_ms_raw:
            return None
        if not pod.metadata.labels.get(constants.JOB_NAME_LABEL):
            return None
        try:
            step_ms = float(step_ms_raw)
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
            if rank == int(ann.get(STRAGGLER_RANK_ANNOTATION, "-1")):
                step_ms *= float(ann.get(STRAGGLER_FACTOR_ANNOTATION, "3.0"))
        except ValueError:
            return None
        if step_ms <= 0.0:
            return None
        return step_ms / 1000.0

    @staticmethod
    def _stall_cap(pod: Pod) -> Optional[int]:
        """Step number past which this rank stops advancing, or None."""
        ann = pod.metadata.annotations
        try:
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
            if rank == int(ann.get(STALL_RANK_ANNOTATION, "-1")):
                return int(ann.get(STALL_AT_STEP_ANNOTATION, "0"))
        except ValueError:
            return None
        return None

    def _next_wait(self) -> Optional[float]:
        if self._kernel != "event":
            return self._tick
        deadline = self._timers.next_deadline()
        if deadline is None:
            return None  # nothing armed: sleep until a watch event kicks
        return max(0.0, deadline - time.time())

    def _reconcile_once(self) -> None:
        if self._kernel == "event":
            self._drain_events()
        else:
            self._scan_tick()

    def _drain_events(self) -> None:
        now = time.time()
        due = self._timers.pop_due(now, limit=_MAX_EVENTS_PER_DRAIN)
        if not due:
            return
        self.events_total += len(due)
        per_kind: Dict[str, int] = {}
        for _, kind, _ in due:
            per_kind[kind] = per_kind.get(kind, 0) + 1
        for kind, n in per_kind.items():
            METRICS.inc("trainingjob_sim_events_total", n, kind=kind)
        if set(per_kind) - {"watchdog"}:
            # One span per dispatched batch (the event kernel's analogue of
            # a scan pass); watchdog-only wakeups are heartbeat noise and
            # must not flood the trace ring.
            with TRACER.span("sim.event", events=len(due)):
                self._dispatch_batch(due, now)
        else:
            self._dispatch_batch(due, now)

    def _dispatch_batch(self, due: List[Tuple[str, str, float]],
                        now: float) -> None:
        for key, kind, deadline in due:
            try:
                if kind == "start":
                    self._fire_start(key, now)
                elif kind == "exit":
                    self._fire_exit(key, now)
                elif kind == "grace":
                    self._fire_grace(key, now)
                elif kind == "step":
                    self._fire_step(key, now)
                elif kind == "serve":
                    self._fire_serve(key, deadline, now)
                elif kind == "sched":
                    self._fire_sched()
                elif kind == "chaos":
                    self._fire_node_fault(key, now)
                elif kind == "chaos_recover":
                    self._fire_node_recover(key, now)
                elif kind == "watchdog":
                    TELEMETRY.check_stalls(now)
                    nxt = deadline + self._tick
                    self._arm(_CLUSTER_KEY, "watchdog",
                              nxt if nxt > now else now + self._tick)
            except Exception:
                log.exception("sim event %s for %s failed", kind, key)

    def _pod_rt_locked(self, key: str) -> Tuple[Optional[Pod],
                                                Optional[_PodRuntime]]:
        return self._pods_cache.get(key), self._state.get(key)

    def _node_ready_locked(self, pod: Pod) -> bool:
        node = (self._nodes_cache.get(pod.spec.node_name)
                if pod.spec.node_name else None)
        return node is not None and node.is_ready()

    def _fire_start(self, key: str, now: float) -> None:
        with self._lock:
            pod, rt = self._pod_rt_locked(key)
            if (pod is None or rt is None
                    or pod.metadata.deletion_timestamp is not None
                    or pod.status.phase != PodPhase.PENDING
                    or not pod.spec.node_name
                    or rt.frozen_on
                    or not self._node_ready_locked(pod)):
                return  # superseded; a later watch/node event re-arms
            pod = copy.deepcopy(pod)  # never mutate the cache
        with TRACER.span("sim.start", pod=key, node=pod.spec.node_name):
            pod.status.phase = PodPhase.RUNNING
            pod.status.start_time = now
            pod.status.container_statuses = [
                ContainerStatus(name=c.name,
                                state=ContainerState(running_started_at=now))
                for c in pod.spec.containers]
            run_s = pod.metadata.annotations.get(RUN_SECONDS_ANNOTATION)
            if not self._try_update_pod(pod):
                self._arm(key, "start", now + self._tick)  # conflict: retry
                return
        with self._lock:
            rt = self._state.get(key)
            if rt is None:
                return  # deleted during the write
            rt.started_at = now
            if run_s is not None and rt.will_exit_at is None:
                rt.will_exit_at = now + float(run_s)
                rt.exit_code = int(pod.metadata.annotations.get(
                    EXIT_CODE_ANNOTATION, "0"))
            cached = self._pods_cache.get(key)
            if cached is not None:
                self._arm_for_pod_locked(key, cached, now)

    def _fire_exit(self, key: str, now: float) -> None:
        with self._lock:
            pod, rt = self._pod_rt_locked(key)
            if (pod is None or rt is None
                    or pod.metadata.deletion_timestamp is not None
                    or pod.status.phase != PodPhase.RUNNING
                    or rt.frozen_on
                    or rt.will_exit_at is None
                    or not self._node_ready_locked(pod)):
                return
            if now < rt.will_exit_at:
                self._arm(key, "exit", rt.will_exit_at)  # deadline moved
                return
            code = rt.exit_code
            pod = copy.deepcopy(pod)  # never mutate the cache
        with TRACER.span("sim.exit", pod=key, exit_code=code) as sp:
            if code != 0:
                sp.set_status("error")
            pod.status.phase = (PodPhase.SUCCEEDED if code == 0
                                else PodPhase.FAILED)
            pod.status.container_statuses = [
                ContainerStatus(name=c.name,
                                state=ContainerState(
                                    terminated_exit_code=code,
                                    terminated_reason="Completed" if code == 0 else "Error"))
                for c in pod.spec.containers]
            if self._try_update_pod(pod):
                with self._lock:
                    rt = self._state.get(key)
                    if rt is not None:
                        rt.will_exit_at = None
                self._flush_requests(pod, rt, now)
            else:
                self._arm(key, "exit", now + self._tick)  # conflict: retry

    def _fire_grace(self, key: str, now: float) -> None:
        with self._lock:
            pod, rt = self._pod_rt_locked(key)
            if pod is None or pod.metadata.deletion_timestamp is None:
                return
            if rt is None:
                rt = self._rt_locked(key, pod.metadata.uid)
            if rt.terminating_since is None:
                rt.terminating_since = now
            remaining = (rt.terminating_since + self._termination_grace) - now
            if remaining > 0:
                # The finalizer stamped a fresher clock than our first
                # observation; honor the full grace from its stamp.
                self._arm(key, "grace", now + remaining)
                return
            namespace, _, name = key.partition("/")
        self._flush_requests(pod, rt, now)
        self._cs.tracker.finalize_delete(Pod.KIND, namespace, name)
        self._drop_state(namespace, name)
        self._timers.cancel_all(key)

    def _fire_step(self, key: str, now: float) -> None:
        with self._lock:
            pod, rt = self._pod_rt_locked(key)
            if (pod is None or rt is None
                    or pod.metadata.deletion_timestamp is not None
                    or pod.status.phase != PodPhase.RUNNING
                    or rt.frozen_on
                    or not self._node_ready_locked(pod)):
                return
        # Rendezvous BEFORE steps: a real survivor reports the rebootstrap
        # outcome before its first post-resize optimizer step, and the step
        # record is what closes the incident window -- reversed, the rung
        # stamp would race the close on the same tick.
        self._synthesize_rendezvous(pod, rt, now)
        self._synthesize_steps(pod, rt, now)
        with self._lock:
            if self._state.get(key) is rt:
                self._arm_step_locked(key, pod, rt)

    def _fire_serve(self, key: str, deadline: float, now: float) -> None:
        with self._lock:
            pod, rt = self._pod_rt_locked(key)
            if (pod is None
                    or pod.metadata.deletion_timestamp is not None
                    or pod.status.phase != PodPhase.RUNNING
                    or (rt is not None and rt.frozen_on)
                    or not self._node_ready_locked(pod)):
                return
        self._synthesize_serve(pod, now)
        if rt is not None:
            self._synthesize_requests(pod, rt, now)
        if (pod.metadata.annotations.get(SERVE_QUEUE_ANNOTATION)
                or pod.metadata.annotations.get(REQ_RATE_ANNOTATION)):
            nxt = deadline + self._tick
            self._arm(key, "serve", nxt if nxt > now else now + self._tick)

    def _fire_sched(self) -> None:
        """One placement round over the pending set -- the event analogue
        of the scan kernel's per-tick scheduling branch, re-armed by watch
        events whenever a pod joins the pending set or node capacity
        changes (never by our own Unschedulable condition echoes)."""
        with self._lock:
            if not self._pending:
                return
            nodes = dict(self._nodes_cache)
            active = list(self._active_cache.values())
        self._schedule_pending(nodes, active)

    # -- the scan kernel (TRAININGJOB_SIM_KERNEL=scan) ------------------------

    def _scan_tick(self) -> None:
        now = time.time()
        with self._lock:
            # Watch-fed snapshots: dict/list copies of privately-owned cached
            # objects, no per-tick store deepcopy.  Steady state walks only
            # the active subset; settled pods cost nothing per tick.
            nodes = dict(self._nodes_cache)
            active = list(self._active_cache.values())

        self._schedule_pending(nodes, active)

        # Walk ACTIVE pods through their lifecycle.  Settled pods are absent
        # by construction (and their _state entries age out via the two-walk
        # reap; the graceful-delete finalizer re-creates an entry, stamped,
        # if one is deleted later).
        for pod, rt in self._pod_states(active):
            if pod.metadata.deletion_timestamp is not None:
                if rt.terminating_since is None:
                    # The finalizer's stamp can be lost to the two-walk reap
                    # when a tick stalls on a long event-drain (the reap then
                    # runs against a pre-stall snapshot; see base.py).  A
                    # kubelet re-observing a terminating pod just starts the
                    # grace clock again -- without this the pod sits until
                    # the GC's deletion-timestamp expiry sweep (30s).
                    rt.terminating_since = now
                elif now - rt.terminating_since >= self._termination_grace:
                    self._flush_requests(pod, rt, now)
                    self._cs.tracker.finalize_delete(Pod.KIND, pod.namespace, pod.name)
                    self._drop_state(pod.namespace, pod.name)
                continue

            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue  # settled mid-snapshot: nothing left to report

            node = nodes.get(pod.spec.node_name) if pod.spec.node_name else None
            if node is None or not node.is_ready():
                continue  # unscheduled or dead node: no kubelet reports

            if pod.status.phase == PodPhase.PENDING and pod.spec.node_name:
                if rt.scheduled_at == 0.0:
                    rt.scheduled_at = now
                delay = float(pod.metadata.annotations.get(
                    START_DELAY_ANNOTATION, self._start_delay))
                if now - rt.scheduled_at >= delay:
                    with TRACER.span("sim.start",
                                     pod=f"{pod.namespace}/{pod.name}",
                                     node=pod.spec.node_name):
                        pod = copy.deepcopy(pod)  # never mutate the cache
                        pod.status.phase = PodPhase.RUNNING
                        pod.status.start_time = now
                        pod.status.container_statuses = [
                            ContainerStatus(name=c.name,
                                            state=ContainerState(running_started_at=now))
                            for c in pod.spec.containers]
                        run_s = pod.metadata.annotations.get(RUN_SECONDS_ANNOTATION)
                        if self._try_update_pod(pod):
                            rt.started_at = now
                            if run_s is not None and rt.will_exit_at is None:
                                rt.will_exit_at = now + float(run_s)
                                rt.exit_code = int(pod.metadata.annotations.get(
                                    EXIT_CODE_ANNOTATION, "0"))

            elif pod.status.phase == PodPhase.RUNNING and rt.frozen_on == "":
                self._synthesize_rendezvous(pod, rt, now)
                self._synthesize_steps(pod, rt, now)
                self._synthesize_serve(pod, now)
                self._synthesize_requests(pod, rt, now)

            if (pod.status.phase == PodPhase.RUNNING
                    and rt.will_exit_at is not None and now >= rt.will_exit_at):
                code = rt.exit_code
                with TRACER.span("sim.exit",
                                 pod=f"{pod.namespace}/{pod.name}",
                                 exit_code=code) as sp:
                    if code != 0:
                        sp.set_status("error")
                    pod = copy.deepcopy(pod)  # never mutate the cache
                    pod.status.phase = (PodPhase.SUCCEEDED if code == 0
                                        else PodPhase.FAILED)
                    pod.status.container_statuses = [
                        ContainerStatus(name=c.name,
                                        state=ContainerState(
                                            terminated_exit_code=code,
                                            terminated_reason="Completed" if code == 0 else "Error"))
                        for c in pod.spec.containers]
                    if self._try_update_pod(pod):
                        # Only clear after a successful write -- a conflict
                        # retries against a fresh snapshot next tick.
                        rt.will_exit_at = None
                        self._flush_requests(pod, rt, now)

        # The kubelet tick doubles as the step-progress watchdog tick, same
        # as the localproc runtime: a stalled pod above is still Running.
        TELEMETRY.check_stalls(now)

    # -- shared kernel pieces -------------------------------------------------

    def _schedule_pending(self, nodes: Dict[str, Node],
                          active: List[Pod]) -> None:
        """Gang-aware scheduling: group pending pods by (namespace, gang); a
        gang is placed only if every member fits simultaneously.  The
        usage/gang maps are maintained incrementally from watch events
        (``_account_pod_locked``) -- settled pods still occupy capacity
        but cost nothing per pass; a pending burst copies O(nodes +
        gangs), never O(pods)."""
        pending = [p for p in active
                   if p.status.phase == PodPhase.PENDING and not p.spec.node_name
                   and p.metadata.deletion_timestamp is None]
        if not pending:
            return
        with self._lock:
            # node -> usage (copies: _schedule_gang mutates them as it
            # places, and a failed write must not poison the live maps)
            pod_count = {n: u[0] for n, u in self._usage.items()}
            tpu_used = {n: u[1] for n, u in self._usage.items()}
            # Gang membership counts ALL live pods carrying the label,
            # not just pending ones: a gap-filled single member of an
            # otherwise-running gang must still be placeable (its
            # siblings already hold nodes).
            gang_totals = dict(self._gang_totals)
        gangs: Dict[tuple, list] = {}
        for pod in pending:
            gang = pod.metadata.labels.get(constants.GANG_LABEL, f"_solo_{pod.name}")
            gangs.setdefault((pod.namespace, gang), []).append(pod)
        for key, gang_pods in gangs.items():
            # Never place a partially OBSERVED gang: the controller creates
            # a slice's pods over several API calls, and placing the
            # visible subset would steal capacity the full gang needs.
            declared = gang_pods[0].metadata.labels.get(
                constants.GANG_SIZE_LABEL)
            if (declared and declared.isdigit()
                    and gang_totals.get(key, len(gang_pods)) < int(declared)):
                continue
            self._schedule_gang(gang_pods, nodes, pod_count, tpu_used)

    def _synthesize_steps(self, pod: Pod, rt: _PodRuntime, now: float) -> None:
        """Advance the pod's simulated step counter and push the records a
        real workload's TelemetryEmitter would have pushed."""
        ann = pod.metadata.annotations
        step_ms_raw = ann.get(STEP_MS_ANNOTATION)
        if not step_ms_raw or rt.started_at == 0.0:
            return
        try:
            step_ms = float(step_ms_raw)
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
            straggler_rank = int(ann.get(STRAGGLER_RANK_ANNOTATION, "-1"))
            if rank == straggler_rank:
                step_ms *= float(ann.get(STRAGGLER_FACTOR_ANNOTATION, "3.0"))
            target = int((now - rt.started_at) * 1000.0 / step_ms)
            stall_rank = int(ann.get(STALL_RANK_ANNOTATION, "-1"))
            if rank == stall_rank:
                target = min(target, int(ann.get(STALL_AT_STEP_ANNOTATION,
                                                 "0")))
            tokens = float(ann.get(TOKENS_PER_STEP_ANNOTATION, "0"))
            flops = float(ann.get(FLOPS_PER_STEP_ANNOTATION, "0"))
            peak = float(ann.get(PEAK_FLOPS_ANNOTATION, "0"))
            ckpt_ms = float(ann.get(CKPT_MS_ANNOTATION, "0"))
            hbm_bytes = float(ann.get(HBM_BYTES_ANNOTATION, "0"))
            restore_ms = float(ann.get(RESTORE_MS_ANNOTATION, "0"))
            compile_ms = float(ann.get(COMPILE_MS_ANNOTATION, "0"))
        except ValueError:
            return  # malformed script annotations: no telemetry
        if step_ms <= 0.0:
            return
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL, "")
        if not job_name:
            return
        job_key = f"{pod.namespace}/{job_name}"
        rtype = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL, "worker")
        if (rt.steps_reported == 0 and target > 0
                and (restore_ms or compile_ms)):
            # Fresh (re)start: a real workload's overlapped_restore pushes
            # its span durations before the first step record does.
            TELEMETRY.ingest({
                "v": 1, "job": job_key, "rtype": rtype, "rank": rank,
                "resume_restore_ms": restore_ms,
                "resume_compile_ms": compile_ms,
                "resume_overlapped": True, "ts": now,
            }, now=now)
        budget = _MAX_STEPS_PER_TICK
        while rt.steps_reported < target and budget > 0:
            record = {
                "v": 1, "job": job_key, "rtype": rtype, "rank": rank,
                "step": rt.steps_reported, "ms": step_ms, "ts": now,
            }
            if tokens:
                record["tokens"] = tokens
            if flops:
                record["flops"] = flops
            if peak:
                record["peak_flops"] = peak
            if ckpt_ms:
                record["ckpt_ms"] = ckpt_ms
            if hbm_bytes:
                record["hbm_bytes"] = hbm_bytes
            TELEMETRY.ingest(record, now=now)
            rt.steps_reported += 1
            budget -= 1

    def _synthesize_rendezvous(self, pod: Pod, rt: _PodRuntime,
                               now: float) -> None:
        """Watch the controller-published generation.json the way a real
        survivor's GenerationWatcher does, and push one rendezvous record
        per NEW generation -- the record a real fallback ladder emits after
        its rebootstrap (obs/telemetry.py ``rendezvous_ms``).  The resize
        dir and baseline generation come from the pod's own container env
        (the controller injects both; a script can also set them on the
        template), so the sim reads exactly the channel the controller
        writes."""
        ann = pod.metadata.annotations
        rdv_ms_raw = ann.get(RENDEZVOUS_MS_ANNOTATION)
        if not rdv_ms_raw or rt.started_at == 0.0:
            return
        env: Dict[str, str] = {}
        for container in pod.spec.containers:
            for e in container.env:
                if e.value is not None:
                    env[e.name] = e.value
        base = env.get(constants.RESIZE_DIR_ENV, "")
        if not base:
            return
        try:
            rdv_ms = float(rdv_ms_raw)
            baseline = int(env.get(constants.RENDEZVOUS_GENERATION_ENV, "0")
                           or "0")
        except ValueError:
            return  # malformed script annotations: no telemetry
        try:
            with open(os.path.join(base, "generation.json"), "r",
                      encoding="utf-8") as fh:
                gen = int(json.load(fh).get("generation", 0))
        except (OSError, ValueError, TypeError, AttributeError):
            return  # unpublished or mid-write: try again next tick
        if gen <= max(rt.generation_reported, baseline):
            return
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL, "")
        if not job_name:
            return
        rt.generation_reported = gen
        rung = ann.get(RENDEZVOUS_RUNG_ANNOTATION, "") or "live"
        TELEMETRY.ingest({
            "v": 1, "job": f"{pod.namespace}/{job_name}",
            "rtype": pod.metadata.labels.get(constants.REPLICA_NAME_LABEL,
                                             "worker"),
            "rank": int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0"),
            "rendezvous_ms": rdv_ms, "rendezvous_rung": rung, "ts": now,
        }, now=now)

    def _synthesize_serve(self, pod: Pod, now: float) -> None:
        """Push the serve snapshot a real DecodeService would have emitted
        (one per tick, naturally throttled by the kubelet cadence)."""
        ann = pod.metadata.annotations
        depth_raw = ann.get(SERVE_QUEUE_ANNOTATION)
        if not depth_raw:
            return
        try:
            depth = float(depth_raw)
            slots = float(ann.get(SERVE_SLOTS_ANNOTATION, "4"))
            # Unset active-slots defaults to the natural reading: a backed-
            # up queue means a full batch, an empty one means idle slots.
            active = float(ann.get(SERVE_ACTIVE_ANNOTATION,
                                   str(slots if depth > 0 else 0.0)))
            p99 = float(ann.get(SERVE_P99_ANNOTATION, "0"))
            tps = float(ann.get(SERVE_TPS_ANNOTATION, "0"))
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
        except ValueError:
            return  # malformed script annotations: no telemetry
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL, "")
        if not job_name:
            return
        TELEMETRY.ingest({
            "v": 1, "job": f"{pod.namespace}/{job_name}",
            "rtype": pod.metadata.labels.get(constants.REPLICA_NAME_LABEL,
                                             "serve"),
            "rank": rank, "serve_queue_depth": depth,
            "serve_active_slots": active, "serve_slots": slots,
            "serve_p99_ms": p99, "serve_tokens_per_sec": tps,
            "serve_completed": 0, "ts": now,
        }, now=now)

    def _synthesize_requests(self, pod: Pod, rt: _PodRuntime,
                             now: float) -> None:
        """Open ``req-rate`` new request ids for this tick and complete the
        previous tick's batch with TTFT/TPOT from the annotations -- the
        records a real workloads/serve.py DecodeService emits.  Every
        record carries the pod's submitted high-water mark, so the batch
        still open when the pod dies is exactly the gap reconcile() would
        file as ``orphaned`` -- unless a death path flushes it first
        (``_flush_requests``)."""
        ann = pod.metadata.annotations
        rate_raw = ann.get(REQ_RATE_ANNOTATION)
        if not rate_raw:
            return
        try:
            rate = int(rate_raw)
            ttft = float(ann.get(REQ_TTFT_ANNOTATION, "80"))
            tpot = float(ann.get(REQ_TPOT_ANNOTATION, "10"))
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
        except ValueError:
            return  # malformed script annotations: no telemetry
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL, "")
        if not job_name or rate <= 0:
            return
        job = f"{pod.namespace}/{job_name}"
        rtype = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL, "serve")
        done, rt.req_open = rt.req_open, [(rt.req_next + i, now)
                                          for i in range(rate)]
        rt.req_next += rate
        hwm = rt.req_next - 1
        tokens = 8
        for rid, t0 in done:
            TELEMETRY.ingest({
                "v": 1, "job": job, "rtype": rtype, "rank": rank,
                "request_outcome": "completed", "request_id": rid,
                "request_epoch": rt.uid, "submitted_hwm": hwm,
                "tokens": tokens, "ttft_ms": ttft, "tpot_ms": tpot,
                "arrival": t0,
                "phase_ms": {"queued": round(ttft * 0.25, 3),
                             "prefill": round(ttft * 0.75, 3),
                             "decode": round(tpot * (tokens - 1), 3)},
                "ts": now,
            }, now=now)

    def _flush_requests(self, pod: Optional[Pod], rt: Optional[_PodRuntime],
                        now: float) -> None:
        """Terminal flush for a dying pod: every still-open request id is
        reported ``evicted`` (attribution: all queued wall) so the audit
        ledger finds no id gap.  Idempotent -- the batch empties on first
        flush -- and called from every death path: exit, graceful-delete
        expiry, the scan kernel's finalize/exit branches, and DELETED."""
        if pod is None or rt is None or not rt.req_open:
            return
        job_name = pod.metadata.labels.get(constants.JOB_NAME_LABEL, "")
        done, rt.req_open = rt.req_open, []
        if not job_name:
            return
        job = f"{pod.namespace}/{job_name}"
        rtype = pod.metadata.labels.get(constants.REPLICA_NAME_LABEL, "serve")
        try:
            rank = int(pod.metadata.labels.get(
                constants.REPLICA_INDEX_LABEL, "0") or "0")
        except ValueError:
            rank = 0
        hwm = rt.req_next - 1
        for rid, t0 in done:
            TELEMETRY.ingest({
                "v": 1, "job": job, "rtype": rtype, "rank": rank,
                "request_outcome": "evicted", "request_id": rid,
                "request_epoch": rt.uid, "submitted_hwm": hwm,
                "tokens": 0, "arrival": t0,
                "phase_ms": {"queued": round(max(0.0, now - t0) * 1000.0, 3)},
                "ts": now,
            }, now=now)

    def _schedule_gang(self, gang_pods, nodes, pod_count, tpu_used) -> None:
        placements = []
        for pod in gang_pods:
            placed = False
            for node in nodes.values():
                if not node.is_ready():
                    continue
                if not self._selector_matches(pod, node):
                    continue
                if pod_count.get(node.name, 0) >= self._pods_per_node:
                    continue
                req = self._pod_tpu_request(pod)
                cap = int(node.status.capacity.get(constants.TPU_RESOURCE, 0))
                if req > 0 and tpu_used.get(node.name, 0) + req > cap:
                    continue
                placements.append((pod, node.name, req))
                pod_count[node.name] = pod_count.get(node.name, 0) + 1
                tpu_used[node.name] = tpu_used.get(node.name, 0) + req
                placed = True
                break
            if not placed:
                # Whole gang stays pending (all-or-nothing); roll back.
                for p, n, req in placements:
                    pod_count[n] -= 1
                    tpu_used[n] -= req
                for p in gang_pods:
                    self._mark_unschedulable(p)
                return
        # One span per committed gang placement (transitions only -- a gang
        # that stays pending retries every tick and must not flood the ring).
        with TRACER.span("sim.schedule", pods=len(placements)):
            for pod, node_name, _ in placements:
                pod = copy.deepcopy(pod)  # never mutate the cache
                pod.spec.node_name = node_name
                pod.status.conditions = [Condition(
                    type=PodConditionType.SCHEDULED, status=ConditionStatus.TRUE,
                    last_transition_time=time.time())]
                self._try_update_pod(pod)

    def _mark_unschedulable(self, pod: Pod) -> None:
        msg = "0/? nodes available: insufficient capacity"
        for cond in pod.status.conditions:
            if cond.type == PodConditionType.SCHEDULED:
                if cond.status == ConditionStatus.FALSE and cond.message == msg:
                    return
        pod = copy.deepcopy(pod)  # never mutate the cache
        pod.status.conditions = [Condition(
            type=PodConditionType.SCHEDULED, status=ConditionStatus.FALSE,
            reason="Unschedulable", message=msg,
            last_transition_time=time.time())]
        self._try_update_pod(pod)

    @staticmethod
    def _selector_matches(pod: Pod, node) -> bool:
        return all(node.metadata.labels.get(k) == v
                   for k, v in pod.spec.node_selector.items())

    @staticmethod
    def _pod_tpu_request(pod: Pod) -> int:
        total = 0
        for c in pod.spec.containers:
            total += int((c.resources.get("requests") or {}).get(
                constants.TPU_RESOURCE, 0))
        return total
