"""Local-process runtime: pods are real subprocesses on this machine.

The end-to-end path without a cluster: the controller creates Pod objects, this
runtime "schedules" them onto virtual nodes, launches ``command+args`` as a
subprocess with the pod's injected env, and reports status back -- so the full
operator stack (rendezvous env, restart machine, preemption, elasticity) is
exercised against real JAX worker processes (BASELINE configs 1-2 run this
way on CPU).

Networking: cluster DNS names do not resolve locally, so every env value
containing ``<name>.<namespace>:<port>`` is rewritten to ``127.0.0.1:<lport>``
through a shared, deterministic port map -- all pods of a job agree on the
mapping, and the owner of a name binds the mapped port.  Fault injection kills
real processes (SIGKILL = preemption; node fail = kill all pods of a virtual
node and mark it NotReady).
"""

from __future__ import annotations

import logging
import os
import re
import signal
import socket
import subprocess
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Optional, Tuple

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.tracker import AlreadyExistsError
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ConditionStatus,
    ContainerState,
    ContainerStatus,
    Pod,
    PodConditionType,
    PodPhase,
    make_ready_node,
    set_node_readiness,
)
from trainingjob_operator_tpu.obs.telemetry import TELEMETRY, TelemetrySink
from trainingjob_operator_tpu.obs.trace import TRACER
from trainingjob_operator_tpu.runtime.base import PodStateRuntime

log = logging.getLogger("trainingjob.localproc")

_port_cursor = [23000 + (os.getpid() % 200) * 50]
_port_lock = threading.Lock()


def _free_port() -> int:
    """Allocate from a private sequential range, bind-testing each candidate.

    Sequential allocation avoids the bind(0)-then-close TOCTOU where the
    kernel hands the same ephemeral port to two consecutive calls; the pid
    offset separates concurrent test processes.
    """
    with _port_lock:
        for _ in range(2000):
            _port_cursor[0] += 1
            if _port_cursor[0] >= 60000:
                _port_cursor[0] = 23000
            candidate = _port_cursor[0]
            with socket.socket() as s:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                try:
                    s.bind(("127.0.0.1", candidate))
                except OSError:
                    continue
                return candidate
        raise RuntimeError("no free local port found")


@dataclass
class _Proc:
    uid: str = ""
    popen: Optional[subprocess.Popen] = None
    node: str = ""
    log_path: str = ""
    terminating_since: Optional[float] = None
    sigkill_sent: bool = False


class LocalProcRuntime(PodStateRuntime):
    """Subprocess-backed kubelet for a Clientset-backed tracker."""

    thread_name = "localproc-kubelet"

    def __init__(self, clientset: Clientset, nodes: int = 1,
                 log_dir: Optional[str] = None, tick: float = 0.02,
                 termination_grace: float = 2.0,
                 pods_per_node: Optional[int] = None):
        super().__init__(clientset, tick)
        self._grace = termination_grace
        self._log_dir = Path(log_dir or "/tmp/tpu-trainingjob-logs")
        self._log_dir.mkdir(parents=True, exist_ok=True)
        self._port_map: Dict[Tuple[str, str], int] = {}
        #: (namespace, name) -> launch count: the per-pod monotonic attempt
        #: counter that keys log filenames.  A wall-clock-ms key collided
        #: when two restarts of the same pod landed in one millisecond,
        #: silently overwriting the earlier attempt's log -- exactly the
        #: log a crash-loop postmortem needs.
        self._launch_attempts: Dict[Tuple[str, str], int] = {}
        self._node_names = [f"local-{i}" for i in range(nodes)]
        #: None = unbounded (every pending pod launches).  Set to bound node
        #: capacity like a real cluster: pods beyond it go Unschedulable --
        #: what the controller's elastic starvation shrink keys on, letting
        #: node loss exercise the true resize path with real processes.
        self._pods_per_node = pods_per_node
        self._telemetry_sink: Optional[TelemetrySink] = None

    def _new_state(self, uid: str) -> _Proc:
        return _Proc(uid=uid)

    def _on_state_discarded(self, proc: _Proc) -> None:
        if proc.popen is not None and proc.popen.poll() is None:
            proc.popen.kill()

    def _signal_terminating(self, proc: _Proc) -> None:
        if proc.popen is not None and proc.popen.poll() is None:
            try:
                proc.popen.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        # Shared tick contract (base.py): wake the loop so the grace clock
        # and exit reporting for this pod start on the next pass, not up to
        # a full tick later.
        self.kick()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for name in self._node_names:
            try:
                self._cs.nodes.create(make_ready_node(name))
            except AlreadyExistsError:
                pass  # node survives from a previous runtime on this tracker
        # Per-step telemetry sink: loopback, ephemeral port.  Starting it
        # here (before the controller creates any pod) publishes the address
        # pod.set_env injects, so worker subprocesses push step records
        # straight back into the in-process aggregator.
        self._telemetry_sink = TelemetrySink().start()
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._telemetry_sink is not None:
            self._telemetry_sink.stop()
            self._telemetry_sink = None
        with self._lock:
            procs = list(self._state.values())
        for proc in procs:
            if proc.popen is not None and proc.popen.poll() is None:
                proc.popen.kill()

    # -- fault injection -----------------------------------------------------

    def preempt_pod(self, namespace: str, name: str) -> None:
        """SIGKILL the pod's process (spot reclaim analogue)."""
        with self._lock:
            proc = self._state.get(f"{namespace}/{name}")
        if proc is not None and proc.popen is not None and proc.popen.poll() is None:
            with TRACER.span("localproc.preempt", pod=f"{namespace}/{name}"):
                proc.popen.kill()

    def fail_node(self, node: str) -> None:
        """Kill every pod process on the node and mark it NotReady."""
        with self._lock:
            victims = [p for p in self._state.values() if p.node == node]
        with TRACER.span("localproc.fail_node", node=node,
                         pods=len(victims)):
            for proc in victims:
                if proc.popen is not None and proc.popen.poll() is None:
                    proc.popen.kill()
            set_node_readiness(self._cs, node, False)

    def recover_node(self, node: str) -> None:
        set_node_readiness(self._cs, node, True)

    def _pick_node(self, pod: Pod, ready_nodes) -> Optional[str]:
        """Capacity-aware placement (None = none fits); unbounded when
        pods_per_node is unset (hash spread, the historical behavior)."""
        if self._pods_per_node is None:
            return ready_nodes[hash(pod.name) % len(ready_nodes)]
        with self._lock:
            load: Dict[str, int] = {}
            for proc in self._state.values():
                if proc.popen is not None and proc.popen.poll() is None:
                    load[proc.node] = load.get(proc.node, 0) + 1
        for node in ready_nodes:
            if load.get(node, 0) < self._pods_per_node:
                return node
        return None

    def _mark_unschedulable(self, pod: Pod) -> None:
        """Same shape the sim scheduler reports (and kube-scheduler would):
        PodScheduled=False/Unschedulable -- the controller's elastic
        starvation shrink keys on it."""
        msg = "0/? nodes available: insufficient capacity"
        for cond in pod.status.conditions:
            if (cond.type == PodConditionType.SCHEDULED
                    and cond.status == ConditionStatus.FALSE
                    and cond.message == msg):
                return
        pod.status.conditions = [Condition(
            type=PodConditionType.SCHEDULED, status=ConditionStatus.FALSE,
            reason="Unschedulable", message=msg,
            last_transition_time=time.time())]
        self._try_update_pod(pod)

    def local_address(self, service_name: str, namespace: str, port: int) -> str:
        """The localhost address a cluster DNS name maps to (for tests)."""
        return f"127.0.0.1:{self._mapped_port(f'{service_name}.{namespace}', str(port))}"

    # -- internals -----------------------------------------------------------

    def _mapped_port(self, host: str, port: str) -> int:
        with self._lock:
            key = (host, port)
            lport = self._port_map.get(key)
            if lport is None:
                lport = _free_port()
                self._port_map[key] = lport
            return lport

    def _rewrite_value(self, value: str, namespace: str) -> str:
        pattern = re.compile(r"([A-Za-z0-9-]+\." + re.escape(namespace) + r"):(\d+)")

        def sub(m: "re.Match[str]") -> str:
            return f"127.0.0.1:{self._mapped_port(m.group(1), m.group(2))}"

        return pattern.sub(sub, value)

    def _reconcile_once(self) -> None:
        now = time.time()
        # The kubelet tick doubles as the step-progress watchdog tick: a
        # worker process that is alive but no longer stepping is invisible
        # to poll()-based liveness below.
        TELEMETRY.check_stalls(now)
        ready_nodes = [n.name for n in self._cs.nodes.list() if n.is_ready()]
        pods = self._cs.pods.list()

        for pod, proc in self._pod_states(pods):
            if pod.metadata.deletion_timestamp is not None:
                self._handle_terminating(pod, proc, now)
                continue

            if pod.status.phase == PodPhase.PENDING and proc.popen is None:
                if not ready_nodes:
                    continue
                node = self._pick_node(pod, ready_nodes)
                if node is None:
                    self._mark_unschedulable(pod)
                    continue
                self._launch(pod, proc, node)
                continue

            if proc.popen is not None:
                code = proc.popen.poll()
                if code is not None and pod.status.phase in (PodPhase.PENDING,
                                                             PodPhase.RUNNING):
                    self._report_exit(pod, code, node=proc.node)
                elif code is None and pod.status.phase == PodPhase.PENDING:
                    # An earlier Running status write hit a conflict; the
                    # list() snapshot is fresh now, so re-apply it (otherwise
                    # the pod would be stranded Pending forever).
                    self._mark_running(pod, proc)

    def _handle_terminating(self, pod: Pod, proc: _Proc, now: float) -> None:
        alive = proc.popen is not None and proc.popen.poll() is None
        since = proc.terminating_since or now
        if alive and now - since >= self._grace and not proc.sigkill_sent:
            proc.popen.kill()
            proc.sigkill_sent = True
            return
        if not alive:
            self._cs.tracker.finalize_delete(Pod.KIND, pod.namespace, pod.name)
            self._drop_state(pod.namespace, pod.name)

    def _launch(self, pod: Pod, proc: _Proc, node: str) -> None:
        if not pod.spec.containers:
            return
        container = pod.spec.containers[0]
        argv = list(container.command) + list(container.args)
        if not argv:
            self._report_exit(pod, 2, node=node, reason="NoCommand")
            return

        # Adopt the reconcile trace that created this pod (stamped into the
        # container env by pod.set_env); the launch span and the workload's
        # own spans then share its trace id.
        parent = next((e.value for e in container.env
                       if e.name == constants.TRACE_CONTEXT_ENV), None)
        with TRACER.span("localproc.launch", parent=parent,
                         pod=f"{pod.namespace}/{pod.name}", node=node) as sp:
            env = dict(os.environ)
            env["PYTHONPATH"] = (str(Path(__file__).resolve().parents[2])
                                 + os.pathsep + env.get("PYTHONPATH", ""))
            env[constants.RUNTIME_ENV] = "localproc"
            for e in container.env:
                env[e.name] = self._rewrite_value(e.value, pod.namespace)

            with self._lock:
                attempt = self._launch_attempts.get(
                    (pod.namespace, pod.name), 0) + 1
                self._launch_attempts[(pod.namespace, pod.name)] = attempt
            log_path = self._log_dir / (
                f"{pod.namespace}_{pod.name}_{attempt:04d}.log")
            try:
                log_file = open(log_path, "wb")
            except OSError as e:
                log.error("launch %s failed: %s", pod.name, e)
                sp.set_status("error")
                self._report_exit(pod, 127, node=node, reason="LaunchError")
                return
            try:
                popen = subprocess.Popen(
                    argv, env=env, stdout=log_file, stderr=subprocess.STDOUT,
                    cwd=container.working_dir or None,
                    start_new_session=True)
                # Hand the pid to the proc record before anything else can
                # raise: once spawned, the child must be reachable from
                # kubelet state (a later flush/status error would otherwise
                # orphan a live process behind a LaunchError report).
                proc.popen = popen
            except OSError as e:
                log.error("launch %s failed: %s", pod.name, e)
                sp.set_status("error")
                self._report_exit(pod, 127, node=node, reason="LaunchError")
                return
            finally:
                log_file.close()

            proc.node = node
            proc.log_path = str(log_path)
            self._mark_running(pod, proc)
            sp.set_attribute("pid", popen.pid)
        log.info("launched %s on %s (pid %d, log %s)",
                 pod.name, node, popen.pid, log_path)

    def _mark_running(self, pod: Pod, proc: _Proc) -> None:
        now = time.time()
        name = pod.spec.containers[0].name if pod.spec.containers else "main"
        pod.spec.node_name = proc.node
        pod.status.phase = PodPhase.RUNNING
        pod.status.start_time = now
        pod.status.conditions = [Condition(type=PodConditionType.SCHEDULED,
                                           status=ConditionStatus.TRUE,
                                           last_transition_time=now)]
        pod.status.container_statuses = [
            ContainerStatus(name=name,
                            state=ContainerState(running_started_at=now))]
        self._try_update_pod(pod)

    def _report_exit(self, pod: Pod, code: int, node: str = "",
                     reason: str = "") -> None:
        if code < 0:  # killed by signal N -> exit code 128+N (shell convention)
            code = 128 - code
        with TRACER.span("localproc.exit", pod=f"{pod.namespace}/{pod.name}",
                         exit_code=code) as sp:
            if code != 0:
                sp.set_status("error")
        pod.status.phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
        if node:
            pod.spec.node_name = node
        name = pod.spec.containers[0].name if pod.spec.containers else "main"
        pod.status.container_statuses = [
            ContainerStatus(name=name,
                            state=ContainerState(
                                terminated_exit_code=code,
                                terminated_reason=reason or (
                                    "Completed" if code == 0 else "Error")))]
        self._try_update_pod(pod)
