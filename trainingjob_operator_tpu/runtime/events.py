"""Deterministic timer queue for the event-driven sim kernel.

The discrete-event kernel (runtime/sim.py) replaces the per-tick pod walk
with "every pod schedules its *next* transition": start delay, exit-at,
graceful-delete expiry, step-synthesis cadence, serve-snapshot emission,
scheduler retry, watchdog probe.  This module is the queue those deadlines
live in -- a binary heap with two properties the kernel depends on:

- **Deterministic ordering.**  Entries pop in ``(deadline, seq)`` order,
  where ``seq`` is a monotonic arm counter: two timers due at the same
  instant fire in the order they were armed, every run.  Seeded fleet runs
  must produce byte-identical phase counts across kernels, so tie-breaking
  can never fall back on dict order or thread timing.

- **O(log n) cancel / re-arm by key.**  Watch events (delete, preempt,
  node fail) retarget a pod's pending timers constantly.  Each logical
  timer is addressed by ``(key, kind)``; arming again simply supersedes
  the old deadline and cancellation is a dict pop.  Superseded/cancelled
  heap entries are dropped lazily on pop ("tombstones"), with a compaction
  pass when tombstones outnumber live entries.

Thread-safety: all methods take the internal lock and touch nothing else,
so TimerQueue sits at the *bottom* of any lock order -- callers may hold
their own locks (the runtime's state lock, the tracker's dispatch lock)
when arming or cancelling, and the queue never calls back out.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, List, Optional, Tuple


class TimerQueue:
    """Keyed one-shot timers with deterministic (deadline, seq) ordering."""

    #: Compact when dead heap entries exceed this many *and* outnumber the
    #: live ones -- amortized O(1) per arm, bounded memory under re-arm storms.
    _COMPACT_SLACK = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._heap: List[Tuple[float, int, str, str]] = []
        #: (key, kind) -> (deadline, seq) of the *live* entry; a heap entry
        #: whose (deadline, seq) no longer matches is a tombstone.
        self._armed: Dict[Tuple[str, str], Tuple[float, int]] = {}
        self._seq = 0

    # -- arming ---------------------------------------------------------------

    def arm(self, key: str, kind: str, deadline: float) -> bool:
        """Schedule (or reschedule) the ``(key, kind)`` timer for
        ``deadline``.  Returns True when this became the queue's earliest
        deadline -- the caller should wake the sleeping kernel thread."""
        with self._lock:
            self._seq += 1
            entry = (deadline, self._seq)
            self._armed[(key, kind)] = entry
            heapq.heappush(self._heap, (deadline, self._seq, key, kind))
            self._maybe_compact_locked()
            return self._heap[0][1] == self._seq

    def cancel(self, key: str, kind: str) -> None:
        """Forget the ``(key, kind)`` timer if armed (tombstones the heap
        entry; it is skipped on pop)."""
        with self._lock:
            self._armed.pop((key, kind), None)

    def cancel_all(self, key: str) -> None:
        """Forget every timer armed under ``key`` (pod deleted)."""
        with self._lock:
            dead = [k for k in self._armed if k[0] == key]
            for k in dead:
                del self._armed[k]

    def armed(self, key: str, kind: str) -> bool:
        """Whether a live ``(key, kind)`` timer is pending.  Lets callers
        keep a relative-cadence timer (serve snapshots every tick) from
        being pushed ever later by unrelated re-arms."""
        with self._lock:
            return (key, kind) in self._armed

    # -- draining -------------------------------------------------------------

    def next_deadline(self) -> Optional[float]:
        """Earliest live deadline, or None when nothing is armed."""
        with self._lock:
            while self._heap:
                deadline, seq, key, kind = self._heap[0]
                if self._armed.get((key, kind)) == (deadline, seq):
                    return deadline
                heapq.heappop(self._heap)  # tombstone
            return None

    def pop_due(self, now: float,
                limit: Optional[int] = None) -> List[Tuple[str, str, float]]:
        """Remove and return every timer with ``deadline <= now`` as
        ``(key, kind, deadline)`` tuples in deterministic (deadline, seq)
        order.  ``limit`` bounds one drain so a storm cannot starve the
        kernel loop's wake/stop checks."""
        due: List[Tuple[str, str, float]] = []
        with self._lock:
            while self._heap and (limit is None or len(due) < limit):
                deadline, seq, key, kind = self._heap[0]
                if deadline > now:
                    break
                heapq.heappop(self._heap)
                if self._armed.get((key, kind)) == (deadline, seq):
                    del self._armed[(key, kind)]
                    due.append((key, kind, deadline))
        return due

    def depth(self) -> int:
        """Live (armed) timer count -- the queue-depth gauge."""
        with self._lock:
            return len(self._armed)

    # -- internals ------------------------------------------------------------

    def _maybe_compact_locked(self) -> None:
        dead = len(self._heap) - len(self._armed)
        if dead > self._COMPACT_SLACK and dead > len(self._armed):
            live = {(ds[0], ds[1], k[0], k[1])
                    for k, ds in self._armed.items()}
            self._heap = [e for e in self._heap if e in live]
            heapq.heapify(self._heap)
