"""Cluster runtime backends.

The control plane talks to a ``Clientset``; a runtime is what makes the
objects *behave*: schedule pods onto nodes, run their containers, report
status, honor graceful deletion.

- ``sim``       -- in-process simulated kubelet+scheduler (tests, bench,
                   fault injection).
- ``localproc`` -- pods are real subprocesses on this machine (end-to-end
                   JAX workloads without a cluster).
- ``kube``      -- adapter to a real Kubernetes cluster (gated on the
                   ``kubernetes`` package being installed).
"""
