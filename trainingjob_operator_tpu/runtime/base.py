"""Shared kubelet-loop machinery for pod-running backends.

Both runtimes (sim, localproc) need the same skeleton: a background tick loop,
a per-pod state map that survives ticks but not pod incarnations (keyed by
namespace/name, reset when the UID changes -- a force-deleted pod recreated
under the same name is a NEW pod), reaping of state for vanished pods, the
graceful-deletion finalizer hookup, and conflict-tolerant status writes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.tracker import ConflictError, NotFoundError
from trainingjob_operator_tpu.core.objects import Pod
from trainingjob_operator_tpu.obs.profiler import PROFILER

log = logging.getLogger("trainingjob.runtime")


class PodStateRuntime:
    """Base for runtimes that track per-pod state across ticks.

    Subclasses provide ``_new_state(uid)`` and ``_reconcile_once()`` and may
    override ``_on_state_discarded(state)`` to release resources (e.g. kill a
    process) when a pod vanishes or is replaced by a new incarnation.
    """

    thread_name = "runtime"

    def __init__(self, clientset: Clientset, tick: float):
        self._cs = clientset
        self._tick = tick
        self._state: Dict[str, Any] = {}
        self._missing: set = set()  # keys absent from exactly one walk
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: Loop cost accounting, read by the fleet harness's kernel A/B:
        #: passes through _reconcile_once and the CPU seconds they burned
        #: (thread time, so sleeps and lock waits don't count).
        self.loop_passes = 0
        self.loop_cpu_seconds = 0.0
        clientset.tracker.register_finalizer(Pod.KIND, self._on_terminating)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # Register the kubelet thread's name with the span profiler so a
        # subclass with a custom ``thread_name`` is still sampled -- the
        # sim/controller CPU split is exactly what the profiler exists to
        # measure (obs/profiler.py; no-op unless the profiler runs).
        PROFILER.note_thread_prefix(self.thread_name)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.thread_name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=3)

    def kick(self) -> None:
        """Wake the loop before its current sleep expires.  Watch handlers
        call this when they arm a deadline earlier than the one the loop
        went to sleep on; a spurious kick just costs one empty reconcile."""
        self._wake.set()

    def _next_wait(self) -> Optional[float]:
        """Seconds to sleep before the next reconcile; None blocks until
        ``kick()``.  The default is the fixed tick cadence every scanning
        runtime (localproc, the sim's scan kernel) was built around; the
        event kernel overrides this with time-to-earliest-deadline."""
        return self._tick

    def _loop(self) -> None:
        while True:
            self._wake.wait(self._next_wait())
            self._wake.clear()
            if self._stop.is_set():
                return
            t0 = time.thread_time()
            try:
                self._reconcile_once()
            except Exception:
                log.exception("%s loop error", self.thread_name)
            finally:
                self.loop_cpu_seconds += time.thread_time() - t0
                self.loop_passes += 1

    # -- per-pod state map ----------------------------------------------------

    def _new_state(self, uid: str) -> Any:
        raise NotImplementedError

    def _reconcile_once(self) -> None:
        raise NotImplementedError

    def _on_state_discarded(self, state: Any) -> None:
        """Release resources held by a discarded state entry."""

    def _on_terminating(self, pod: Pod) -> None:
        """Graceful-delete finalizer: record when termination began."""
        with self._lock:
            state = self._state.setdefault(f"{pod.namespace}/{pod.name}",
                                           self._new_state(pod.metadata.uid))
            if not state.uid:
                state.uid = pod.metadata.uid
            state.terminating_since = time.time()
        self._signal_terminating(state)

    def _signal_terminating(self, state: Any) -> None:
        """Hook: deliver the SIGTERM analogue."""

    def _pod_states(self, pods: List[Pod]) -> Iterable[Tuple[Pod, Any]]:
        """Pair each pod with its state entry; reap vanished pods' state and
        reset entries whose pod was replaced by a new incarnation."""
        existing = {f"{p.namespace}/{p.name}" for p in pods}
        with self._lock:
            # Reap only keys missing from TWO consecutive walks.  The
            # caller's pod snapshot predates this walk, and the graceful-
            # delete finalizer can create a state entry (terminating_since
            # stamped) for a pod created-then-deleted inside that window;
            # reaping it on the first miss loses the stamp, and the fresh
            # entry the next walk creates never finalizes -- the pod then
            # sits until the GC's deletion-timestamp expiry sweep.
            stale = [k for k in self._state if k not in existing]
            discarded = []
            missed_once = set()
            for k in stale:
                if k in self._missing:
                    discarded.append(self._state.pop(k))
                else:
                    missed_once.add(k)
            self._missing = missed_once
        for state in discarded:
            self._on_state_discarded(state)

        for pod in pods:
            key = f"{pod.namespace}/{pod.name}"
            with self._lock:
                state = self._state.setdefault(key, self._new_state(pod.metadata.uid))
                if state.uid != pod.metadata.uid:
                    old = state
                    state = self._new_state(pod.metadata.uid)
                    self._state[key] = state
                else:
                    old = None
            if old is not None:
                self._on_state_discarded(old)
            yield pod, state

    def _drop_state(self, namespace: str, name: str) -> None:
        with self._lock:
            self._state.pop(f"{namespace}/{name}", None)

    # -- status writes --------------------------------------------------------

    def _try_update_pod(self, pod: Pod) -> bool:
        """Write pod status; False on conflict/not-found (caller retries next
        tick against a fresh snapshot)."""
        try:
            self._cs.pods.update(pod)
            return True
        except (ConflictError, NotFoundError):
            return False
        except Exception:
            log.exception("pod status update failed for %s", pod.name)
            return False
