"""Real-Kubernetes backend (gated on the ``kubernetes`` package).

Reference: the reference operator talks to a real apiserver through generated
clients (pkg/client/) and self-creates its CRD (controller.go:210-234).  This
module provides:

- ``crd_manifest()`` -- a structural-schema CRD manifest (the modern form of
  the reference's schema-less v1beta1 self-creation, SURVEY.md §8), always
  available for ``kubectl apply``.
- ``KubeClientset`` -- an adapter with the same surface as
  ``client.Clientset``, backed by the kubernetes Python client.  Importing it
  without the package installed raises a clear error; the rest of the
  framework never imports this module unless ``--backend kube`` is requested.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from trainingjob_operator_tpu.api import constants


def kubernetes_available() -> bool:
    try:
        import kubernetes  # noqa: F401

        return True
    except ImportError:
        return False


def crd_manifest() -> Dict[str, Any]:
    """Structural CRD for TPUTrainingJob (apply with kubectl or via
    KubeClientset.ensure_crd)."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{constants.KIND_PLURAL}.{constants.GROUP_NAME}"},
        "spec": {
            "group": constants.GROUP_NAME,
            "scope": "Namespaced",
            "names": {
                "kind": constants.KIND,
                "plural": constants.KIND_PLURAL,
                "singular": constants.KIND.lower(),
                "shortNames": [constants.SHORT_NAME],
            },
            "versions": [{
                "name": constants.GROUP_VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": {"type": "object",
                                 "x-kubernetes-preserve-unknown-fields": True},
                        "status": {"type": "object",
                                   "x-kubernetes-preserve-unknown-fields": True},
                    },
                }},
            }],
        },
    }


class KubeClientset:
    """Clientset-compatible adapter over the kubernetes Python client.

    Objects cross the boundary as dicts via the dataclasses' to_dict/from_dict,
    so the controller code is identical against sim and real clusters.
    """

    def __init__(self, kubeconfig: Optional[str] = None, master_url: str = "",
                 in_cluster: bool = False):
        if not kubernetes_available():
            raise ImportError(
                "the 'kubernetes' package is not installed; the kube backend "
                "is unavailable in this environment (use --backend sim or "
                "localproc, or export manifests via runtime.kube.crd_manifest)")
        raise NotImplementedError(
            "KubeClientset CRUD adapters land with the kube backend milestone; "
            "this build targets the sim and localproc backends")
