"""Real-Kubernetes backend glue: CRD manifest + runtime adapter.

Reference: the reference operator talks to a real apiserver through generated
clients (pkg/client/, cmd/app/server.go:111-151) and self-creates its CRD
(controller.go:210-234).  This module provides:

- ``crd_manifest()`` -- a structural-schema CRD manifest (the modern form of
  the reference's schema-less v1beta1 self-creation, SURVEY.md §8), applied
  by ``KubeClientset.ensure_crd`` at startup or via ``kubectl apply``.
- ``KubeRuntime`` -- the runtime-shaped adapter for the kube backend: there
  is no local kubelet to run (the cluster runs pods); start/stop manage the
  CRD bootstrap and the reflector threads feeding the informer cache.

The transport is the stdlib REST client (client/rest.py) + typed adapters
(client/kube.py) -- no ``kubernetes`` package required.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from trainingjob_operator_tpu.api import constants


def crd_manifest() -> Dict[str, Any]:
    """Structural CRD for TPUTrainingJob (apply with kubectl or via
    KubeClientset.ensure_crd)."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{constants.KIND_PLURAL}.{constants.GROUP_NAME}"},
        "spec": {
            "group": constants.GROUP_NAME,
            "scope": "Namespaced",
            "names": {
                "kind": constants.KIND,
                "plural": constants.KIND_PLURAL,
                "singular": constants.KIND.lower(),
                "shortNames": [constants.SHORT_NAME],
            },
            "versions": [{
                "name": constants.GROUP_VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": {"type": "object",
                                 "x-kubernetes-preserve-unknown-fields": True},
                        "status": {"type": "object",
                                   "x-kubernetes-preserve-unknown-fields": True},
                    },
                }},
            }],
        },
    }


class KubeRuntime:
    """Runtime-shaped lifecycle for the kube backend.

    The other backends' runtimes ARE the cluster (sim kubelet, local
    processes); on a real cluster the kubelet/scheduler already exist, so
    ``start`` only has to (a) self-create the CRD like the reference
    (controller.go:210-234) and (b) start the reflectors that feed the
    informer cache, blocking until the initial LISTs land
    (WaitForCacheSync, controller.go:195).

    Telemetry (stub wiring): pass ``telemetry_port`` to also run the
    per-step telemetry sink (obs/telemetry.py) bound to 0.0.0.0, and
    ``telemetry_advertise`` with an address pods can reach (the operator
    pod's service/DNS name -- in-cluster pods cannot reach the operator's
    loopback).  The advertised address is what pod.set_env injects as
    ``TRAININGJOB_TELEMETRY_ADDR``.  Left at 0, no sink runs and workload
    telemetry stays disabled -- safe default for the stub backend.
    """

    def __init__(self, clientset: Any, apply_crd: bool = True,
                 telemetry_port: int = 0, telemetry_advertise: str = ""):
        self._cs = clientset
        self._apply_crd = apply_crd
        self._telemetry_port = telemetry_port
        self._telemetry_advertise = telemetry_advertise
        self._telemetry_sink = None

    def start(self) -> None:
        if self._apply_crd:
            if self._cs.ensure_crd():
                import logging

                logging.getLogger("trainingjob.kube").info(
                    "created CRD %s.%s", constants.KIND_PLURAL,
                    constants.GROUP_NAME)
        if self._telemetry_port:
            from trainingjob_operator_tpu.obs.telemetry import TelemetrySink

            # check_interval: no kubelet tick exists on this backend, so the
            # sink runs the stall watchdog on its own timer.
            self._telemetry_sink = TelemetrySink(
                host="0.0.0.0", port=self._telemetry_port,
                advertise=self._telemetry_advertise,
                check_interval=1.0).start()
        self._cs.start(wait_synced=True)

    def stop(self) -> None:
        if self._telemetry_sink is not None:
            self._telemetry_sink.stop()
            self._telemetry_sink = None
        self._cs.stop()
