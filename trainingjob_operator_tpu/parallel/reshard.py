"""Peer-to-peer shard redistribution for in-place elastic resize.

When a Resize-scope group loses a replica (docs/ELASTIC.md), the survivors
keep their processes -- and therefore their live parameter/optimizer shards
-- and re-form the mesh at the new width.  The shards they already hold are
the wrong slices for the new layout, but almost all of the bytes are
already resident: redistribution is a device-to-device exchange, not a
checkpoint round-trip.

Two layers:

- **Plan arithmetic** (pure, testable): ``shard_ranges`` / ``plan_exchange``
  model one array axis chunked jax-style (ceil division, last shard ragged)
  across the old and new shard counts, and emit per-destination segments
  tagged with the source shard that holds them.  A segment whose source
  died with the lost replica is ``missing`` -- survivors cannot cover it and
  the caller must fall back to the checkpoint (``plan.covered`` gates the
  fast path).  With FSDP sharding the parameter axis never lives on a lost
  host alone unless that host held the only copy, so in the common
  dp-replicated case every segment is covered.
- **Live executor** (``redistribute``): ``jax.device_put`` of the live
  pytree onto the new mesh's shardings.  XLA turns the placement delta into
  direct device-to-device copies; elements whose source and destination
  shard coincide do not move at all (the plan's ``stationary`` share, the
  reason wide->narrow resharding beats any restore).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Segment:
    """One contiguous run of elements destined for shard ``dst``.

    ``src`` is the old shard that holds the run, or None when that shard
    was lost with the dead replica (checkpoint fallback required).
    """

    dst: int
    src: Optional[int]
    start: int
    stop: int

    @property
    def size(self) -> int:
        return self.stop - self.start


@dataclass(frozen=True)
class ExchangePlan:
    """The full segment list for one axis of one (logical) array."""

    n: int
    old_shards: int
    new_shards: int
    segments: Tuple[Segment, ...]

    @property
    def stationary(self) -> Tuple[Segment, ...]:
        """Runs already resident on their destination shard: zero traffic."""
        return tuple(s for s in self.segments
                     if s.src is not None and s.src == s.dst)

    @property
    def moves(self) -> Tuple[Segment, ...]:
        """Runs that cross shards: the peer-to-peer traffic."""
        return tuple(s for s in self.segments
                     if s.src is not None and s.src != s.dst)

    @property
    def missing(self) -> Tuple[Segment, ...]:
        """Runs whose only source died: survivors cannot supply them."""
        return tuple(s for s in self.segments if s.src is None)

    @property
    def covered(self) -> bool:
        """True when the survivors hold every element of the new layout --
        the gate for the in-place fast path (else: orbax fallback)."""
        return not self.missing

    def bytes_moved(self, itemsize: int = 4) -> int:
        return sum(s.size for s in self.moves) * itemsize

    def stats(self, itemsize: int = 4) -> Dict[str, int]:
        return {
            "moved_bytes": self.bytes_moved(itemsize),
            "stationary_bytes": sum(s.size for s in self.stationary) * itemsize,
            "missing_bytes": sum(s.size for s in self.missing) * itemsize,
        }


def shard_ranges(n: int, shards: int) -> List[Tuple[int, int]]:
    """Per-shard [start, stop) element ranges, jax-style ceil chunking:
    every shard but possibly the last holds ``ceil(n/shards)`` elements,
    trailing shards may be empty when ``shards > n``."""
    if n < 0 or shards <= 0:
        raise ValueError(f"need n >= 0 and shards > 0, got n={n}, "
                         f"shards={shards}")
    chunk = -(-n // shards) if n else 0
    return [(min(i * chunk, n), min((i + 1) * chunk, n))
            for i in range(shards)]


def plan_exchange(n: int, old_shards: int, new_shards: int,
                  lost: Iterable[int] = ()) -> ExchangePlan:
    """Plan the old->new redistribution of one axis of ``n`` elements.

    ``lost`` are OLD shard indices that died with the resize: their runs
    come out as ``src=None`` (missing).  The segments partition [0, n)
    exactly -- every element of the new layout is accounted for, covered
    or not.
    """
    dead = frozenset(lost)
    old = shard_ranges(n, old_shards)
    segments: List[Segment] = []
    for dst, (a, b) in enumerate(shard_ranges(n, new_shards)):
        for src, (oa, ob) in enumerate(old):
            start, stop = max(a, oa), min(b, ob)
            if stop > start:
                segments.append(Segment(
                    dst=dst, src=None if src in dead else src,
                    start=start, stop=stop))
    return ExchangePlan(n=n, old_shards=old_shards, new_shards=new_shards,
                        segments=tuple(segments))


def plan_pytree_exchange(shapes: Dict[str, Tuple[int, ...]],
                         old_shards: int, new_shards: int,
                         lost: Iterable[int] = (), axis: int = 0,
                         itemsize: int = 4) -> Dict[str, Any]:
    """Aggregate exchange plans over a pytree's leaf shapes.

    ``shapes`` maps leaf path -> array shape (as the checkpoint layout
    tool reports them); the sharded ``axis`` of each leaf is planned
    independently, the off-axis extent scales the byte counts.  Returns
    ``{"plans": {path: plan}, "covered": bool, "moved_bytes": int,
    "stationary_bytes": int, "missing_bytes": int}`` -- the caller's one
    fast-path/fallback decision plus the traffic it should expect.
    """
    plans: Dict[str, ExchangePlan] = {}
    totals = {"moved_bytes": 0, "stationary_bytes": 0, "missing_bytes": 0}
    for path, shape in sorted(shapes.items()):
        if not shape:
            continue
        ax = axis if axis < len(shape) else 0
        row = itemsize
        for i, dim in enumerate(shape):
            if i != ax:
                row *= dim
        plan = plan_exchange(shape[ax], old_shards, new_shards, lost)
        plans[path] = plan
        for key, value in plan.stats(row).items():
            totals[key] += value
    return {"plans": plans,
            "covered": all(p.covered for p in plans.values()),
            **totals}


def redistribute(tree: Any, new_mesh: Any) -> Any:
    """Device-to-device reshard of a LIVE pytree onto ``new_mesh``.

    Each leaf keeps its own PartitionSpec -- the layout the sharding rules
    chose at init -- re-fitted onto the new (narrower or wider) mesh via
    ``fit_spec``, so one call handles params AND optimizer state without
    re-deriving rules for optax's wrapper paths.  ``jax.device_put`` with
    the new NamedShardings lets the runtime express the placement delta as
    direct copies between the surviving devices -- no host staging, no
    checkpoint round-trip.  The input tree must be fully addressable by
    this process (single-process sim, or after the survivors'
    re-initialize), which is exactly the state the resize loop is in when
    it calls us.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from trainingjob_operator_tpu.parallel.sharding import fit_spec

    def place(leaf: Any) -> Any:
        if not isinstance(leaf, jax.Array):
            return leaf
        old = leaf.sharding
        spec = old.spec if isinstance(old, NamedSharding) else PartitionSpec()
        fitted = fit_spec(tuple(spec), leaf.shape, new_mesh)
        return jax.device_put(leaf, NamedSharding(new_mesh, fitted))

    return jax.tree_util.tree_map(place, tree)
