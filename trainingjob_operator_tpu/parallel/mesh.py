"""Device-mesh construction for the operator-provisioned topology.

Axis convention (scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- ``dp``   -- data parallel; gradients all-reduce.  Across slices this axis
              rides DCN (multislice), within a slice ICI.
- ``fsdp`` -- fully-sharded data parallel; params/opt-state sharded, gathered
              per layer (XLA all-gather / reduce-scatter on ICI).
- ``tp``   -- tensor parallel; activations collective on ICI every layer.
- ``sp``   -- sequence/context parallel; ring attention ppermutes KV blocks.

The operator tells each worker its slice topology via env
(TRAININGJOB_TPU_TOPOLOGY, MEGASCALE_NUM_SLICES); ``mesh_from_rendezvous``
turns that into a concrete mesh.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous

#: DCN-outermost order: dp (gradient all-reduce) and pp (infrequent
#: point-to-point stage hand-offs) tolerate the slow link; fsdp/tp/sp/ep are
#: per-layer ICI collectives.
AXIS_ORDER = ("dp", "pp", "fsdp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    """Axis sizes, in DCN-outermost order."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def of(cls, **sizes: int) -> "MeshSpec":
        axes = tuple((name, int(sizes[name])) for name in AXIS_ORDER
                     if name in sizes and sizes[name] > 0)
        extra = set(sizes) - set(AXIS_ORDER)
        if extra:
            raise ValueError(f"unknown mesh axes {sorted(extra)}; "
                             f"known: {AXIS_ORDER}")
        return cls(axes)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    def size(self) -> int:
        return math.prod(self.shape) if self.axes else 1


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh``; axis product must equal device count.

    DCN-aware: when more than one slice is present (multislice), the leading
    axis should be the DCN axis (dp) so inter-slice traffic is only gradient
    all-reduce -- use ``jax.experimental.mesh_utils`` device ordering when
    running on real multislice hardware.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from trainingjob_operator_tpu.parallel.collectives import device_slice_id

    devs = list(devices if devices is not None else jax.devices())
    want = spec.size()
    if want != len(devs):
        raise ValueError(
            f"mesh {dict(spec.axes)} needs {want} devices, have {len(devs)}")
    slice_ids = {device_slice_id(d) for d in devs}
    if len(slice_ids) > 1:
        # Multislice: the LEADING axis must stride across slices and every
        # trailing axis stay inside one slice -- dp carries the DCN hop,
        # fsdp/tp/sp ride ICI (the layout axis_crosses_dcn/require_ici_axis
        # enforce).  Validate the geometry BEFORE building anything: a
        # leading axis that cannot absorb whole slices would silently put
        # inner axes on DCN.
        n_slices = len(slice_ids)
        if spec.shape[0] % n_slices != 0 or len(devs) % n_slices != 0:
            raise ValueError(
                f"multislice mesh {dict(spec.axes)}: leading axis "
                f"{spec.names[0]}={spec.shape[0]} must be a multiple of the "
                f"{n_slices} slices (else inner axes would cross DCN)")
        if all(getattr(d, "slice_index", None) is not None for d in devs):
            # Real TPU multislice: let mesh_utils order within-slice devices
            # along the ICI torus (neighbor collectives), with the DCN
            # product on the leading axis.
            try:
                from jax.experimental import mesh_utils

                dcn_shape = [1] * len(spec.shape)
                per_slice = list(spec.shape)
                dcn_shape[0] = n_slices
                per_slice[0] = spec.shape[0] // n_slices
                arr = mesh_utils.create_hybrid_device_mesh(
                    per_slice, dcn_shape, devices=devs)
                return Mesh(arr, spec.names)
            except Exception as exc:
                import logging

                logging.getLogger("trainingjob.mesh").warning(
                    "create_hybrid_device_mesh failed (%s); falling back to "
                    "slice-major ordering", exc)
        # Virtual multislice (CPU test mesh): no ICI topology to read; a
        # slice-major sort gives the correct DCN structure (validated above).
        arr = np.array(sorted(devs, key=lambda d: (device_slice_id(d),
                                                   getattr(d, "id", 0)))
                       ).reshape(spec.shape)
        return Mesh(arr, spec.names)
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(spec.shape, devices=devs)
    # analyzer: allow[broad-except]: mesh_utils needs real topology info;
    # on CPU test meshes any failure falls back to flat device order.
    except Exception:
        arr = np.array(devs).reshape(spec.shape)
    return Mesh(arr, spec.names)


def mesh_from_rendezvous(rdv: Rendezvous, model_parallel: int = 1,
                         sequence_parallel: int = 1,
                         expert_parallel: int = 1,
                         pipeline_parallel: int = 1,
                         fsdp: bool = True):
    """Derive the standard mesh for this worker's provisioned topology.

    Local devices x num_processes = global devices; DCN (slices) maps to the
    leading dp axis, ICI carries fsdp/tp/sp/ep (``ep`` carries the MoE
    expert all-to-all, models/moe.py -- latency-bound, so it must never
    cross DCN).
    """
    import jax

    n = jax.device_count()
    inner = (model_parallel * sequence_parallel * expert_parallel
             * pipeline_parallel)
    if n % inner != 0:
        raise ValueError(f"{n} devices not divisible by "
                         f"tp*sp*ep*pp={inner}")
    data = n // inner
    dp = max(rdv.num_slices, 1)
    if data % dp != 0:
        # Never silently let fsdp span slices: per-layer all-gathers would
        # ride DCN instead of ICI, the exact layout this module forbids.
        raise ValueError(
            f"data axis {data} not divisible by num_slices={dp}; choose "
            f"tp/sp/ep/pp so each slice holds an equal data shard")
    fsdp_size = data // dp
    if fsdp:
        spec = MeshSpec.of(dp=dp, pp=pipeline_parallel, fsdp=fsdp_size,
                           tp=model_parallel, sp=sequence_parallel,
                           ep=expert_parallel)
    else:
        spec = MeshSpec.of(dp=data, pp=pipeline_parallel,
                           tp=model_parallel, sp=sequence_parallel,
                           ep=expert_parallel)
    return make_mesh(spec)
