"""Ring attention: sequence-parallel exact attention for long context.

Sequence axis ``sp`` shards Q/K/V by sequence block.  Each step computes
blockwise (flash-style, log-sum-exp accumulated) attention of the local Q
block against the currently-held KV block, then rotates KV one hop around the
ring with ``ppermute`` -- on TPU the rotation rides neighbor ICI links and
overlaps with the block matmuls (XLA schedules the collective-permute
asynchronously).  After ``sp`` steps every Q block has seen every KV block;
results are exact (same math as full attention), memory is O(T/sp) per device.

Long-context/sequence parallelism is a first-class capability of this
framework (SURVEY.md §5.7: absent in the reference by design; required here).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, scale, mask):
    """One flash-attention accumulation step, GQA-aware.

    q: [B, Tq, Hq, D]; k/v: [B, Tk, Hkv, D] with Hq a multiple of Hkv (query
    head j attends kv head j // (Hq/Hkv), matching ``jnp.repeat`` ordering);
    m,l: [B, Hq, Tq]; o: [B, Tq, Hq, D]; mask: [Tq, Tk] bool or None.
    """
    import jax.numpy as jnp

    B, Tq, Hq, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    # Softmax statistics in float32 regardless of compute dtype (the flash-
    # attention convention): bf16 max/exp/sum loses enough precision over long
    # sequences to move the training loss.  THE same score function as the
    # custom backward (_ring_bwd) -- forward lse and backward probabilities
    # must come from identical math.
    s = _scores_gqa(q, k, scale)
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l_new = l * correction + p.sum(axis=-1)
    if Hq != Hkv:
        pg = p.reshape(B, Hkv, Hq // Hkv, Tq, Tk)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", pg, v,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(B, Tq, Hq, D)
    else:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                        preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _block_mask(my, kv_idx, T, causal: bool):
    """[Tq, Tk] causal mask between the local q block and kv block
    ``kv_idx`` (None when not causal)."""
    import jax.numpy as jnp

    if not causal:
        return None
    base = jnp.arange(T)
    q_pos = my * T + base[:, None]
    k_pos = kv_idx * T + base[None, :]
    return k_pos <= q_pos


def _scores_gqa(q, k, scale):
    """f32 scores [B, Hq, Tq, Tk] for q [B,Tq,Hq,D] vs k [B,Tk,Hkv,D]."""
    import jax.numpy as jnp

    B, Tq, Hq, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    if Hq != Hkv:
        g = Hq // Hkv
        qg = q.reshape(B, Tq, Hkv, g, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32)
        return s.reshape(B, Hq, Tq, Tk) * scale
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def _ring_forward(q, k, v, axis_name, causal, scale):
    """(out [B,T,H,D], lse [B,H,T] f32) -- the forward ring pass."""
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.parallel import collectives

    sp = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    B, T, H, D = q.shape

    # f32 accumulators (softmax stats + output) independent of compute dtype.
    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)

    def step(s, carry):
        m, l, o, k_cur, v_cur = carry
        kv_idx = (my - s) % sp
        mask = _block_mask(my, kv_idx, T, causal)
        m, l, o = _block_attn(q, k_cur, v_cur, m, l, o, scale, mask)
        # GQA: the ring rotates the narrow [.., Hkv, D] blocks -- ICI bytes
        # scale with kv heads, not query heads.
        k_nxt = collectives.ppermute_next(k_cur, axis_name, sp)
        v_nxt = collectives.ppermute_next(v_cur, axis_name, sp)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, sp, step, (m0, l0, o0, k, v))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return (o / denom).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention over a sequence-sharded axis.  Call inside shard_map.

    q, k, v: [B, T_local, H, D] -- the local sequence block.
    Returns [B, T_local, H, D].

    Differentiable via a CUSTOM ring backward: a second ring pass
    recomputes blockwise probabilities from the saved per-row log-sum-exp,
    with dK/dV riding the rotating KV blocks home -- residual memory is
    O(T/sp) (q, k, v, out, lse), never the per-step [B, H, Tl, Tl] score
    tensors plain autodiff-through-the-loop would save.  The (out, lse)
    residuals carry the ``attn_out`` remat anchors, so the "attn" policy
    (models/llama.py _remat_wrap) skips re-running the whole ring --
    including its sp ppermute rounds -- in the layer backward.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _ring_forward(q, k, v, axis_name, causal, scale)[0]


def _ring_fwd(q, k, v, axis_name, causal, scale):
    from jax.ad_checkpoint import checkpoint_name

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _ring_forward(q, k, v, axis_name, causal, scale)
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_out")
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, g):
    """Ring backward: dQ accumulates locally; dK/dV travel with their KV
    blocks through the full ring and arrive home after sp hops."""
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.parallel import collectives

    q, k, v, out, lse = res
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    sp = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    grp = Hq // Hkv

    gf = g.astype(jnp.float32)
    # delta = rowsum(dO * O) per query row, [B, Hq, T] (matches lse layout).
    delta = (gf * out.astype(jnp.float32)).sum(-1).transpose(0, 2, 1)
    # Loop invariants, hoisted: the head-grouped views of dO and Q.
    gg = gf.reshape(B, T, Hkv, grp, D)
    qg = q.astype(jnp.float32).reshape(B, T, Hkv, grp, D)

    def step(s, carry):
        dq, k_cur, v_cur, dk, dv = carry
        kv_idx = (my - s) % sp
        mask = _block_mask(my, kv_idx, T, causal)
        z = _scores_gqa(q, k_cur, scale)                 # [B,Hq,Tq,Tk] f32
        if mask is not None:
            # Mask BEFORE the exp (as in the forward): a masked raw score
            # above lse would overflow the exp before being zeroed.
            z = jnp.where(mask[None, None], z, NEG_INF)
        p = jnp.exp(z - lse[..., None])
        # dp = dO @ V^T, grouped form (exact for grp == 1 too).
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", gg, v_cur,
                        preferred_element_type=jnp.float32)
        dp = dp.reshape(B, Hq, T, -1)
        dz = p * (dp - delta[..., None]) * scale         # [B,Hq,Tq,Tk]
        dzg = dz.reshape(B, Hkv, grp, T, -1)
        pg = p.reshape(B, Hkv, grp, T, -1)
        dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", dzg,
                             k_cur.astype(jnp.float32)).reshape(B, T, Hq, D)
        dk = dk + jnp.einsum("bhgqk,bqhgd->bkhd", dzg, qg)
        dv = dv + jnp.einsum("bhgqk,bqhgd->bkhd", pg, gg)
        k_nxt = collectives.ppermute_next(k_cur, axis_name, sp)
        v_nxt = collectives.ppermute_next(v_cur, axis_name, sp)
        dk_nxt = collectives.ppermute_next(dk, axis_name, sp)
        dv_nxt = collectives.ppermute_next(dv, axis_name, sp)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    zero_q = jnp.zeros(q.shape, jnp.float32)
    zero_kv = jnp.zeros(k.shape, jnp.float32)
    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, sp, step, (zero_q, k, v, zero_kv, zero_kv))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = "sp",
                           causal: bool = True):
    """shard_map wrapper: q/k/v are global [B, T, H, D] arrays sharded on T."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map

        compat = {"check_vma": False}
    except ImportError:  # jax < 0.8
        from jax.experimental.shard_map import shard_map

        compat = {"check_rep": False}

    from trainingjob_operator_tpu.parallel import collectives

    # The ring must ride neighbor ICI links; a DCN-crossing sp axis would
    # serialize every hop over the slow inter-slice network.
    collectives.require_ici_axis(mesh, axis_name)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch = data_axes if len(data_axes) > 1 else (data_axes[0] if data_axes else None)
    # Heads ride tp when they tile it: attention is head-independent, so
    # the ring runs per tp shard on its own head block -- no tp all-gather
    # of q/k/v at the shard_map boundary, and the rotating KV blocks carry
    # 1/tp of the bytes.  (Contiguous head blocks keep the GQA query->kv
    # mapping local, as in flash_attention_sharded.)
    tp = "tp" if "tp" in mesh.axis_names else None
    if tp:
        ntp = mesh.shape[tp]
        if q.shape[2] % ntp or k.shape[2] % ntp:
            tp = None
    spec = P(batch, axis_name, tp, None)

    # Positional call: custom_vjp functions reject keyword arguments.
    fn = shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name, causal, None),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **compat)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None):
    """Plain full attention for correctness checks."""
    import jax.numpy as jnp

    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
