"""Ring attention: sequence-parallel exact attention for long context.

Sequence axis ``sp`` shards Q/K/V by sequence block.  Each step computes
blockwise (flash-style, log-sum-exp accumulated) attention of the local Q
block against the currently-held KV block, then rotates KV one hop around the
ring with ``ppermute`` -- on TPU the rotation rides neighbor ICI links and
overlaps with the block matmuls (XLA schedules the collective-permute
asynchronously).  After ``sp`` steps every Q block has seen every KV block;
results are exact (same math as full attention), memory is O(T/sp) per device.

Long-context/sequence parallelism is a first-class capability of this
framework (SURVEY.md §5.7: absent in the reference by design; required here).
"""

from __future__ import annotations

import functools
from typing import Optional

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, scale, mask):
    """One flash-attention accumulation step, GQA-aware.

    q: [B, Tq, Hq, D]; k/v: [B, Tk, Hkv, D] with Hq a multiple of Hkv (query
    head j attends kv head j // (Hq/Hkv), matching ``jnp.repeat`` ordering);
    m,l: [B, Hq, Tq]; o: [B, Tq, Hq, D]; mask: [Tq, Tk] bool or None.
    """
    import jax.numpy as jnp

    B, Tq, Hq, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    # Softmax statistics in float32 regardless of compute dtype (the flash-
    # attention convention): bf16 max/exp/sum loses enough precision over long
    # sequences to move the training loss.
    if Hq != Hkv:
        g = Hq // Hkv
        qg = q.reshape(B, Tq, Hkv, g, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                       preferred_element_type=jnp.float32)
        s = s.reshape(B, Hq, Tq, Tk) * scale
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l_new = l * correction + p.sum(axis=-1)
    if Hq != Hkv:
        pg = p.reshape(B, Hkv, Hq // Hkv, Tq, Tk)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", pg, v,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(B, Tq, Hq, D)
    else:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                        preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention over a sequence-sharded axis.  Call inside shard_map.

    q, k, v: [B, T_local, H, D] -- the local sequence block.
    Returns [B, T_local, H, D].
    """
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.parallel import collectives

    sp = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5

    # f32 accumulators (softmax stats + output) independent of compute dtype.
    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)

    base = jnp.arange(T)

    def step(s, carry):
        m, l, o, k_cur, v_cur = carry
        kv_idx = (my - s) % sp
        if causal:
            # Block-level: attend iff kv block is at or before ours; diagonal
            # block applies the in-block causal mask.
            q_pos = my * T + base[:, None]
            k_pos = kv_idx * T + base[None, :]
            mask = k_pos <= q_pos
        else:
            mask = None
        m, l, o = _block_attn(q, k_cur, v_cur, m, l, o, scale, mask)
        # GQA: the ring rotates the narrow [.., Hkv, D] blocks -- ICI bytes
        # scale with kv heads, not query heads.
        k_nxt = collectives.ppermute_next(k_cur, axis_name, sp)
        v_nxt = collectives.ppermute_next(v_cur, axis_name, sp)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, sp, step, (m0, l0, o0, k, v))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = "sp",
                           causal: bool = True):
    """shard_map wrapper: q/k/v are global [B, T, H, D] arrays sharded on T."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map

        compat = {"check_vma": False}
    except ImportError:  # jax < 0.8
        from jax.experimental.shard_map import shard_map

        compat = {"check_rep": False}

    from trainingjob_operator_tpu.parallel import collectives

    # The ring must ride neighbor ICI links; a DCN-crossing sp axis would
    # serialize every hop over the slow inter-slice network.
    collectives.require_ici_axis(mesh, axis_name)

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch = data_axes if len(data_axes) > 1 else (data_axes[0] if data_axes else None)
    spec = P(batch, axis_name, None, None)

    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **compat)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None):
    """Plain full attention for correctness checks."""
    import jax.numpy as jnp

    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
