"""Parameter/batch sharding rules: path-pattern -> PartitionSpec.

The scaling-book recipe: annotate a few load-bearing shardings (params in,
batch in, outputs) and let XLA propagate + insert collectives.  Rules map
regex patterns over flattened param paths (``"layers/3/attn/wq"``) to
``PartitionSpec``s; first match wins, default replicated.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

Rules = Sequence[Tuple[str, Tuple[Optional[object], ...]]]


def path_of(key_path) -> str:
    """jax.tree_util key path -> 'a/b/3/c' string."""
    import jax

    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for_path(path: str, rules: Rules):
    """First-match PartitionSpec for a param path; replicated by default."""
    from jax.sharding import PartitionSpec as P

    for pattern, spec in rules:
        if re.search(pattern, path):
            return P(*spec)
    return P()


def fit_spec(spec, shape, mesh):
    """Adapt a rule's PartitionSpec to a concrete leaf: align to the TRAILING
    dims when the spec is longer than the rank (stacked-layer rules carry a
    leading scan-axis entry that unstacked leaves don't have), and drop
    sharding on axes the dimension cannot divide (replicate there) -- keeps
    one rule set valid across model sizes."""
    import math

    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    if len(entries) > len(shape):
        entries = entries[len(entries) - len(shape):]
    fitted: List[Optional[object]] = []
    for i, entry in enumerate(entries):
        if entry is None:
            fitted.append(None)
            continue
        # Axes a rule names but this mesh lacks are dropped (replicated
        # there): one rule set stays valid across mesh layouts (e.g. a
        # dp x sp mesh has no fsdp/tp axis).
        names = tuple(n for n in
                      (entry if isinstance(entry, tuple) else (entry,))
                      if n in mesh.axis_names)
        if not names:
            fitted.append(None)
            continue
        # Collapse singleton tuples to the bare axis name: dropping absent
        # axes can shrink ("dp", "fsdp") to ("dp",), and PartitionSpec does
        # not treat ("dp",) and "dp" as equal on every jax version.
        entry = names if len(names) > 1 else names[0]
        size = math.prod(mesh.shape[n] for n in names)
        fitted.append(entry if size and shape[i] % size == 0 else None)
    return P(*fitted)


def shard_pytree(tree: Any, rules: Rules, mesh) -> Any:
    """Device-put every leaf with its rule's NamedSharding."""
    import jax
    from jax.sharding import NamedSharding

    def place(key_path, leaf):
        spec = spec_for_path(path_of(key_path), rules)
        return jax.device_put(
            leaf, NamedSharding(mesh, fit_spec(spec, leaf.shape, mesh)))

    return jax.tree_util.tree_map_with_path(place, tree)


def sharding_pytree(tree: Any, rules: Rules, mesh) -> Any:
    """The NamedSharding pytree for jit in_shardings/out_shardings."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: NamedSharding(
            mesh, fit_spec(spec_for_path(path_of(kp), rules),
                           getattr(leaf, "shape", ()), mesh)),
        tree)


def batch_spec(mesh, sequence_axis: bool = False):
    """Batch PartitionSpec: batch dim over (dp, fsdp), optionally sequence dim
    over sp."""
    from jax.sharding import PartitionSpec as P

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    batch_axes = data_axes if len(data_axes) > 1 else (data_axes[0] if data_axes else None)
    if sequence_axis and "sp" in mesh.axis_names:
        return P(batch_axes, "sp")
    return P(batch_axes)


def constrain(x, mesh, *spec):
    """with_sharding_constraint under a concrete mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def precast_weights(layers: Any, rules: Rules, mesh, compute,
                    pattern: str, prefix: str = "layers/") -> Any:
    """Cast matmul weights to the compute dtype with explicit sharding
    anchors (leaves whose path matches ``pattern``; others untouched).

    XLA hoists per-layer ``astype`` casts out of the layer scan anyway, but
    the hoisted stacked bf16 tensor then carries no user sharding, and on
    many-axis meshes the SPMD partitioner can choose CLASHING shardings for
    its forward and backward-scan uses -- an "Involuntary full
    rematerialization" (replicate-then-repartition every step).  Doing the
    cast up front under ``with_sharding_constraint`` anchors it; the
    in-body casts become no-ops.
    """
    import jax
    from jax.sharding import NamedSharding

    def cast(kp, x):
        path = prefix + path_of(kp)
        if not re.search(pattern, path):
            return x
        y = x.astype(compute)
        return jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, fit_spec(
                spec_for_path(path, rules), y.shape, mesh)))

    return jax.tree_util.tree_map_with_path(cast, layers)


def pin_batch_act(y, mesh, *, sequence_parallel: bool = False):
    """Pin a [B, T, ...] activation to the canonical batch sharding.

    Also constrains the COTANGENT in the backward (the constraint is its
    own transpose), which keeps custom-vjp backward passes (rmsnorm, flash
    attention) sharding-consistent: without it the incoming grad can
    arrive tp-sharded on the model dim against batch-sharded saved stats
    and the partitioner resolves the clash with an involuntary full
    rematerialization.
    """
    import jax
    from jax.sharding import NamedSharding

    # Same canonical layout as the input batches (trailing dims replicate).
    spec = batch_spec(mesh, sequence_axis=sequence_parallel)
    return jax.lax.with_sharding_constraint(y, NamedSharding(mesh, spec))
