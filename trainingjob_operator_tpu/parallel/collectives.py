"""Named-axis collective helpers used inside shard_map'd code.

XLA compiles these onto ICI (intra-slice) or DCN (across the dp axis when it
spans slices); there is no NCCL-style backend to manage (SURVEY.md §5.8) --
topology correctness is the operator's job, collective choice is ours.
"""

from __future__ import annotations

from typing import Any


def pmean(x: Any, axis: str):
    import jax

    return jax.lax.pmean(x, axis)


def psum(x: Any, axis: str):
    import jax

    return jax.lax.psum(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True):
    import jax

    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dimension: int = 0):
    import jax

    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute_next(x: Any, axis: str, axis_size: int):
    """Rotate a block one step around the ring (i -> i+1)."""
    import jax

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return jax.lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    import jax

    return jax.lax.axis_index(axis)
